"""KV-cached autoregressive decoding for the flagship transformer.

Parity target: the serving half of the reference's model families
(reference: the generation utilities its RLlib/serve examples lean on;
the training side lives in models/transformer.py). TPU-first design:
the KV cache is a preallocated [L, B, max_len, H, Dh] pytree so every
decode step is ONE jitted program of static shapes — `prefill` runs
the prompt through the full-sequence layers (flash/XLA attention)
while writing the cache, and `decode_step` attends the new token
against the cache with a position mask (no recompute, no dynamic
shapes). `generate` wraps both in a `lax.scan`, so an N-token
generation is exactly two compiled programs.

Oracle: greedy generate() must match per-step argmax of the FULL
forward() on the growing prefix — tests/test_ops.py asserts this
exactly, which pins the cache bookkeeping (rope offsets, masking,
update slices) to the training forward's semantics.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.transformer import TransformerConfig
from ray_tpu.ops.attention import flash_attention
from ray_tpu.ops.norms import rmsnorm
from ray_tpu.ops.rotary import apply_rotary, rope_frequencies


def init_kv_cache(cfg: TransformerConfig, batch: int,
                  max_len: int) -> Dict:
    shape = (cfg.n_layers, batch, max_len, cfg.n_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype),
            "pos": jnp.zeros((), jnp.int32)}


def _qkv(lp, h, Dh):
    B, T = h.shape[:2]
    q = (h @ lp["wq"]).reshape(B, T, -1, Dh)
    k = (h @ lp["wk"]).reshape(B, T, -1, Dh)
    v = (h @ lp["wv"]).reshape(B, T, -1, Dh)
    return q, k, v


def _mlp(lp, x):
    h = rmsnorm(x, lp["mlp_norm"])
    g = jax.nn.silu((h @ lp["w_gate"]).astype(jnp.float32))
    u = (h @ lp["w_up"]).astype(jnp.float32)
    return x + ((g * u).astype(x.dtype) @ lp["w_down"]).astype(x.dtype)


@functools.partial(jax.jit, static_argnames=("cfg",))
def prefill(params, tokens, cache: Dict,
            cfg: TransformerConfig) -> Tuple[jnp.ndarray, Dict]:
    """Run the prompt [B, T0] through the stack, writing each layer's
    K/V into the cache. Returns (last-token logits [B, V], cache)."""
    B, T0 = tokens.shape
    max_len = cache["k"].shape[2]
    cos, sin = rope_frequencies(cfg.head_dim, max_len,
                                theta=cfg.rope_theta)
    positions = jnp.arange(T0)
    x = params["embed"][tokens]

    def body(x, layer_in):
        lp, ck, cv = layer_in
        h = rmsnorm(x, lp["attn_norm"])
        q, k, v = _qkv(lp, h, cfg.head_dim)
        q = apply_rotary(q, cos, sin, positions=positions)
        k = apply_rotary(k, cos, sin, positions=positions)
        # same kernel as the training forward's local path (Pallas on
        # TPU, XLA fallback off-TPU) so prefill logits match forward()
        # bit for bit and long prompts keep the blocked-VMEM property
        o = flash_attention(q, k, v, causal=True).reshape(B, T0, -1)
        x = x + (o @ lp["wo"]).astype(x.dtype)
        x = _mlp(lp, x)
        ck = lax.dynamic_update_slice(ck, k.astype(ck.dtype),
                                      (0, 0, 0, 0))
        cv = lax.dynamic_update_slice(cv, v.astype(cv.dtype),
                                      (0, 0, 0, 0))
        return x, (ck, cv)

    x, (ck, cv) = lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"]))
    x = rmsnorm(x, params["final_norm"])
    # bf16 matmul then f32, bit-matching the training forward's
    # unembed so greedy decode agrees with full-forward argmax exactly
    logits = (x[:, -1] @ params["embed"].T.astype(x.dtype)
              ).astype(jnp.float32)
    return logits, {"k": ck, "v": cv,
                    "pos": jnp.asarray(T0, jnp.int32)}


def decode_step(params, cache: Dict, token,
                cfg: TransformerConfig) -> Tuple[jnp.ndarray, Dict]:
    """One token [B] in, next-token logits [B, V] out; cache advances.
    Eager-call entry with a capacity check — dynamic_update_slice
    CLAMPS out-of-range writes, so stepping past max_len would
    silently overwrite the last slot instead of failing."""
    if int(cache["pos"]) >= cache["k"].shape[2]:
        raise ValueError(
            f"KV cache full (pos {int(cache['pos'])} of "
            f"{cache['k'].shape[2]}); allocate a larger max_len")
    return _decode_step_jit(params, cache, token, cfg)


@functools.partial(jax.jit, static_argnames=("cfg",))
def _decode_step_jit(params, cache: Dict, token,
                     cfg: TransformerConfig) -> Tuple[jnp.ndarray, Dict]:
    """Jitted body: a single fused device program per step (attention
    against the full static-shape cache with a position mask)."""
    B = token.shape[0]
    max_len = cache["k"].shape[2]
    pos = cache["pos"]
    cos, sin = rope_frequencies(cfg.head_dim, max_len,
                                theta=cfg.rope_theta)
    positions = pos[None]  # [1]
    x = params["embed"][token][:, None, :]  # [B, 1, D]
    sm_scale = cfg.head_dim ** -0.5
    valid = (jnp.arange(max_len) <= pos)[None, None, :]  # [1,1,Tmax]

    def body(x, layer_in):
        lp, ck, cv = layer_in
        h = rmsnorm(x, lp["attn_norm"])
        q, k, v = _qkv(lp, h, cfg.head_dim)
        q = apply_rotary(q, cos, sin, positions=positions)
        k = apply_rotary(k, cos, sin, positions=positions)
        ck = lax.dynamic_update_slice(ck, k.astype(ck.dtype),
                                      (0, pos, 0, 0))
        cv = lax.dynamic_update_slice(cv, v.astype(cv.dtype),
                                      (0, pos, 0, 0))
        s = jnp.einsum("bhd,bkhd->bhk", q[:, 0], ck,
                       preferred_element_type=jnp.float32) * sm_scale
        s = jnp.where(valid, s, -jnp.inf)
        # accumulation dtypes bit-match ops.attention (softmax fp32,
        # p cast to the value dtype, p@v accumulated in fp32)
        p = jax.nn.softmax(s, axis=-1).astype(cv.dtype)
        o = jnp.einsum("bhk,bkhd->bhd", p, cv,
                       preferred_element_type=jnp.float32
                       ).astype(q.dtype)
        x = x + (o.reshape(B, 1, -1) @ lp["wo"]).astype(x.dtype)
        x = _mlp(lp, x)
        return x, (ck, cv)

    x, (ck, cv) = lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"]))
    x = rmsnorm(x[:, 0], params["final_norm"])
    logits = (x @ params["embed"].T.astype(x.dtype)
              ).astype(jnp.float32)
    return logits, {"k": ck, "v": cv, "pos": pos + 1}


# ------------------------------------------------------------- slot cache
# Continuous batching (serve/decode_scheduler.py) needs per-SLOT decode
# offsets: one sequence prefills into an open batch row while the other
# rows keep stepping, and a finished row frees immediately. The whole-
# batch cache above carries a single scalar ``pos``; these variants
# carry ``pos: [slots]`` and mask per row. Invariants the scheduler
# relies on:
#
# * ``slot_prefill`` rewrites rows [0, T0) of its slot and resets that
#   slot's pos, so a reused slot never sees its predecessor's K/V — the
#   stale tail beyond T0 is always overwritten (step s writes position
#   pos BEFORE attending it) and never attended.
# * ``slot_decode_step`` writes every row's K/V unconditionally (a
#   masked write would cost a gather per layer) but advances ``pos``
#   only where ``active``: an inactive row's cache may take garbage at
#   its frozen pos, which is sound because inactive rows are only ever
#   re-entered through ``slot_prefill``.


def init_slot_cache(cfg: TransformerConfig, slots: int,
                    max_len: int) -> Dict:
    """KV cache with an independent decode offset per batch row."""
    shape = (cfg.n_layers, slots, max_len, cfg.n_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype),
            "pos": jnp.zeros((slots,), jnp.int32)}


@functools.partial(jax.jit, static_argnames=("cfg",))
def slot_prefill(params, tokens, cache: Dict, slot,
                 cfg: TransformerConfig) -> Tuple[jnp.ndarray, Dict]:
    """Run one prompt [1, T0] through the stack, writing each layer's
    K/V into cache row ``slot`` (a traced index: one compiled program
    serves every slot). Returns (last-token logits [1, V], cache).
    Compiles once per distinct T0 — serving callers should bucket or
    pad prompt lengths if retrace cost matters."""
    _, T0 = tokens.shape
    max_len = cache["k"].shape[2]
    cos, sin = rope_frequencies(cfg.head_dim, max_len,
                                theta=cfg.rope_theta)
    positions = jnp.arange(T0)
    x = params["embed"][tokens]

    def body(x, layer_in):
        lp, ck, cv = layer_in  # ck/cv: [slots, max_len, H, Dh]
        h = rmsnorm(x, lp["attn_norm"])
        q, k, v = _qkv(lp, h, cfg.head_dim)
        q = apply_rotary(q, cos, sin, positions=positions)
        k = apply_rotary(k, cos, sin, positions=positions)
        o = flash_attention(q, k, v, causal=True).reshape(1, T0, -1)
        x = x + (o @ lp["wo"]).astype(x.dtype)
        x = _mlp(lp, x)
        ck = lax.dynamic_update_slice(ck, k.astype(ck.dtype),
                                      (slot, 0, 0, 0))
        cv = lax.dynamic_update_slice(cv, v.astype(cv.dtype),
                                      (slot, 0, 0, 0))
        return x, (ck, cv)

    x, (ck, cv) = lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"]))
    x = rmsnorm(x, params["final_norm"])
    logits = (x[:, -1] @ params["embed"].T.astype(x.dtype)
              ).astype(jnp.float32)
    return logits, {"k": ck, "v": cv,
                    "pos": cache["pos"].at[slot].set(T0)}


def _rotary_rows(x, cos, sin, pos):
    """apply_rotary for per-ROW positions: x [B, 1, H, D], pos [B].
    (ops.rotary broadcasts one [T] position vector over the batch; a
    continuous batch has every row at a different offset.)"""
    c = cos[pos][:, None, None, :]
    s = sin[pos][:, None, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)


@functools.partial(jax.jit, static_argnames=("cfg",))
def slot_decode_step(params, cache: Dict, token, active,
                     cfg: TransformerConfig) -> Tuple[jnp.ndarray, Dict]:
    """One continuous-batching step: token [B] in, next-token logits
    [B, V] out; each ACTIVE row attends its own prefix (per-row
    position mask) and advances its own pos. Inactive rows are free
    riders — their logits are garbage and their pos is frozen."""
    B = token.shape[0]
    max_len = cache["k"].shape[2]
    pos = cache["pos"]  # [B]
    cos, sin = rope_frequencies(cfg.head_dim, max_len,
                                theta=cfg.rope_theta)
    x = params["embed"][token][:, None, :]  # [B, 1, D]
    sm_scale = cfg.head_dim ** -0.5
    # row r attends positions [0, pos[r]] (pos[r] is written this step)
    valid = (jnp.arange(max_len)[None, None, :]
             <= pos[:, None, None])  # [B, 1, Tmax]
    rows = jnp.arange(B)

    def body(x, layer_in):
        lp, ck, cv = layer_in
        h = rmsnorm(x, lp["attn_norm"])
        q, k, v = _qkv(lp, h, cfg.head_dim)
        q = _rotary_rows(q, cos, sin, pos)
        k = _rotary_rows(k, cos, sin, pos)
        ck = ck.at[rows, pos].set(k[:, 0].astype(ck.dtype))
        cv = cv.at[rows, pos].set(v[:, 0].astype(cv.dtype))
        s = jnp.einsum("bhd,bkhd->bhk", q[:, 0], ck,
                       preferred_element_type=jnp.float32) * sm_scale
        s = jnp.where(valid, s, -jnp.inf)
        # accumulation dtypes bit-match _decode_step_jit so a batch of
        # one slot reproduces the whole-batch decode exactly
        p = jax.nn.softmax(s, axis=-1).astype(cv.dtype)
        o = jnp.einsum("bhk,bkhd->bhd", p, cv,
                       preferred_element_type=jnp.float32
                       ).astype(q.dtype)
        x = x + (o.reshape(B, 1, -1) @ lp["wo"]).astype(x.dtype)
        x = _mlp(lp, x)
        return x, (ck, cv)

    x, (ck, cv) = lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"]))
    x = rmsnorm(x[:, 0], params["final_norm"])
    logits = (x @ params["embed"].T.astype(x.dtype)
              ).astype(jnp.float32)
    new_pos = jnp.where(active, pos + 1, pos)
    return logits, {"k": ck, "v": cv, "pos": new_pos}


@functools.partial(jax.jit,
                   static_argnames=("cfg", "steps", "sample"))
def _decode_loop(params, logits, cache, key, temperature, *, cfg,
                 steps, sample):
    """Module-level jit: the scanned decode loop compiles ONCE per
    (cfg, steps, sample, shapes) across generate() calls — a per-call
    closure would retrace every invocation, and a static temperature
    would recompile per distinct float, so only the greedy/sampling
    BRANCH is static and the magnitude is a traced operand."""
    def pick(logits, k):
        if not sample:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            k, logits / temperature).astype(jnp.int32)

    def body(carry, i):
        logits, cache, key = carry
        key, sub = jax.random.split(key)
        tok = pick(logits, sub)
        # the token sampled on the LAST iteration needs no successor
        # logits: skip its decode_step (at steps=1 this halves the
        # per-generation device work)
        logits, cache = lax.cond(
            i < steps - 1,
            lambda: _decode_step_jit(params, cache, tok, cfg),
            lambda: (logits, cache))
        return (logits, cache, key), tok

    (_, cache, _), toks = lax.scan(
        body, (logits, cache, key), jnp.arange(steps))
    return toks.swapaxes(0, 1)  # [B, steps]


def generate(params, prompt, cfg: TransformerConfig, *, steps: int,
             key: Optional[jax.Array] = None, temperature: float = 0.0,
             max_len: Optional[int] = None) -> jnp.ndarray:
    """Autoregressive sampling: greedy at temperature 0, categorical
    otherwise (an explicit ``key`` is required then — a silent fixed
    seed would make every call return the same completion). Returns
    generated tokens [B, steps]. Two compiled programs total, cached
    across calls: prefill + the scanned decode loop."""
    B, T0 = prompt.shape
    max_len = max_len or min(cfg.max_seq, T0 + steps)
    if T0 + steps > max_len:
        raise ValueError(f"prompt ({T0}) + steps ({steps}) exceeds "
                         f"max_len ({max_len})")
    if temperature > 0.0 and key is None:
        raise ValueError("temperature > 0 requires an explicit key")
    cache = init_kv_cache(cfg, B, max_len)
    logits, cache = prefill(params, prompt, cache, cfg)
    if key is None:
        key = jax.random.key(0)  # unused by the greedy path
    return _decode_loop(params, logits, cache, key,
                        jnp.asarray(max(temperature, 1e-8),
                                    jnp.float32),
                        cfg=cfg, steps=steps,
                        sample=temperature > 0.0)
