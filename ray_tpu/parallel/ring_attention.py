"""Ring attention over a mesh axis.

Sequence/context parallelism is absent from the reference snapshot
(SURVEY.md §5.7) — this is the TPU-native capability that replaces it:
K/V shards rotate around the ``sp`` axis ring via ``lax.ppermute``
(nearest-neighbor ICI hops) while each device keeps a blockwise
online-softmax accumulator over its local Q shard, so attention over a
sequence of length ``n_sp * T_local`` never materializes on one chip.

Call inside ``shard_map`` (ray_tpu.parallel.collectives' version-
portable accessor) with q/k/v sharded on dim 1 (seq) over
``axis``. Shapes: [batch, seq_local, heads, head_dim].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.parallel.collectives import axis_size


def _block_attn(q, k, v, q_pos, kv_pos, causal, sm_scale):
    # q: [B,Tq,H,D] k,v: [B,Tk,H,D] → scores [B,H,Tq,Tk]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        mask = q_pos[:, None] >= kv_pos[None, :]      # [Tq,Tk]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    m = jnp.max(s, axis=-1)                            # [B,H,Tq]
    # Fully-masked rows (no visible keys yet in the ring) → avoid -inf.
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = jnp.sum(p, axis=-1)                            # [B,H,Tq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o, m_safe, l


def ring_attention(q, k, v, *, axis: str = "sp", causal: bool = True,
                   sm_scale: float | None = None):
    """Blockwise ring attention. Returns [B, T_local, H, D] in q.dtype."""
    n = axis_size(axis)
    my = lax.axis_index(axis)
    B, T, H, D = q.shape
    sm_scale = sm_scale if sm_scale is not None else D ** -0.5
    q_pos = my * T + jnp.arange(T)

    q32 = q.astype(jnp.float32)

    def step(carry, i):
        o_acc, m_acc, l_acc, k_cur, v_cur = carry
        src = (my - i) % n                       # whose K/V block we hold
        kv_pos = src * T + jnp.arange(T)
        o, m, l = _block_attn(q32, k_cur, v_cur, q_pos, kv_pos,
                              causal, sm_scale)
        # online softmax merge
        m_new = jnp.maximum(m_acc, m)
        alpha = jnp.exp(m_acc - m_new)
        beta = jnp.exp(m - m_new)
        l_new = l_acc * alpha + l * beta
        o_new = (o_acc * alpha.transpose(0, 2, 1)[..., None]
                 + o * beta.transpose(0, 2, 1)[..., None])
        # rotate K/V to the next rank (skip after the final block; the
        # ppermute still runs — the scan carries it — but is cheap and
        # keeps the loop body static for XLA)
        perm = [(r, (r + 1) % n) for r in range(n)]
        k_nxt = lax.ppermute(k_cur, axis_name=axis, perm=perm)
        v_nxt = lax.ppermute(v_cur, axis_name=axis, perm=perm)
        return (o_new, m_new, l_new, k_nxt, v_nxt), None

    o0 = jnp.zeros((B, T, H, D), jnp.float32)
    m0 = jnp.full((B, H, T), -1e30)  # finite "-inf" sentinel
    l0 = jnp.zeros((B, H, T))
    (o, m, l, _, _), _ = lax.scan(
        step, (o0, m0, l0, k.astype(jnp.float32), v.astype(jnp.float32)),
        jnp.arange(n))
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)
