"""GSPMD sharding rules for transformer training.

The scaling recipe: pick a mesh, annotate param/activation shardings
with ``PartitionSpec``, let XLA insert the collectives. This module maps
*logical* tensor axis names to mesh axes — the seam where tp/dp/sp/pp
layout policy lives.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# logical axis name → mesh axis (None = replicated).
# Weights are sharded over tp (MXU dim) and optionally fsdp-style over dp.
DEFAULT_RULES = {
    "batch": "dp",
    "seq": "sp",          # sequence parallelism for activations
    "kv_seq": None,
    "embed": None,        # residual stream replicated across tp
    "mlp": "tp",          # ffn hidden sharded over tp
    "heads": "tp",        # attention heads sharded over tp
    "head_dim": None,
    "vocab": "tp",
    "layers": None,       # stacked-layer leading dim (pp shards it)
    "stages": "pp",
    "expert": "tp",       # experts ride the tp axis by default
}


def transformer_rules(**overrides) -> dict:
    rules = dict(DEFAULT_RULES)
    rules.update(overrides)
    return rules


def logical_to_mesh(logical_axes: Sequence[Optional[str]],
                    rules: Optional[dict] = None) -> P:
    """('batch','seq','embed') → PartitionSpec('dp','sp',None)."""
    rules = rules or DEFAULT_RULES
    return P(*[rules.get(a) if a else None for a in logical_axes])


def with_sharding(mesh: Mesh, x, logical_axes: Sequence[Optional[str]],
                  rules: Optional[dict] = None):
    """Constrain ``x`` to the sharding implied by its logical axes."""
    spec = logical_to_mesh(logical_axes, rules)
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, spec))


def named_sharding(mesh: Mesh, logical_axes: Sequence[Optional[str]],
                   rules: Optional[dict] = None) -> NamedSharding:
    return NamedSharding(mesh, logical_to_mesh(logical_axes, rules))
