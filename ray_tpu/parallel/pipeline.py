"""SPMD pipeline parallelism (GPipe schedule over a mesh axis).

Pipeline parallelism is absent from the reference (SURVEY.md §2.4).
TPU-native design: each ``pp`` rank holds one stage's params (the
stacked-stage leading dim sharded over ``pp``); microbatch activations
hop between neighbor ranks with ``lax.ppermute`` inside a ``lax.scan``
— a static-shape loop XLA compiles once, with the bubble cost
``(n_stages - 1) / n_microbatches``. Differentiable: jax.grad through
the scan yields the reverse (backward) schedule automatically.

Call inside ``shard_map`` (the version-portable accessor in
ray_tpu.parallel.collectives) over the ``pp`` axis.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.parallel.collectives import axis_size


# AD note (verified empirically, jax 0.9 shard_map check_vma=False):
# the transpose of lax.psum SUMS cotangents across ranks, so per-rank
# grads equal ∂(Σ_ranks loss_r)/∂(local params). The final
# psum-broadcast below hands every pp rank an identical copy of the
# output; if every rank then computes the same loss, stage-param grads
# come out n_pp-fold inflated. Callers must divide their per-rank loss
# (or the resulting grads) by the pp axis size — the model train step
# does this uniformly (models/transformer.py make_train_step).


def pipeline_spmd(stage_fn, stage_params, x, *, axis: str = "pp",
                  num_microbatches: int | None = None):
    """Run ``stage_fn(stage_params, mb)`` as a pipeline.

    x: [B, ...] full (pp-replicated) batch; returns [B, ...] outputs,
    valid on every rank (last stage's results are psum-broadcast).
    num_microbatches defaults to the pipeline depth.
    """
    n = axis_size(axis)
    rank = lax.axis_index(axis)
    B = x.shape[0]
    M = num_microbatches or n
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    mb = B // M
    xs = x.reshape((M, mb) + x.shape[1:])
    steps = M + n - 1
    perm = [(r, (r + 1) % n) for r in range(n)]

    def body(carry, t):
        recv, out_buf = carry
        # stage 0 reads microbatch t (clamped; masked out when t >= M)
        feed = lax.dynamic_index_in_dim(
            xs, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
        inp = jnp.where(rank == 0, feed.astype(recv.dtype), recv)
        out = stage_fn(stage_params, inp)
        # last rank finished microbatch t-(n-1) at this step
        mb_idx = jnp.clip(t - (n - 1), 0, M - 1)
        valid = jnp.logical_and(rank == n - 1, t >= n - 1)
        cur = lax.dynamic_index_in_dim(out_buf, mb_idx, 0, keepdims=False)
        upd = jnp.where(valid, out, cur)
        out_buf = lax.dynamic_update_index_in_dim(out_buf, upd, mb_idx, 0)
        recv_next = lax.ppermute(out, axis_name=axis, perm=perm)
        return (recv_next, out_buf), None

    probe = jax.eval_shape(stage_fn, stage_params,
                           jax.ShapeDtypeStruct((mb,) + x.shape[1:],
                                                x.dtype))
    recv0 = jnp.zeros(probe.shape, probe.dtype)
    buf0 = jnp.zeros((M,) + probe.shape, probe.dtype)
    (_, out_buf), _ = lax.scan(body, (recv0, buf0), jnp.arange(steps))
    # broadcast last rank's results to all pp ranks
    out_buf = lax.psum(
        jnp.where(rank == n - 1, out_buf, jnp.zeros_like(out_buf)),
        axis_name=axis)
    return out_buf.reshape((B,) + out_buf.shape[2:])
