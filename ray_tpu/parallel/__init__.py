"""TPU-native parallelism layer.

The reference (mwtian/ray) has *no* tensor/pipeline/sequence parallelism
(SURVEY.md §2.4, §5.7) — DP exists as a library (``ray.util.sgd``) over
NCCL (``ray.util.collective``). Here the equivalent capability is built
TPU-first: a named ``jax.sharding.Mesh`` over the ICI torus, GSPMD
sharding rules, and XLA collectives, with ring attention and Ulysses
all-to-all as first-class sequence-parallel schedules.

Axes (by convention, any subset may be size 1):
  dp — data parallel (batch)
  pp — pipeline parallel (layer stages)
  sp — sequence/context parallel (ring attention / Ulysses)
  tp — tensor parallel (MXU-dim sharding; also used for experts)
"""

from ray_tpu.parallel.mesh import (  # noqa: F401
    AXES,
    MeshConfig,
    build_mesh,
    default_mesh_shape,
)
from ray_tpu.parallel.collectives import (  # noqa: F401
    all_gather,
    all_to_all,
    axis_index,
    axis_size,
    pmean,
    ppermute_ring,
    psum,
    psum_scatter,
    shard_map,
)
from ray_tpu.parallel.sharding import (  # noqa: F401
    logical_to_mesh,
    transformer_rules,
    with_sharding,
)
from ray_tpu.parallel.ring_attention import ring_attention  # noqa: F401
from ray_tpu.parallel.ulysses import ulysses_attention  # noqa: F401
from ray_tpu.parallel.pipeline import pipeline_spmd  # noqa: F401
from ray_tpu.parallel.moe import moe_dispatch_combine  # noqa: F401
