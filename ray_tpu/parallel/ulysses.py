"""Ulysses-style sequence parallelism: head↔sequence all-to-all.

Alternative SP schedule to ring attention (SURVEY.md §5.7): instead of
rotating K/V, re-shard — an all-to-all over the ``sp`` axis converts
seq-sharded/head-full activations into seq-full/head-sharded ones, runs
ordinary (full-sequence) attention on the local heads, then converts
back. Two all-to-alls per attention; wins when heads ≥ sp and the
sequence fits per-device once head-sharded.

Call inside ``shard_map`` (ray_tpu.parallel.collectives' version-
portable accessor); q/k/v: [B, T_local, H, D], H % sp == 0.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def _seq_to_heads(x, axis):
    # [B, T/n, H, D] → [B, T, H/n, D]
    return lax.all_to_all(x, axis_name=axis, split_axis=2, concat_axis=1,
                          tiled=True)


def _heads_to_seq(x, axis):
    # [B, T, H/n, D] → [B, T/n, H, D]
    return lax.all_to_all(x, axis_name=axis, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(q, k, v, *, axis: str = "sp", causal: bool = True,
                      sm_scale: float | None = None,
                      attn_fn=None):
    """Returns [B, T_local, H, D]. ``attn_fn(q,k,v,causal,sm_scale)``
    runs full attention on head-sharded tensors (defaults to a fused
    softmax-attention; swap in a Pallas flash kernel on TPU)."""
    D = q.shape[-1]
    sm_scale = sm_scale if sm_scale is not None else D ** -0.5
    qh = _seq_to_heads(q, axis)
    kh = _seq_to_heads(k, axis)
    vh = _seq_to_heads(v, axis)
    if attn_fn is None:
        from ray_tpu.ops.attention import attention as attn_fn  # lazy
    oh = attn_fn(qh, kh, vh, causal=causal, sm_scale=sm_scale)
    return _heads_to_seq(oh, axis)
