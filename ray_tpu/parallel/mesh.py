"""Device mesh construction.

TPU-native replacement for the reference's NCCL/Gloo group bootstrap
(reference: python/ray/util/collective/collective.py:39 GroupManager,
collective_group/nccl_collective_group.py): instead of rendezvous'ing
communicators, we build a named ``jax.sharding.Mesh`` over the devices
and let XLA compile collectives onto ICI.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis order: outermost (slowest, DCN-friendly) → innermost
# (fastest, wants contiguous ICI neighbors). tp innermost so MXU-dim
# collectives ride nearest-neighbor ICI links.
AXES = ("dp", "pp", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Sizes for each named axis; -1 on at most one axis means 'rest'."""

    dp: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1

    def sizes(self) -> tuple:
        return (self.dp, self.pp, self.sp, self.tp)

    def resolve(self, n_devices: int) -> "MeshConfig":
        sizes = list(self.sizes())
        if -1 in sizes:
            i = sizes.index(-1)
            known = math.prod(s for s in sizes if s != -1)
            if n_devices % known:
                raise ValueError(
                    f"{n_devices} devices not divisible by {known}")
            sizes[i] = n_devices // known
        if math.prod(sizes) != n_devices:
            raise ValueError(
                f"mesh {dict(zip(AXES, sizes))} != {n_devices} devices")
        return MeshConfig(*sizes)


def default_mesh_shape(n_devices: int) -> MeshConfig:
    """Factorize n_devices over (dp, pp, sp, tp), giving every axis ≥2
    when possible (powers of two first), so all four parallelism kinds
    are exercised on any mesh of ≥16 devices (≥3 kinds on 8)."""
    sizes = [1, 1, 1, 1]
    rest = n_devices
    # Deal factors of two round-robin across axes, tp first (innermost
    # gets the fastest links), then dp (batch scales best), then sp, pp.
    order = [3, 0, 2, 1]
    i = 0
    while rest % 2 == 0 and rest > 1:
        sizes[order[i % 4]] *= 2
        rest //= 2
        i += 1
    sizes[0] *= rest  # odd remainder onto dp
    return MeshConfig(*sizes)


def build_mesh(config: Optional[MeshConfig] = None,
               devices: Optional[Sequence] = None) -> Mesh:
    """Build a 4-axis named Mesh; singleton axes are kept so sharding
    rules can always name all of dp/pp/sp/tp."""
    devices = list(devices if devices is not None else jax.devices())
    config = (config or default_mesh_shape(len(devices))).resolve(
        len(devices))
    arr = np.array(devices).reshape(config.sizes())
    return Mesh(arr, AXES)
