"""Expert parallelism: top-1 MoE dispatch/combine via all-to-all.

EP does not exist in the reference (SURVEY.md §2.4). TPU-native design
(Mesh-TensorFlow-style einsum routing): experts are sharded over a mesh
axis; tokens are routed with a capacity-bounded one-hot dispatch tensor
and exchanged with a single tiled ``lax.all_to_all`` each way, which XLA
lowers to ICI all-to-all. Static shapes throughout (dropped tokens pass
through on the residual path, standard Switch-Transformer behavior).

Call inside ``shard_map`` (ray_tpu.parallel.collectives' version-
portable accessor); x: [T_local, D]; experts sharded so each
rank owns E_local = E / axis_size experts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.parallel.collectives import axis_size


def moe_dispatch_combine(x, gate_logits, expert_fn, expert_params, *,
                         axis: str = "tp", capacity_factor: float = 1.25):
    """Returns [T_local, D] combined expert outputs (0 for dropped).

    gate_logits: [T_local, E] (E = global expert count).
    expert_fn(params, xs): params for E_local experts with leading dim
    E_local; xs [E_local, cap_total, D] → [E_local, cap_total, D].
    """
    n = axis_size(axis)
    T, D = x.shape
    E = gate_logits.shape[-1]
    if E % n:
        raise ValueError(f"{E} experts not divisible by axis size {n}")
    cap = max(1, int(capacity_factor * T / E))

    gates = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(gates, axis=-1)                  # [T]
    gate_val = jnp.max(gates, axis=-1)                       # [T]
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [T,E]
    # position of each token within its expert's buffer
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0          # [T,E]
    keep = (pos < cap) & (onehot > 0)
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), cap,
                            dtype=jnp.float32) * keep[..., None]
    dispatch = pos_oh                                        # [T,E,cap]
    combine = dispatch * gate_val[:, None, None]             # [T,E,cap]

    xe = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
    # [E,cap,D] → exchange so each rank holds its E_local experts'
    # buffers from every rank: after the all-to-all the leading dim
    # indexes the SOURCE rank, so transpose to [E_local, n, cap, D]
    # before flattening the per-expert token dim.
    xe = xe.reshape(n, E // n, cap, D)
    xe = lax.all_to_all(xe, axis_name=axis, split_axis=0, concat_axis=0,
                        tiled=False)
    xe = xe.transpose(1, 0, 2, 3).reshape(E // n, n * cap, D)
    ye = expert_fn(expert_params, xe.astype(x.dtype))        # [E_l,n*cap,D]
    ye = (ye.astype(jnp.float32)
          .reshape(E // n, n, cap, D).transpose(1, 0, 2, 3))
    ye = lax.all_to_all(ye, axis_name=axis, split_axis=0, concat_axis=0,
                        tiled=False)
    ye = ye.reshape(E, cap, D)
    out = jnp.einsum("tec,ecd->td", combine, ye)
    return out.astype(x.dtype)


def load_balance_loss(gate_logits, axis: str | None = None):
    """Switch-Transformer auxiliary loss: E * Σ_e f_e · p_e."""
    E = gate_logits.shape[-1]
    gates = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    frac = jnp.mean(
        jax.nn.one_hot(jnp.argmax(gates, -1), E, dtype=jnp.float32),
        axis=tuple(range(gates.ndim - 1)))
    prob = jnp.mean(gates, axis=tuple(range(gates.ndim - 1)))
    return E * jnp.sum(frac * prob)
