"""Named-axis collective wrappers.

TPU-native equivalent of ``ray.util.collective``'s op surface
(reference: python/ray/util/collective/collective.py — allreduce :244,
allgather :409, reducescatter :457, broadcast :358, send/recv :514+),
expressed as XLA collectives over mesh axis names so they compile onto
ICI instead of going through NCCL communicators. Used inside
``shard_map``/``pjit`` bodies (see :func:`shard_map` below for the
version-portable accessor).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


# ------------------------------------------------------------- shard_map
#
# jax moved shard_map across versions: old releases ship it only as
# ``jax.experimental.shard_map.shard_map`` with a ``check_rep=`` kwarg;
# newer ones promote it to ``jax.shard_map`` and rename the kwarg to
# ``check_vma=``. Everything in this repo (parallel schedules, the SPMD
# train step, the differential tests) routes through this accessor so
# the pinned jax can move in either direction without touching call
# sites.

@functools.lru_cache(maxsize=1)
def _shard_map_impl():
    """(callable, accepted_kwarg_names) for the hosting jax."""
    import inspect

    impl = getattr(jax, "shard_map", None)
    if impl is None:
        from jax.experimental.shard_map import shard_map as impl
    try:
        params = frozenset(inspect.signature(impl).parameters)
    except (TypeError, ValueError):
        params = frozenset()
    return impl, params


def shard_map(f, *, mesh, in_specs, out_specs, **kwargs):
    """Version-portable ``jax.shard_map``.

    Accepts either spelling of the replication-check kwarg
    (``check_vma=`` / ``check_rep=``) and translates to whatever the
    hosting jax understands; every other kwarg passes through.
    """
    impl, params = _shard_map_impl()
    for ours, theirs in (("check_vma", "check_rep"),
                         ("check_rep", "check_vma")):
        if ours in kwargs and ours not in params and theirs in params:
            kwargs[theirs] = kwargs.pop(ours)
    return impl(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                **kwargs)


def psum(x, axis: str):
    """All-reduce-sum over a mesh axis (ray.util.collective.allreduce)."""
    return lax.psum(x, axis_name=axis)


def pmean(x, axis: str):
    return lax.pmean(x, axis_name=axis)


def all_gather(x, axis: str, *, tiled: bool = True, gather_dim: int = 0):
    """Gather shards along a mesh axis (collective.allgather)."""
    return lax.all_gather(x, axis_name=axis, axis=gather_dim, tiled=tiled)


def psum_scatter(x, axis: str, *, scatter_dim: int = 0, tiled: bool = True):
    """Reduce-scatter (collective.reducescatter)."""
    return lax.psum_scatter(x, axis_name=axis,
                            scatter_dimension=scatter_dim, tiled=tiled)


def all_to_all(x, axis: str, *, split_dim: int, concat_dim: int,
               tiled: bool = True):
    """All-to-all over a mesh axis — the Ulysses/MoE primitive."""
    return lax.all_to_all(x, axis_name=axis, split_axis=split_dim,
                          concat_axis=concat_dim, tiled=tiled)


def ppermute_ring(x, axis: str, *, shift: int = 1):
    """Rotate shards around the axis ring (ring attention's hop)."""
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name=axis, perm=perm)


def axis_index(axis: str):
    return lax.axis_index(axis)


def axis_size(axis: str) -> int:
    """Static (Python int) size of a mesh axis, version-portably:
    ``lax.axis_size`` where it exists; on older jax the axis frame —
    which some releases hand back as the bare int itself. Every
    schedule needing the size for Python-level control flow (pipeline
    step counts, ring permutations) goes through here."""
    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis)
    from jax import core
    frame = core.axis_frame(axis)
    return getattr(frame, "size", frame)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_copy(x, axis: str):
    """Identity forward, psum backward (Megatron's "f" operator).

    Place on a tp-replicated activation right before column-parallel
    (output-sharded) matmuls: each tp rank backpropagates only its
    shard's contribution to the activation cotangent, so the cotangents
    must be summed over tp to stay consistent with the replicated
    forward value.
    """
    return x


def _tp_copy_fwd(x, axis):
    return x, None


def _tp_copy_bwd(axis, _, g):
    return (lax.psum(g, axis_name=axis),)


tp_copy.defvjp(_tp_copy_fwd, _tp_copy_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_allreduce(x, axis: str):
    """psum forward, identity backward (Megatron's "g" operator).

    Place on a row-parallel matmul's partial output. ``lax.psum``'s own
    transpose SUMS cotangents across ranks, which is right only when
    every rank's cotangent is a distinct contribution; here the
    downstream compute is replicated on ``axis`` (every rank holds the
    same loss copy and produces the same cotangent), so the true
    cotangent of each rank's partial is that single copy — identity.
    Requires: the output must be consumed by tp-replicated computation.
    """
    return lax.psum(x, axis_name=axis)


def _tp_allreduce_fwd(x, axis):
    return lax.psum(x, axis_name=axis), None


def _tp_allreduce_bwd(axis, _, g):
    return (g,)


tp_allreduce.defvjp(_tp_allreduce_fwd, _tp_allreduce_bwd)


def broadcast_from(x, axis: str, root: int = 0):
    """Broadcast the root shard's value to all ranks on the axis
    (collective.broadcast): select root's contribution, all-reduce."""
    idx = lax.axis_index(axis)
    contrib = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(contrib, axis_name=axis)
