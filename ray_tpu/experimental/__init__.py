"""Experimental utilities (reference: python/ray/experimental/ —
internal_kv, dynamic_resources, the shuffle scaling harness)."""

from ray_tpu.experimental.dynamic_resources import set_resource  # noqa: F401
from ray_tpu.experimental.shuffle import shuffle  # noqa: F401
from ray_tpu.worker import (  # noqa: F401
    experimental_internal_kv_del as internal_kv_del,
    experimental_internal_kv_get as internal_kv_get,
    experimental_internal_kv_list as internal_kv_list,
    experimental_internal_kv_put as internal_kv_put,
)
