"""Distributed shuffle harness: all-to-all over the object plane.

Parity target: the reference's shuffle scaling harness
(reference: python/ray/experimental/shuffle.py:135 — map tasks emit
per-reducer partitions into the object store, reduce tasks gather
their partition from every mapper; used to validate 1TB+ shuffles).
Scaled to this runtime: block sizes and partition counts are
arguments, the harness reports rows/s and bytes moved, and the
correctness check (every row lands exactly once) runs by default.
"""

from __future__ import annotations

import time
from typing import Dict, List

import numpy as np

import ray_tpu


def _map_block(block_id: int, rows_per_block: int,
               num_reducers: int, row_bytes: int) -> List:
    """One mapper: produce this block's rows, partition by
    hash(row) % reducers, return per-reducer arrays (small enough to
    inline or large enough to ride plasma — the runtime decides)."""
    rng = np.random.default_rng(block_id)
    keys = rng.integers(0, 2**63 - 1, size=rows_per_block,
                        dtype=np.int64)
    pad = max(1, row_bytes // 8)
    parts = []
    for r in range(num_reducers):
        sel = keys[keys % num_reducers == r]
        # row payload: key replicated to the requested row width
        parts.append(np.repeat(sel[:, None], pad, axis=1))
    return parts


def _reduce_partition(*mapper_parts) -> Dict[str, float]:
    """One reducer: gather its partition from every mapper."""
    total_rows = 0
    total_bytes = 0
    checksum = np.int64(0)
    for arr in mapper_parts:
        total_rows += arr.shape[0]
        total_bytes += arr.nbytes
        if arr.size:
            checksum ^= np.bitwise_xor.reduce(arr[:, 0])
    return {"rows": float(total_rows), "bytes": float(total_bytes),
            "checksum": float(checksum % (2**31))}


def shuffle(num_mappers: int = 4, num_reducers: int = 4,
            rows_per_block: int = 100_000, row_bytes: int = 8,
            verify: bool = True) -> Dict[str, float]:
    """Run one all-to-all shuffle round; returns throughput stats.

    Data volume = mappers * rows_per_block * row_bytes. Each mapper's
    output is ``num_returns=num_reducers`` objects, so a reducer pulls
    exactly one object per mapper — the reference's partition-object
    topology (shuffle.py ObjectStoreWriter/Reader roles).
    """
    mapper = ray_tpu.remote(_map_block).options(
        num_returns=num_reducers)
    reducer = ray_tpu.remote(_reduce_partition)

    t0 = time.perf_counter()
    part_refs = []  # [mapper][reducer]
    for b in range(num_mappers):
        refs = mapper.remote(b, rows_per_block, num_reducers, row_bytes)
        part_refs.append(refs if isinstance(refs, list) else [refs])
    reduce_refs = [
        reducer.remote(*[part_refs[m][r] for m in range(num_mappers)])
        for r in range(num_reducers)]
    results = ray_tpu.get(reduce_refs)
    wall = time.perf_counter() - t0

    rows = sum(r["rows"] for r in results)
    nbytes = sum(r["bytes"] for r in results)
    out = {
        "num_mappers": num_mappers,
        "num_reducers": num_reducers,
        "rows": rows,
        "bytes": nbytes,
        "wall_s": round(wall, 3),
        "rows_per_s": round(rows / wall, 1),
        "mb_per_s": round(nbytes / wall / 1e6, 2),
    }
    if verify:
        expected = float(num_mappers * rows_per_block)
        if rows != expected:
            raise AssertionError(
                f"shuffle lost rows: {rows} != {expected}")
    return out


def main() -> None:  # pragma: no cover — manual harness entry
    import json

    ray_tpu.init()
    try:
        print(json.dumps(shuffle()))
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":  # pragma: no cover
    main()
