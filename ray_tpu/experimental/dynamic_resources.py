"""Dynamic custom resources: change a node's capacity at runtime.

Parity target: the reference's dynamic resources
(reference: python/ray/experimental/dynamic_resources.py
set_resource — adjust a custom resource's capacity on a live node so
schedulable work changes without restarting raylets).

``set_resource(name, capacity)`` targets the local node by default, or
any node by id. Capacity 0 deletes the resource. The raylet adjusts
both total and available (available moves by the same delta so leases
already granted keep their accounting), then re-runs its scheduler
tick — queued tasks waiting on the new resource dispatch immediately.
"""

from __future__ import annotations

from typing import Optional

import ray_tpu


def set_resource(resource_name: str, capacity: float,
                 node_id: Optional[bytes] = None) -> bool:
    """Set ``resource_name`` to ``capacity`` on a node (default: the
    node this driver/worker is attached to). Returns True on success."""
    if resource_name in ("CPU",):
        raise ValueError("CPU capacity is fixed at node start "
                         "(reference: set_resource rejects CPU/GPU)")
    if capacity < 0:
        raise ValueError("capacity must be >= 0")
    w = ray_tpu.worker._require_connected()
    core = w.core

    async def _go():
        address = None
        if node_id is None or node_id == core.node_id:
            address = core.raylet_address
        else:
            reply, _ = await core._gcs_call("GetAllNodeInfo", {})
            for n in reply["nodes"]:
                if n["node_id"] == node_id and n["alive"]:
                    address = n["address"]
                    break
        if address is None:
            raise ValueError(f"no alive node {node_id!r}")
        from ray_tpu._private import rpc

        if address == core.raylet_address:
            conn = core.raylet_conn
            reply, _ = await conn.call("SetResource", {
                "name": resource_name, "capacity": float(capacity)})
        else:
            conn = await rpc.connect(address, peer_name="set-resource")
            try:
                reply, _ = await conn.call("SetResource", {
                    "name": resource_name, "capacity": float(capacity)})
            finally:
                await conn.close()
        return bool(reply.get("ok"))

    return core._run(_go())
