"""Distributed training driver (the reference's sgd/v2 equivalent).

API parity: ``Trainer`` (reference: python/ray/util/sgd/v2/trainer.py)
runs a user ``train_func(config)`` on N worker actors;
``report(**metrics)`` streams intermediate results to the driver
(reference: sgd/v2/session.py); checkpoints save/load through the
driver-visible filesystem.

Backends (see ``backends.py``): the default ``host`` backend syncs
host arrays through a ``ray_tpu.util.collective`` group; ``torch``
wires a real ``torch.distributed`` gloo process group across the
worker actors (reference: util/sgd/torch/distributed_torch_runner.py);
``jax`` exports the ``jax.distributed`` coordinator env per worker.
Single-process multi-device DP should instead use
``ray_tpu.parallel`` shardings directly.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.worker_group import WorkerGroup
from ray_tpu.util.queue import Empty, Queue

# Per-worker-process training session context (set inside workers).
_session: Optional[dict] = None


def _init_session(state, rank: int, world: int, group_name: str,
                  results_queue, ckpt_dir: Optional[str]) -> None:
    global _session
    from ray_tpu.util import collective

    if world > 1:
        collective.init_collective_group(world, rank,
                                         group_name=group_name)
    _session = {"rank": rank, "world": world, "queue": results_queue,
                "ckpt_dir": ckpt_dir, "group": group_name}
    state["session"] = _session


def _leave_group(state) -> None:
    """Worker-side: drop this rank's collective membership (trainer
    shutdown calls this before killing the actor)."""
    if _session and _session["world"] > 1:
        from ray_tpu.util import collective

        collective.destroy_collective_group(_session["group"])


def _run_train_func(state, fn, config):
    out = fn(config) if config is not None else fn()
    q = _session["queue"] if _session else None
    if q is not None:
        q.put({"type": "done", "rank": _session["rank"], "result": out})
    return out


def world_rank() -> int:
    return _session["rank"] if _session else 0


def world_size() -> int:
    return _session["world"] if _session else 1


def local_rank() -> int:
    return world_rank()  # single-host-per-worker model


def collective_group_name() -> str:
    """Name of this training run's collective group (for
    ``ray_tpu.util.collective`` ops inside ``train_func``)."""
    return _session["group"] if _session else "default"


def report(**metrics) -> None:
    """Stream intermediate metrics to the Trainer's result iterator."""
    if _session and _session["queue"] is not None:
        _session["queue"].put({"type": "report",
                               "rank": _session["rank"],
                               "metrics": metrics})


def save_checkpoint(**checkpoint) -> None:
    if not _session or not _session["ckpt_dir"]:
        return
    path = os.path.join(_session["ckpt_dir"],
                        f"checkpoint_rank{_session['rank']}.pkl")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(checkpoint, f)
    os.replace(tmp, path)


def load_checkpoint() -> Optional[Dict[str, Any]]:
    if not _session or not _session["ckpt_dir"]:
        return None
    path = os.path.join(_session["ckpt_dir"],
                        f"checkpoint_rank{_session['rank']}.pkl")
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        return pickle.load(f)


class TrainingCallback:
    """Driver-side hook for streamed results."""

    def handle_result(self, results: List[Dict], **info) -> None:
        pass

    def start_training(self, **info) -> None:
        pass

    def finish_training(self, error: bool = False, **info) -> None:
        pass


class Trainer:
    _group_counter = 0

    def __init__(self, backend: str = "host", num_workers: int = 1,
                 use_tpu: bool = False, resources_per_worker=None,
                 checkpoint_dir: Optional[str] = None):
        self._backend = backend
        self._num_workers = num_workers
        self._use_tpu = use_tpu
        self._resources = resources_per_worker
        self._ckpt_dir = checkpoint_dir
        self._wg: Optional[WorkerGroup] = None

    def start(self) -> None:
        if self._wg is not None:
            return
        self._wg = WorkerGroup(
            num_workers=self._num_workers,
            num_tpus_per_worker=1 if self._use_tpu else 0,
            resources_per_worker=self._resources)
        Trainer._group_counter += 1
        # unique across driver processes: two drivers on one cluster
        # must not share a coordinator (uuid, not just a counter)
        import uuid

        group_name = (f"rtpu_train_{Trainer._group_counter}_"
                      f"{uuid.uuid4().hex[:8]}")
        self._group_name = group_name
        self._queue = Queue()
        if self._ckpt_dir:
            os.makedirs(self._ckpt_dir, exist_ok=True)
        futs = [
            self._wg.workers[r].execute_with_state.remote(
                _init_session, r, self._num_workers, group_name,
                self._queue, self._ckpt_dir)
            for r in range(self._num_workers)]
        ray_tpu.get(futs)
        # framework wiring (torch process group / jax distributed env)
        from ray_tpu.train.backends import make_train_backend

        self._backend_impl = make_train_backend(self._backend)
        self._backend_impl.on_start(self._wg, self._num_workers)

    def run(self, train_func: Callable, config: Optional[dict] = None,
            callbacks: Optional[List[TrainingCallback]] = None
            ) -> List[Any]:
        """Run to completion; returns each worker's return value.
        Streamed ``report()`` metrics go to callbacks as they arrive."""
        self.start()
        callbacks = callbacks or []
        for cb in callbacks:
            cb.start_training(num_workers=self._num_workers)
        futs = [w.execute_with_state.remote(_run_train_func, train_func,
                                            config)
                for w in self._wg.workers]
        done = 0
        pending_reports: Dict[int, List[dict]] = {}
        # The crash-detection gets inside the poll loop raise too — the
        # whole run is under one try so callbacks always learn of failure.
        try:
            while done < self._num_workers:
                try:
                    msg = self._queue.get(timeout=0.1)
                except Empty:
                    # surface worker crashes instead of spinning forever: a
                    # single failed future must abort the run (survivors may
                    # be blocked in a collective waiting for the dead rank)
                    ready, _ = ray_tpu.wait(futs, num_returns=len(futs),
                                            timeout=0)
                    for fut in ready:
                        ray_tpu.get(fut)  # raises if that worker crashed
                    if len(ready) == len(futs):
                        break
                    continue
                if msg["type"] == "done":
                    done += 1
                elif msg["type"] == "report":
                    rank = msg["rank"]
                    pending_reports.setdefault(rank, []).append(
                        msg["metrics"])
                    if all(len(v) > 0 for v in pending_reports.values()) \
                            and len(pending_reports) == self._num_workers:
                        batch = [pending_reports[r].pop(0)
                                 for r in sorted(pending_reports)]
                        pending_reports = {
                            r: v for r, v in pending_reports.items() if v}
                        for cb in callbacks:
                            cb.handle_result(batch)
            results = ray_tpu.get(futs)
            for cb in callbacks:
                cb.finish_training(error=False)
            return results
        except Exception:
            for cb in callbacks:
                cb.finish_training(error=True)
            raise

    def run_iterator(self, train_func: Callable,
                     config: Optional[dict] = None):
        """Run to completion, then replay the per-rank ``report()``
        batches in order; StopIteration's value is the final results
        list. (Post-hoc replay, not live streaming — use a callback
        with ``run()`` for live results.)"""
        results: List[dict] = []

        class _Collect(TrainingCallback):
            def handle_result(self, batch, **info):
                results.append(batch)

        final = self.run(train_func, config, callbacks=[_Collect()])
        yield from results
        return final

    @property
    def latest_checkpoint_dir(self) -> Optional[str]:
        return self._ckpt_dir

    def shutdown(self) -> None:
        if self._wg is not None:
            from ray_tpu.util.collective import destroy_collective_group

            if getattr(self, "_backend_impl", None) is not None:
                self._backend_impl.on_shutdown(self._wg)

            # Each rank leaves the group BEFORE its actor dies — the
            # coordinator's membership refcount must reach zero or the
            # detached coordinator outlives the trainer and a later
            # same-named group attaches to the stale world size.
            try:
                ray_tpu.get([
                    w.execute_with_state.remote(_leave_group)
                    for w in self._wg.workers], timeout=10)
            except Exception:  # noqa: BLE001 — dead workers can't leave
                pass
            self._wg.shutdown()
            self._wg = None
            # force: every rank is gone; a rank that crashed before
            # leaving must not leak the detached coordinator
            destroy_collective_group(self._group_name, force=True)
