"""Group of training worker actors.

API parity with the reference's ``ray.util.sgd.v2.WorkerGroup``
(reference: python/ray/util/sgd/v2/worker_group.py): N actors, execute
a function on all (or one) of them, sync or async.
"""

from __future__ import annotations

from typing import Any, Callable, List

import ray_tpu


class _ExecutableWorker:
    """Generic executor actor; also carries a per-worker state dict so
    train backends can stash context (rank, collective group, etc.)."""

    def __init__(self):
        self.state: dict = {}

    def execute(self, fn: Callable, *args, **kwargs):
        return fn(*args, **kwargs)

    def execute_with_state(self, fn: Callable, *args, **kwargs):
        return fn(self.state, *args, **kwargs)


class WorkerGroup:
    def __init__(self, num_workers: int = 1, num_cpus_per_worker: float = 1,
                 num_tpus_per_worker: float = 0,
                 resources_per_worker: dict | None = None):
        if num_workers <= 0:
            raise ValueError("num_workers must be positive")
        cls = ray_tpu.remote(_ExecutableWorker).options(
            num_cpus=num_cpus_per_worker,
            num_tpus=num_tpus_per_worker or None,
            resources=resources_per_worker)
        self.workers = [cls.remote() for _ in range(num_workers)]

    def __len__(self) -> int:
        return len(self.workers)

    def execute_async(self, fn: Callable, *args, **kwargs) -> List:
        return [w.execute.remote(fn, *args, **kwargs)
                for w in self.workers]

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return ray_tpu.get(self.execute_async(fn, *args, **kwargs))

    def execute_single_async(self, rank: int, fn: Callable, *args,
                             **kwargs):
        return self.workers[rank].execute.remote(fn, *args, **kwargs)

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs):
        return ray_tpu.get(
            self.execute_single_async(rank, fn, *args, **kwargs))

    def shutdown(self) -> None:
        for w in self.workers:
            ray_tpu.kill(w)
        self.workers = []
