from ray_tpu.train.trainer import (  # noqa: F401
    Trainer,
    TrainingCallback,
    collective_group_name,
    load_checkpoint,
    local_rank,
    report,
    save_checkpoint,
    world_rank,
    world_size,
)
from ray_tpu.train.worker_group import WorkerGroup  # noqa: F401
