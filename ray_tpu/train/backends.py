"""Training backends: per-framework worker-group setup.

Parity target: the reference's sgd v2 backend abstraction
(reference: python/ray/util/sgd/v2/backends/{backend.py,torch.py,
tensorflow.py} — BackendConfig + on_start/on_shutdown hooks that wire
each framework's process group over the worker actors).

* ``HostBackend`` — no extra wiring; the object-store collective group
  from trainer start() is the communication fabric.
* ``TorchBackend`` — initializes ``torch.distributed`` (gloo) across
  the worker actors: rank 0's host opens a TCP store, every worker
  joins; user train functions can use dist.all_reduce etc.
* ``JaxBackend`` — exports the multi-process JAX env
  (coordinator/process count/process id) on every worker so a train
  function may call ``jax.distributed.initialize()``; on real
  multi-host TPU slices those processes ride ICI via XLA collectives.
"""

from __future__ import annotations

from typing import Optional

import ray_tpu


class Backend:
    def on_start(self, worker_group, num_workers: int) -> None:
        pass

    def on_shutdown(self, worker_group) -> None:
        pass


class HostBackend(Backend):
    pass


def _rank0_rendezvous(state):
    """Runs ON rank 0's worker: its node's IP + a free port there —
    the rendezvous must live where rank 0 lives, not on the driver
    (workers may be on other nodes). Free-port probing is inherently
    racy; init_process_group retries/fails loudly if the port is
    stolen between probe and bind."""
    import socket as sock

    with sock.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]
    # The UDP-connect trick picks the interface that routes outward —
    # gethostbyname(gethostname()) returns 127.0.1.1 on hosts with the
    # common Debian-style /etc/hosts entry, which would point every
    # remote rank at its own loopback.
    try:
        with sock.socket(sock.AF_INET, sock.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))  # no packet is sent
            ip = s.getsockname()[0]
    except OSError:
        # no default route (air-gapped cluster): hostname resolution
        # still beats loopback when /etc/hosts maps a real address
        try:
            ip = sock.gethostbyname(sock.gethostname())
        except OSError:
            ip = "127.0.0.1"
    return ip, port


def _torch_init(state, rank, world_size, addr, port):
    import datetime
    import os

    import torch.distributed as dist

    os.environ["MASTER_ADDR"] = addr
    os.environ["MASTER_PORT"] = str(port)
    # Explicit store: rendezvous failures (stolen port, wrong address)
    # surface within 60s, while the GROUP timeout — which governs every
    # later collective — stays at torch's generous default order (a
    # slow step with >60s between all_reduces must not abort training).
    # 5 min rendezvous: enough for worker-start skew under load (cold
    # torch import + actor scheduling), still 6x faster to surface a
    # bad address than the 30-min collective timeout.
    store = dist.TCPStore(addr, port, world_size,
                          is_master=(rank == 0),
                          timeout=datetime.timedelta(minutes=5))
    dist.init_process_group(
        backend="gloo", store=store, rank=rank,
        world_size=world_size,
        timeout=datetime.timedelta(minutes=30))
    state["torch_distributed"] = True
    return rank


def _torch_shutdown(state):
    import torch.distributed as dist

    if state.pop("torch_distributed", None) and dist.is_initialized():
        dist.destroy_process_group()


class TorchBackend(Backend):
    def __init__(self, master_addr: Optional[str] = None,
                 master_port: Optional[int] = None):
        self.master_addr = master_addr
        self.master_port = master_port

    def on_start(self, worker_group, num_workers: int) -> None:
        addr, port = self.master_addr, self.master_port
        if addr is None or port is None:
            r_addr, r_port = ray_tpu.get(
                worker_group.workers[0].execute_with_state.remote(
                    _rank0_rendezvous))
            addr, port = addr or r_addr, port or r_port
        ray_tpu.get([
            w.execute_with_state.remote(
                _torch_init, rank, num_workers, addr, port)
            for rank, w in enumerate(worker_group.workers)])

    def on_shutdown(self, worker_group) -> None:
        try:
            ray_tpu.get([w.execute_with_state.remote(_torch_shutdown)
                         for w in worker_group.workers])
        except Exception:  # noqa: BLE001 — workers may already be dead
            pass


def _jax_env_init(state, rank, world_size, coordinator):
    import os

    os.environ["JAX_COORDINATOR_ADDRESS"] = coordinator
    os.environ["JAX_NUM_PROCESSES"] = str(world_size)
    os.environ["JAX_PROCESS_ID"] = str(rank)
    state["jax_distributed_env"] = True
    return rank


class JaxBackend(Backend):
    """Exports the jax.distributed env; the train function decides
    when (and whether) to call ``jax.distributed.initialize()`` —
    initializing eagerly would pin the backend choice before user code
    can configure platforms."""

    def __init__(self, coordinator_address: Optional[str] = None):
        self.coordinator_address = coordinator_address

    def on_start(self, worker_group, num_workers: int) -> None:
        coordinator = self.coordinator_address
        if coordinator is None:
            ip, port = ray_tpu.get(
                worker_group.workers[0].execute_with_state.remote(
                    _rank0_rendezvous))
            coordinator = f"{ip}:{port}"
        ray_tpu.get([
            w.execute_with_state.remote(_jax_env_init, rank,
                                        num_workers, coordinator)
            for rank, w in enumerate(worker_group.workers)])


_BACKENDS = {"host": HostBackend, "torch": TorchBackend,
             "jax": JaxBackend}


def make_train_backend(backend) -> Backend:
    if isinstance(backend, Backend):
        return backend
    try:
        return _BACKENDS[backend]()
    except KeyError:
        raise ValueError(
            f"unknown train backend {backend!r}; "
            f"one of {sorted(_BACKENDS)}") from None
