"""Columnar blocks: struct-of-numpy-arrays with rows only at the edge.

Parity target: the reference's Arrow block layer
(reference: python/ray/data/impl/arrow_block.py:57 ArrowBlockAccessor —
blocks are columnar tables with exact byte sizes and vectorized
sort/shuffle/groupby). Here the columnar format is a dict of numpy
arrays (TPU-idiomatic: ``to_jax``/``iter_batches`` hand columns to
``jnp.asarray`` with zero conversion, and every reorganization op is a
fancy-index/``argsort``/``searchsorted`` instead of a Python row loop).
Arbitrary row types (nested dicts, mixed shapes) fall back to plain
list blocks; every block helper in dataset.py accepts both.

The SCALAR sentinel column holds datasets of bare values
(``data.range``, ``from_numpy``) — one array, rows are its elements.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

SCALAR = "__value__"

_SCALAR_TYPES = (int, float, bool, str, np.generic)


class ColumnBlock:
    """One block as named numpy columns (all the same length)."""

    __slots__ = ("cols",)

    def __init__(self, cols: Dict[str, np.ndarray]):
        self.cols = cols

    # -- pickling (slots classes need explicit state) --------------------
    def __getstate__(self):
        return self.cols

    def __setstate__(self, cols):
        self.cols = cols

    # -- shape -----------------------------------------------------------
    @property
    def scalar(self) -> bool:
        return SCALAR in self.cols

    def __len__(self) -> int:
        for v in self.cols.values():
            return len(v)
        return 0

    def size_bytes(self) -> int:
        """EXACT in-memory bytes of the numpy representation (object
        columns add the per-element payload the pointer array hides)."""
        total = 0
        for arr in self.cols.values():
            total += arr.nbytes
            if arr.dtype == object:
                total += sum(sys.getsizeof(o) for o in arr.flat)
        return total

    def schema(self):
        if self.scalar:
            return _dtype_name(self.cols[SCALAR])
        return {k: _dtype_name(v) for k, v in self.cols.items()}

    # -- vectorized ops ---------------------------------------------------
    def key_values(self, key: Optional[str]) -> np.ndarray:
        """The sort/partition/group key column. ``None`` means the
        scalar column (sorting bare values, like ``sorted(rows)``)."""
        if key is None:
            if not self.scalar:
                raise KeyError(
                    "column datasets need a named sort/group key")
            return self.cols[SCALAR]
        return self.cols[key]

    def take(self, indices: np.ndarray) -> "ColumnBlock":
        return ColumnBlock({k: v[indices] for k, v in self.cols.items()})

    def slice(self, start: int, stop: int) -> "ColumnBlock":
        return ColumnBlock({k: v[start:stop]
                            for k, v in self.cols.items()})

    # -- the row edge -----------------------------------------------------
    def to_rows(self) -> List[Any]:
        if self.scalar:
            return self.cols[SCALAR].tolist()
        names = list(self.cols)
        listed = [self.cols[k].tolist() for k in names]
        return [dict(zip(names, vals)) for vals in zip(*listed)]


def _dtype_name(arr: np.ndarray) -> str:
    kind = arr.dtype.kind
    if kind in "iu":
        return "int"
    if kind == "f":
        return "float"
    if kind == "b":
        return "bool"
    if kind in "US":
        return "str"
    for o in arr.flat:  # object column: name the first element's type
        return type(o).__name__
    return "object"


def _column(values: list) -> Optional[np.ndarray]:
    """values -> 1-D numpy column, or None when the values don't form
    one (ragged arrays, nested rows)."""
    try:
        arr = np.asarray(values)
    except (ValueError, TypeError, OverflowError):
        return None
    if arr.ndim != 1:
        return None  # per-row ndarrays etc. stay in list blocks
    if arr.dtype == object:
        return None  # mixed / nested values: not a real column
    if arr.dtype.kind == "S":
        return None  # numpy 'S' strips trailing NULs: unsafe for bytes
    if arr.dtype.kind == "U" and \
            not all(isinstance(v, str) for v in values):
        return None  # numpy coerced mixed values to strings: corrupting
    if arr.dtype.kind == "f" and \
            not all(isinstance(v, (float, np.floating)) for v in values):
        return None  # int->float promotion would rewrite values
        # (e.g. 2**60+1 rounds); mixed numerics stay row blocks
    if arr.dtype.kind in "iu" and any(isinstance(v, bool)
                                      for v in values):
        return None  # [True, 2] -> int64 would turn True into 1
    return arr


def from_rows(rows: list) -> Union["ColumnBlock", list]:
    """Columnize when the rows are uniform flat dicts or bare scalars;
    otherwise return the list unchanged (legacy row block)."""
    if isinstance(rows, ColumnBlock):
        return rows
    if not isinstance(rows, list) or not rows:
        return rows
    first = rows[0]
    if isinstance(first, dict):
        names = list(first)
        if not names:
            return rows  # empty dicts have no columns to carry length
        if any(not isinstance(r, dict) or list(r) != names
               for r in rows):
            return rows
        cols = {}
        for k in names:
            col = _column([r[k] for r in rows])
            if col is None:
                return rows
            cols[k] = col
        return ColumnBlock(cols)
    if isinstance(first, _SCALAR_TYPES):
        col = _column(rows)
        if col is None:
            return rows
        return ColumnBlock({SCALAR: col})
    return rows


def rows_of(block) -> list:
    """Rows view of any block (the API edge)."""
    if isinstance(block, ColumnBlock):
        return block.to_rows()
    return block


def num_rows(block) -> int:
    return len(block)


def split_by_partition(block: "ColumnBlock", part: np.ndarray,
                       n: int) -> List["ColumnBlock"]:
    """Group a block's rows by partition id (one stable sort +
    bincount + slices) — shared by range-partition and shuffle-split."""
    grouped = block.take(np.argsort(part, kind="stable"))
    counts = np.bincount(part, minlength=n)
    parts, start = [], 0
    for c in counts[:n]:
        parts.append(grouped.slice(start, start + int(c)))
        start += int(c)
    return parts


def concat(blocks: Sequence) -> Union["ColumnBlock", list]:
    """Merge blocks; columnar stays columnar when schemas line up."""
    blocks = [b for b in blocks if len(b)]
    if not blocks:
        return []
    if all(isinstance(b, ColumnBlock) for b in blocks):
        names = list(blocks[0].cols)
        if all(list(b.cols) == names for b in blocks):
            return ColumnBlock({
                k: np.concatenate([b.cols[k] for b in blocks])
                for k in names})
    out: list = []
    for b in blocks:
        out.extend(rows_of(b))
    return out
