"""Dataset: a list of block ObjectRefs + per-block task transforms.

Reference: python/ray/data/dataset.py (Dataset :49). Each transform
launches one task per block; blocks stay in the object store between
stages (zero-copy for numpy payloads via the shm plane).

Blocks are COLUMNAR (block.py ColumnBlock — struct of numpy arrays,
reference analog: data/impl/arrow_block.py:57) whenever the rows
columnize; sort/shuffle/partition/aggregate on them are numpy
argsort/searchsorted/bincount instead of Python row loops, and
``key``/``on`` accept COLUMN NAMES (vectorized) as well as callables
(row path). Rows materialize only at the API edge (take/iter_rows).
"""

from __future__ import annotations

import builtins
import functools
import operator
import random
from typing import Any, Callable, List, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu.data.block import (SCALAR, ColumnBlock, concat as _concat,
                                from_rows, rows_of, split_by_partition)

KeyType = Union[None, str, Callable]


def _key_fn(key: KeyType):
    """Row-space accessor for a key spec (column name or callable)."""
    if isinstance(key, str):
        return operator.itemgetter(key)
    return key


def _vec_key(key: KeyType) -> bool:
    """Keys the columnar path handles without materializing rows."""
    return key is None or isinstance(key, str)


# ---- block-level helpers (run inside tasks; module-level = picklable) --


def _block_map(fn, block):
    return from_rows([fn(r) for r in rows_of(block)])


def _block_map_batches(fn, block, fmt):
    if fmt == "numpy" and isinstance(block, ColumnBlock):
        # zero row-trip: scalar blocks hand the bare array, named
        # blocks a dict of column arrays
        batch = block.cols[SCALAR] if block.scalar else dict(block.cols)
    elif fmt == "numpy":
        batch = np.array(block)
    else:
        batch = rows_of(block)
    out = fn(batch)
    if isinstance(out, dict):  # columns back in -> columnar block
        cols = {k: np.atleast_1d(np.asarray(v)) for k, v in out.items()}
        lens = {len(v) for v in cols.values()}
        if len(lens) > 1:
            raise ValueError(
                f"map_batches returned ragged columns (lengths {lens})")
        return ColumnBlock(cols)
    if isinstance(out, np.ndarray):
        return ColumnBlock({SCALAR: out}) if out.ndim == 1 else list(out)
    return from_rows(list(out))


def _block_filter(fn, block):
    return from_rows([r for r in rows_of(block) if fn(r)])


def _block_flat_map(fn, block):
    out = []
    for r in rows_of(block):
        out.extend(fn(r))
    return from_rows(out)


def _sample_block_keys(block, key, k):
    """Up to k evenly-spaced key values from one block (boundary
    sampling for the distributed sort) — columnar blocks never touch
    rows."""
    if isinstance(block, ColumnBlock) and _vec_key(key):
        kv = block.key_values(key)
        if len(kv) > k:
            kv = kv[np.linspace(0, len(kv) - 1, k).astype(np.int64)]
        return kv.tolist()
    kf = _key_fn(key)
    rows = rows_of(block)
    step = max(1, len(rows) // max(1, k))
    return [(kf(r) if kf else r) for r in rows[::step][:k]]


def _block_sort(block, key, descending):
    if isinstance(block, ColumnBlock) and _vec_key(key):
        idx = np.argsort(block.key_values(key), kind="stable")
        return block.take(idx[::-1] if descending else idx)
    return from_rows(sorted(rows_of(block), key=_key_fn(key),
                            reverse=descending))


def _block_partition(block, boundaries, key):
    """Range-partition one block for distributed sort."""
    if isinstance(block, ColumnBlock) and _vec_key(key) and boundaries:
        # partition id = number of boundaries <= key (same rule as the
        # row loop below)
        part = np.searchsorted(np.asarray(boundaries),
                               block.key_values(key), side="right")
        return split_by_partition(block, part, len(boundaries) + 1)
    kf = _key_fn(key)
    out: List[List] = [[] for _ in range(len(boundaries) + 1)]
    for r in rows_of(block):
        k = kf(r) if kf else r
        lo = 0
        for i, b in enumerate(boundaries):
            if k < b:
                break
            lo = i + 1
        out[lo].append(r)
    return out


def _block_shuffle_split(block, n, seed):
    if isinstance(block, ColumnBlock):
        rng = np.random.default_rng(seed)
        return split_by_partition(block, rng.integers(0, n, len(block)),
                                  n)
    rng = random.Random(seed)
    out: List[List] = [[] for _ in range(n)]
    for r in block:
        out[rng.randrange(n)].append(r)
    return out


def _block_shuffle(block, seed):
    if isinstance(block, ColumnBlock):
        rng = np.random.default_rng(seed)
        return block.take(rng.permutation(len(block)))
    block = list(block)
    random.Random(seed).shuffle(block)
    return block


def _merge_blocks(*parts):
    return _concat(parts)


def _merge_sorted(key, descending, *parts):
    return _block_sort(_concat(parts), key, descending)


def _zip_blocks(a, b):
    return list(zip(rows_of(a), rows_of(b)))


def _block_limit(block, n):
    if isinstance(block, ColumnBlock):
        return block.slice(0, n)
    return block[:n]


def _block_select_columns(block, cols):
    if not cols:
        # ColumnBlock({}) cannot carry a row count (same hazard
        # drop_columns guards); match it with a clear error
        raise ValueError("select_columns needs at least one column")
    if isinstance(block, ColumnBlock) and not block.scalar:
        return ColumnBlock({k: block.cols[k] for k in cols})
    return from_rows([{k: r[k] for k in cols} for r in rows_of(block)])


def _block_drop_columns(block, cols):
    drop = set(cols)
    if isinstance(block, ColumnBlock) and not block.scalar:
        kept = {k: v for k, v in block.cols.items() if k not in drop}
        if not kept and len(block):
            # ColumnBlock({}) has no column to carry the row count —
            # dropping EVERY column would silently empty the dataset
            raise ValueError("drop_columns removed every column")
        return ColumnBlock(kept)
    rows = [{k: v for k, v in r.items() if k not in drop}
            for r in rows_of(block)]
    if rows and not rows[0]:
        raise ValueError("drop_columns removed every column")
    return from_rows(rows)


def _block_add_column(block, name, fn):
    if isinstance(block, ColumnBlock) and not block.scalar:
        col = np.asarray(fn(dict(block.cols)))
        if col.shape[:1] != (len(block),):
            raise ValueError(
                f"add_column fn returned shape {col.shape} for a "
                f"{len(block)}-row block")
        cols = dict(block.cols)
        cols[name] = col
        return ColumnBlock(cols)
    rows = rows_of(block)
    if not rows:
        return block
    # row fallback: fn still sees a columns dict, which requires
    # UNIFORM dict rows (same keys throughout) — scalar datasets have
    # no record to add a column to
    names = rows[0].keys() if isinstance(rows[0], dict) else None
    if names is None or any(not isinstance(r, dict)
                            or r.keys() != names for r in rows):
        raise ValueError(
            "add_column needs a dataset of uniform dict rows")
    cols_view = {k: np.asarray([r[k] for r in rows]) for k in names}
    vals = np.asarray(fn(cols_view))
    if vals.shape[:1] != (len(rows),):  # same contract as columnar path
        raise ValueError(
            f"add_column fn returned shape {vals.shape} for a "
            f"{len(rows)}-row block")
    out = []
    for r, v in zip(rows, vals):
        r = dict(r)
        r[name] = v.item() if hasattr(v, "item") else v
        out.append(r)
    return from_rows(out)


def _block_sample(block, fraction, seed):
    rng = np.random.default_rng(seed)
    if isinstance(block, ColumnBlock):
        return block.take(np.nonzero(
            rng.random(len(block)) < fraction)[0])
    keep = rng.random(len(block)) < fraction
    return [r for r, k in zip(block, keep) if k]


def _block_agg(agg, on, block):
    if isinstance(block, ColumnBlock) and _vec_key(on):
        if not len(block):
            return None
        col = block.key_values(on)
        fn = {"sum": np.sum, "min": np.min, "max": np.max}[agg]
        return fn(col).item()
    of = _key_fn(on)
    vals = [of(r) if of else r for r in rows_of(block)]
    if not vals:
        return None
    if agg == "sum":
        return builtins.sum(vals)
    if agg == "min":
        return builtins.min(vals)
    if agg == "max":
        return builtins.max(vals)
    raise ValueError(agg)


_remote_cache: dict = {}


def _remote(fn, num_returns=1):
    key = (fn, num_returns)
    if key not in _remote_cache:
        _remote_cache[key] = ray_tpu.remote(fn).options(
            num_returns=num_returns)
    return _remote_cache[key]


class Dataset:
    def __init__(self, blocks: List):
        self._blocks = list(blocks)
        self._meta = None  # cached List[BlockMetadata]
        # per-block row counts fetched incrementally by limit() (cheaper
        # than materializing full _metadata for a prefix-only scan)
        self._row_counts: dict = {}

    # ------------------------------------------------------------ meta

    @property
    def num_blocks(self) -> int:
        return len(self._blocks)

    def _metadata(self):
        """Per-block metadata, computed once (reference: BlockMetadata
        tracked by data/block.py; here fetched via one task per block
        and cached on the dataset)."""
        if self._meta is None:
            metas = ray_tpu.get([_remote(_block_meta).remote(b)
                                 for b in self._blocks])
            self._meta = [BlockMetadata(*m) for m in metas]
        return self._meta

    def count(self) -> int:
        return builtins.sum(m.num_rows for m in self._metadata())

    def size_bytes(self) -> int:
        """Estimated in-memory size across blocks."""
        return builtins.sum(m.size_bytes for m in self._metadata())

    def schema(self):
        """Schema of the first non-empty block (dict rows → {field:
        type name}; scalar rows → type name)."""
        for m in self._metadata():
            if m.schema is not None:
                return m.schema
        return None

    def groupby(self, key: KeyType) -> "GroupedDataset":
        """``key``: a column name (vectorized groupby on columnar
        blocks) or a row callable."""
        return GroupedDataset(self, key)

    # ------------------------------------------------------------ write

    def write_parquet(self, dir_path: str) -> List[str]:
        from ray_tpu.data import read_api

        return read_api.write_parquet(self, dir_path)

    def write_csv(self, dir_path: str) -> List[str]:
        from ray_tpu.data import read_api

        return read_api.write_csv(self, dir_path)

    def write_json(self, dir_path: str) -> List[str]:
        from ray_tpu.data import read_api

        return read_api.write_json(self, dir_path)

    def write_numpy(self, dir_path: str) -> List[str]:
        from ray_tpu.data import read_api

        return read_api.write_numpy(self, dir_path)

    def __repr__(self):
        return f"Dataset(num_blocks={self.num_blocks})"

    # ------------------------------------------------------ transforms

    def map(self, fn: Callable) -> "Dataset":
        r = _remote(_block_map)
        return Dataset([r.remote(fn, b) for b in self._blocks])

    def map_batches(self, fn: Callable,
                    batch_format: str = "native") -> "Dataset":
        r = _remote(_block_map_batches)
        return Dataset([r.remote(fn, b, batch_format)
                        for b in self._blocks])

    def filter(self, fn: Callable) -> "Dataset":
        r = _remote(_block_filter)
        return Dataset([r.remote(fn, b) for b in self._blocks])

    def flat_map(self, fn: Callable) -> "Dataset":
        r = _remote(_block_flat_map)
        return Dataset([r.remote(fn, b) for b in self._blocks])

    # ------------------------------------------------- reorganization

    def repartition(self, num_blocks: int) -> "Dataset":
        """Rebalance into num_blocks blocks (full rebuild, like the
        reference's shuffle=True path). Columnar inputs re-slice as
        arrays without a row trip."""
        fetched = ray_tpu.get(list(self._blocks))
        merged = _concat(fetched)
        total = len(merged)
        step, rem = divmod(total, num_blocks)
        blocks, i = [], 0
        for b in range(num_blocks):
            n = step + (1 if b < rem else 0)
            if isinstance(merged, ColumnBlock):
                blocks.append(ray_tpu.put(merged.slice(i, i + n)))
            else:
                blocks.append(ray_tpu.put(from_rows(merged[i:i + n])))
            i += n
        return Dataset(blocks)

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Distributed 2-stage shuffle (reference: simple_shuffle,
        data/impl/shuffle.py:16): map splits each block into N random
        partitions; reduce merges partition j of every block."""
        n = max(1, self.num_blocks)
        seed = seed if seed is not None else random.randrange(2 ** 31)
        if n == 1:
            r = _remote(_block_shuffle)
            return Dataset([r.remote(b, seed) for b in self._blocks])
        split = _remote(_block_shuffle_split, num_returns=n)
        parts = [split.remote(b, n, seed + i)
                 for i, b in enumerate(self._blocks)]
        merge = _remote(_merge_blocks)
        shuf = _remote(_block_shuffle)
        out = [shuf.remote(
                   merge.remote(*[parts[i][j]
                                  for i in range(len(parts))]),
                   seed + 7919 * j)
               for j in range(n)]
        return Dataset(out)

    def sort(self, key: KeyType = None,
             descending: bool = False) -> "Dataset":
        """Distributed range-partitioned sort (reference:
        data/impl/sort.py): sample boundaries, partition each block,
        merge-sort each range. ``key``: column name (vectorized on
        columnar blocks, like the reference's Arrow sort) or callable."""
        n = max(1, self.num_blocks)
        if n == 1:
            r = _remote(_block_sort)
            return Dataset([r.remote(self._blocks[0], key, descending)])
        # sample boundaries from the data (per-block key samples; no
        # row materialization on columnar blocks)
        per = max(8, 1000 // n)
        sampler = _remote(_sample_block_keys)
        keys = sorted(k for ks in ray_tpu.get(
            [sampler.remote(b, key, per) for b in self._blocks])
            for k in ks)
        boundaries = [keys[min(len(keys) - 1,
                               int(len(keys) * (i + 1) / n))]
                      for i in range(n - 1)] if keys else []
        part = _remote(_block_partition, num_returns=n)
        parts = [part.remote(b, boundaries, key) for b in self._blocks]
        # key/descending travel as task args so the cached remote function
        # stays one module-level entry (a fresh partial per sort() call
        # would grow _remote_cache without bound).
        merge = _remote(_merge_sorted)
        out = [merge.remote(key, descending,
                            *[parts[i][j] for i in range(len(parts))])
               for j in range(n)]
        if descending:
            out = out[::-1]
        return Dataset(out)

    def limit(self, n: int) -> "Dataset":
        """First n rows (reference: dataset.py limit) — columnar
        blocks slice without a row trip. Block row counts are fetched
        INCREMENTALLY so a limit over an expensive pipeline only
        executes the prefix blocks it needs (like take())."""
        meta_fn = _remote(_block_meta)
        out, have = [], 0
        for i, b in enumerate(self._blocks):
            if have >= n:
                break
            if self._meta is not None:
                rows = self._meta[i].num_rows
            elif i in self._row_counts:
                rows = self._row_counts[i]
            else:
                rows = self._row_counts[i] = \
                    ray_tpu.get(meta_fn.remote(b))[0]
            take_n = min(rows, n - have)
            if take_n == rows:
                out.append(b)
            else:
                out.append(_remote(_block_limit).remote(b, take_n))
            have += take_n
        return Dataset(out)

    @staticmethod
    def _column_list(cols) -> List[str]:
        if isinstance(cols, str):
            # list('ab') would silently mean columns 'a' and 'b'
            raise TypeError(
                f"pass a list of column names, not the string {cols!r}")
        return list(cols)

    def select_columns(self, cols: List[str]) -> "Dataset":
        """Keep only the named columns (reference: map over rows; here
        a zero-copy column subset on columnar blocks)."""
        r = _remote(_block_select_columns)
        cols = self._column_list(cols)
        return Dataset([r.remote(b, cols) for b in self._blocks])

    def drop_columns(self, cols: List[str]) -> "Dataset":
        r = _remote(_block_drop_columns)
        cols = self._column_list(cols)
        return Dataset([r.remote(b, cols) for b in self._blocks])

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        """Add/replace a column computed from each batch (reference:
        dataset.py add_column — fn receives the columnar batch)."""
        r = _remote(_block_add_column)
        return Dataset([r.remote(b, name, fn) for b in self._blocks])

    def random_sample(self, fraction: float, *,
                      seed: Optional[int] = None) -> "Dataset":
        """Bernoulli row sample (reference: dataset.py random_sample)."""
        if not 0.0 <= fraction <= 1.0:
            raise ValueError("fraction must be in [0, 1]")
        base = seed if seed is not None else random.randrange(2 ** 31)
        r = _remote(_block_sample)
        return Dataset([r.remote(b, fraction, base + i)
                        for i, b in enumerate(self._blocks)])

    def split(self, n: int) -> List["Dataset"]:
        """Split into n datasets by whole blocks (repartitions first if
        fewer blocks than splits)."""
        ds = self if self.num_blocks >= n else self.repartition(n)
        shards: List[List] = [[] for _ in range(n)]
        for i, b in enumerate(ds._blocks):
            shards[i % n].append(b)
        return [Dataset(s) for s in shards]

    def union(self, *others: "Dataset") -> "Dataset":
        blocks = list(self._blocks)
        for o in others:
            blocks.extend(o._blocks)
        return Dataset(blocks)

    def zip(self, other: "Dataset") -> "Dataset":
        if self.num_blocks != other.num_blocks:
            raise ValueError("zip requires equal block counts")
        r = _remote(_zip_blocks)
        return Dataset([r.remote(a, b)
                        for a, b in zip(self._blocks, other._blocks)])

    # ---------------------------------------------------- consumption

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        for b in self._blocks:
            blk = ray_tpu.get(b)
            if isinstance(blk, ColumnBlock):
                # rows materialize for the TAKEN prefix only
                out.extend(blk.slice(0, n - len(out)).to_rows())
            else:
                out.extend(blk)
            if len(out) >= n:
                return out[:n]
        return out

    def take_all(self) -> List[Any]:
        out: List[Any] = []
        for block in ray_tpu.get(list(self._blocks)):
            out.extend(rows_of(block))
        return out

    def show(self, n: int = 20) -> None:
        for r in self.take(n):
            print(r)

    def sum(self, on: KeyType = None):
        vals = [v for v in ray_tpu.get(
            [_remote(_block_agg).remote("sum", on, b)
             for b in self._blocks]) if v is not None]
        return builtins.sum(vals) if vals else 0

    def min(self, on: KeyType = None):
        vals = [v for v in ray_tpu.get(
            [_remote(_block_agg).remote("min", on, b)
             for b in self._blocks]) if v is not None]
        return builtins.min(vals)

    def max(self, on: KeyType = None):
        vals = [v for v in ray_tpu.get(
            [_remote(_block_agg).remote("max", on, b)
             for b in self._blocks]) if v is not None]
        return builtins.max(vals)

    def mean(self, on: KeyType = None):
        return self.sum(on) / max(1, self.count())

    def iter_rows(self):
        for b in self._blocks:
            yield from rows_of(ray_tpu.get(b))

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "native"):
        buf: List[Any] = []
        carry: Optional[np.ndarray] = None  # columnar remainder
        for b in self._blocks:
            blk = ray_tpu.get(b)
            if batch_format == "numpy" and isinstance(blk, ColumnBlock) \
                    and blk.scalar and not buf:
                # array-slice batches, no row materialization
                arr = blk.cols[SCALAR]
                if carry is not None:
                    arr = np.concatenate([carry, arr])
                    carry = None
                full = (len(arr) // batch_size) * batch_size
                for i in range(0, full, batch_size):
                    yield arr[i:i + batch_size]
                if full < len(arr):
                    carry = arr[full:]
                continue
            if carry is not None:  # fell off the fast path mid-stream
                buf.extend(carry.tolist())
                carry = None
            buf.extend(rows_of(blk))
            while len(buf) >= batch_size:
                batch, buf = buf[:batch_size], buf[batch_size:]
                yield (np.array(batch) if batch_format == "numpy"
                       else batch)
        if carry is not None:
            yield carry
        elif buf:
            yield np.array(buf) if batch_format == "numpy" else buf

    def to_numpy(self) -> np.ndarray:
        blocks = ray_tpu.get(list(self._blocks))
        if blocks and all(isinstance(b, ColumnBlock) and b.scalar
                          for b in blocks):
            return np.concatenate([b.cols[SCALAR] for b in blocks])
        out: List[Any] = []
        for b in blocks:
            out.extend(rows_of(b))
        return np.array(out)

    def to_jax(self, *, batch_size: Optional[int] = None):
        """Device-ready arrays: the whole dataset (batch_size=None) or
        an iterator of jnp batches."""
        import jax.numpy as jnp

        if batch_size is None:
            return jnp.asarray(self.to_numpy())
        return (jnp.asarray(b) for b in self.iter_batches(
            batch_size=batch_size, batch_format="numpy"))

    def to_torch(self, *, batch_size: Optional[int] = None):
        """Torch tensors (reference: python/ray/data/dataset.py:1047 to_torch):
        the whole dataset (batch_size=None) or an iterator of batches."""
        import torch

        if batch_size is None:
            return torch.as_tensor(self.to_numpy())
        return (torch.as_tensor(b) for b in self.iter_batches(
            batch_size=batch_size, batch_format="numpy"))

    # ------------------------------------------------------- pipeline

    def window(self, *, blocks_per_window: int = 2):
        from ray_tpu.data.pipeline import DatasetPipeline

        windows = [Dataset(self._blocks[i:i + blocks_per_window])
                   for i in range(0, self.num_blocks, blocks_per_window)]
        return DatasetPipeline(windows)

    def repeat(self, times: int):
        from ray_tpu.data.pipeline import DatasetPipeline

        return DatasetPipeline([self] * times)


# -------------------------------------------------------- block metadata

class BlockMetadata:
    """Per-block stats (reference: data/block.py BlockMetadata)."""

    __slots__ = ("num_rows", "size_bytes", "schema")

    def __init__(self, num_rows: int, size_bytes: int, schema):
        self.num_rows = num_rows
        self.size_bytes = size_bytes
        self.schema = schema

    def __repr__(self):
        return (f"BlockMetadata(rows={self.num_rows}, "
                f"bytes={self.size_bytes}, schema={self.schema})")


def _block_meta(block):
    import sys

    if isinstance(block, ColumnBlock):
        # columnar: EXACT bytes + dtype-derived schema (reference:
        # arrow_block.py BlockMetadata carries exact size_bytes)
        return [len(block), block.size_bytes(),
                block.schema() if len(block) else None]
    if block and isinstance(block[0], dict):
        schema = {k: type(v).__name__ for k, v in block[0].items()}
    elif block:
        schema = type(block[0]).__name__
    else:
        schema = None
    size = builtins.sum(sys.getsizeof(r) for r in block[:64])
    if len(block) > 64:  # extrapolate from the sampled prefix
        size = int(size * len(block) / 64)
    return [len(block), size, schema]


def _block_group(key, agg_fn, on, block):
    # Partials NEVER apply the init seed: a key spanning blocks would
    # absorb it once per block. The seed folds in exactly once, after
    # the final merge (_group_dict_to_rows).
    kf = _key_fn(key)
    of = _key_fn(on)
    out = {}
    for row in rows_of(block):
        k = kf(row)
        v = of(row) if of else row
        out[k] = agg_fn(out[k], v) if k in out else v
    return out


def _block_group_vec(key, agg, on, block):
    """Vectorized per-block groupby for sum/count on named columns
    (reference: arrow GroupedDataset aggregations): one np.unique +
    bincount instead of a per-row dict loop."""
    if isinstance(block, ColumnBlock) and _vec_key(key) and \
            (agg == "count" or _vec_key(on)):
        if not len(block):
            return {}
        uniq, inv = np.unique(block.key_values(key),
                              return_inverse=True)
        if agg == "count":
            vals = np.bincount(inv, minlength=len(uniq))
        else:
            col = block.key_values(on)
            if col.dtype.kind in "iub":
                # exact integer accumulation (bincount's float64
                # weights would round sums above 2**53)
                vals = np.zeros(len(uniq), dtype=np.int64)
                np.add.at(vals, inv, col)
            else:
                vals = np.bincount(inv, weights=col,
                                   minlength=len(uniq))
        return dict(zip(uniq.tolist(), vals.tolist()))
    kf = _key_fn(key)
    of = _key_fn(on)
    out: dict = {}
    for row in rows_of(block):
        k = kf(row) if kf else row
        v = 1 if agg == "count" else (of(row) if of else row)
        out[k] = out.get(k, 0) + v
    return out


def _tree_reduce(merge_remote, partials, extra_args=()):
    """4-way tree fan-in of partial results (shared by the vectorized
    and generic groupby paths)."""
    while len(partials) > 1:
        nxt = []
        for i in builtins.range(0, len(partials), 4):
            group = partials[i:i + 4]
            nxt.append(merge_remote.remote(*extra_args, *group)
                       if len(group) > 1 else group[0])
        partials = nxt
    return partials[0]


def _merge_group_dicts(agg_fn, *dicts):
    out = {}
    for d in dicts:
        for k, v in d.items():
            out[k] = agg_fn(out[k], v) if k in out else v
    return out


class GroupedDataset:
    """``ds.groupby(key)`` → per-key aggregations (reference:
    data/grouped_dataset.py). Hash-combine per block, tree-merge;
    column-name keys run the vectorized (bincount) path."""

    def __init__(self, ds: "Dataset", key: KeyType):
        self._ds = ds
        self._key = key

    def _check_on(self, on, what: str) -> None:
        """Driver-side validation: aggregating whole rows (on=None)
        only makes sense for scalar rows. On a named-column dataset it
        used to surface as a remote KeyError from inside a task — fail
        here, with the fix spelled out."""
        if on is not None:
            return
        schema = self._ds.schema()
        if isinstance(schema, dict):
            cols = ", ".join(repr(c) for c in schema)
            raise ValueError(
                f"groupby(...).{what} needs on=<column> for a dataset "
                f"with named columns ({cols}): whole dict rows cannot "
                f"be aggregated")

    def _agg_vec(self, agg: str, on: KeyType) -> "Dataset":
        part = _remote(_block_group_vec)
        partials = [part.remote(self._key, agg, on, b)
                    for b in self._ds._blocks]
        root = _tree_reduce(_remote(_merge_group_dicts), partials,
                            extra_args=(operator.add,))
        items = _remote(_group_dict_to_rows).remote(root)
        return Dataset([items])

    def aggregate(self, agg_fn: Callable, *, on: Optional[Callable] = None,
                  init=None) -> "Dataset":
        self._check_on(on, "aggregate(...)")
        part = _remote(_block_group)
        partials = [part.remote(self._key, agg_fn, on, b)
                    for b in self._ds._blocks]
        root = _tree_reduce(_remote(_merge_group_dicts), partials,
                            extra_args=(agg_fn,))
        items = _remote(_group_dict_to_rows).remote(root, agg_fn, init)
        return Dataset([items])

    def count(self) -> "Dataset":
        if _vec_key(self._key):
            return self._agg_vec("count", None)
        return self.aggregate(lambda a, b: a + b, on=lambda _: 1)

    def sum(self, on: KeyType = None) -> "Dataset":
        self._check_on(on, "sum()")
        if _vec_key(self._key) and _vec_key(on):
            return self._agg_vec("sum", on)
        return self.aggregate(lambda a, b: a + b, on=on)


def _group_dict_to_rows(d, agg_fn=None, init=None):
    if init is not None:
        d = {k: agg_fn(init, v) for k, v in d.items()}
    return sorted(d.items())
