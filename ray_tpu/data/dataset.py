"""Dataset: a list of block ObjectRefs + per-block task transforms.

Reference: python/ray/data/dataset.py (Dataset :49). Each transform
launches one task per block; blocks stay in the object store between
stages (zero-copy for numpy payloads via the shm plane).
"""

from __future__ import annotations

import builtins
import functools
import random
from typing import Any, Callable, List, Optional

import numpy as np

import ray_tpu

# ---- block-level helpers (run inside tasks; module-level = picklable) --


def _block_map(fn, block):
    return [fn(r) for r in block]


def _block_map_batches(fn, block, fmt):
    if fmt == "numpy":
        batch = np.array(block)
    else:
        batch = block
    out = fn(batch)
    if isinstance(out, np.ndarray):
        return list(out)
    return list(out)


def _block_filter(fn, block):
    return [r for r in block if fn(r)]


def _block_flat_map(fn, block):
    out = []
    for r in block:
        out.extend(fn(r))
    return out


def _block_sort(block, key, descending):
    return sorted(block, key=key, reverse=descending)


def _block_partition(block, boundaries, key):
    """Range-partition a sorted-input block for distributed sort."""
    parts: List[List] = [[] for _ in range(len(boundaries) + 1)]
    for r in block:
        k = key(r) if key else r
        lo = 0
        for i, b in enumerate(boundaries):
            if k < b:
                break
            lo = i + 1
        parts[lo].append(r)
    return parts


def _block_shuffle_split(block, n, seed):
    rng = random.Random(seed)
    parts: List[List] = [[] for _ in range(n)]
    for r in block:
        parts[rng.randrange(n)].append(r)
    return parts


def _block_shuffle(block, seed):
    block = list(block)
    random.Random(seed).shuffle(block)
    return block


def _merge_blocks(*parts):
    out = []
    for p in parts:
        out.extend(p)
    return out


def _merge_sorted(key, descending, *parts):
    return sorted(_merge_blocks(*parts),
                  key=key, reverse=descending)


def _zip_blocks(a, b):
    return list(zip(a, b))


def _block_agg(agg, on, block):
    vals = [on(r) if on else r for r in block]
    if not vals:
        return None
    if agg == "sum":
        return builtins.sum(vals)
    if agg == "min":
        return builtins.min(vals)
    if agg == "max":
        return builtins.max(vals)
    raise ValueError(agg)


_remote_cache: dict = {}


def _remote(fn, num_returns=1):
    key = (fn, num_returns)
    if key not in _remote_cache:
        _remote_cache[key] = ray_tpu.remote(fn).options(
            num_returns=num_returns)
    return _remote_cache[key]


class Dataset:
    def __init__(self, blocks: List):
        self._blocks = list(blocks)
        self._meta = None  # cached List[BlockMetadata]

    # ------------------------------------------------------------ meta

    @property
    def num_blocks(self) -> int:
        return len(self._blocks)

    def _metadata(self):
        """Per-block metadata, computed once (reference: BlockMetadata
        tracked by data/block.py; here fetched via one task per block
        and cached on the dataset)."""
        if self._meta is None:
            metas = ray_tpu.get([_remote(_block_meta).remote(b)
                                 for b in self._blocks])
            self._meta = [BlockMetadata(*m) for m in metas]
        return self._meta

    def count(self) -> int:
        return builtins.sum(m.num_rows for m in self._metadata())

    def size_bytes(self) -> int:
        """Estimated in-memory size across blocks."""
        return builtins.sum(m.size_bytes for m in self._metadata())

    def schema(self):
        """Schema of the first non-empty block (dict rows → {field:
        type name}; scalar rows → type name)."""
        for m in self._metadata():
            if m.schema is not None:
                return m.schema
        return None

    def groupby(self, key: Callable) -> "GroupedDataset":
        return GroupedDataset(self, key)

    # ------------------------------------------------------------ write

    def write_parquet(self, dir_path: str) -> List[str]:
        from ray_tpu.data import read_api

        return read_api.write_parquet(self, dir_path)

    def write_csv(self, dir_path: str) -> List[str]:
        from ray_tpu.data import read_api

        return read_api.write_csv(self, dir_path)

    def write_json(self, dir_path: str) -> List[str]:
        from ray_tpu.data import read_api

        return read_api.write_json(self, dir_path)

    def write_numpy(self, dir_path: str) -> List[str]:
        from ray_tpu.data import read_api

        return read_api.write_numpy(self, dir_path)

    def __repr__(self):
        return f"Dataset(num_blocks={self.num_blocks})"

    # ------------------------------------------------------ transforms

    def map(self, fn: Callable) -> "Dataset":
        r = _remote(_block_map)
        return Dataset([r.remote(fn, b) for b in self._blocks])

    def map_batches(self, fn: Callable,
                    batch_format: str = "native") -> "Dataset":
        r = _remote(_block_map_batches)
        return Dataset([r.remote(fn, b, batch_format)
                        for b in self._blocks])

    def filter(self, fn: Callable) -> "Dataset":
        r = _remote(_block_filter)
        return Dataset([r.remote(fn, b) for b in self._blocks])

    def flat_map(self, fn: Callable) -> "Dataset":
        r = _remote(_block_flat_map)
        return Dataset([r.remote(fn, b) for b in self._blocks])

    # ------------------------------------------------- reorganization

    def repartition(self, num_blocks: int) -> "Dataset":
        """Rebalance into num_blocks blocks (full rebuild, like the
        reference's shuffle=True path)."""
        rows = self.take_all()
        step, rem = divmod(len(rows), num_blocks)
        blocks, i = [], 0
        for b in range(num_blocks):
            n = step + (1 if b < rem else 0)
            blocks.append(ray_tpu.put(rows[i:i + n]))
            i += n
        return Dataset(blocks)

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Distributed 2-stage shuffle (reference: simple_shuffle,
        data/impl/shuffle.py:16): map splits each block into N random
        partitions; reduce merges partition j of every block."""
        n = max(1, self.num_blocks)
        seed = seed if seed is not None else random.randrange(2 ** 31)
        if n == 1:
            r = _remote(_block_shuffle)
            return Dataset([r.remote(b, seed) for b in self._blocks])
        split = _remote(_block_shuffle_split, num_returns=n)
        parts = [split.remote(b, n, seed + i)
                 for i, b in enumerate(self._blocks)]
        merge = _remote(_merge_blocks)
        shuf = _remote(_block_shuffle)
        out = [shuf.remote(
                   merge.remote(*[parts[i][j]
                                  for i in range(len(parts))]),
                   seed + 7919 * j)
               for j in range(n)]
        return Dataset(out)

    def sort(self, key: Optional[Callable] = None,
             descending: bool = False) -> "Dataset":
        """Distributed range-partitioned sort (reference:
        data/impl/sort.py): sample boundaries, partition each block,
        merge-sort each range."""
        n = max(1, self.num_blocks)
        if n == 1:
            r = _remote(_block_sort)
            return Dataset([r.remote(self._blocks[0], key, descending)])
        # sample boundaries from the data
        sample = self.take(min(1000, self.count()))
        keys = sorted((key(r) if key else r) for r in sample)
        boundaries = [keys[min(len(keys) - 1,
                               int(len(keys) * (i + 1) / n))]
                      for i in range(n - 1)] if keys else []
        part = _remote(_block_partition, num_returns=n)
        parts = [part.remote(b, boundaries, key) for b in self._blocks]
        # key/descending travel as task args so the cached remote function
        # stays one module-level entry (a fresh partial per sort() call
        # would grow _remote_cache without bound).
        merge = _remote(_merge_sorted)
        out = [merge.remote(key, descending,
                            *[parts[i][j] for i in range(len(parts))])
               for j in range(n)]
        if descending:
            out = out[::-1]
        return Dataset(out)

    def split(self, n: int) -> List["Dataset"]:
        """Split into n datasets by whole blocks (repartitions first if
        fewer blocks than splits)."""
        ds = self if self.num_blocks >= n else self.repartition(n)
        shards: List[List] = [[] for _ in range(n)]
        for i, b in enumerate(ds._blocks):
            shards[i % n].append(b)
        return [Dataset(s) for s in shards]

    def union(self, *others: "Dataset") -> "Dataset":
        blocks = list(self._blocks)
        for o in others:
            blocks.extend(o._blocks)
        return Dataset(blocks)

    def zip(self, other: "Dataset") -> "Dataset":
        if self.num_blocks != other.num_blocks:
            raise ValueError("zip requires equal block counts")
        r = _remote(_zip_blocks)
        return Dataset([r.remote(a, b)
                        for a, b in zip(self._blocks, other._blocks)])

    # ---------------------------------------------------- consumption

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        for b in self._blocks:
            out.extend(ray_tpu.get(b))
            if len(out) >= n:
                return out[:n]
        return out

    def take_all(self) -> List[Any]:
        out: List[Any] = []
        for block in ray_tpu.get(list(self._blocks)):
            out.extend(block)
        return out

    def show(self, n: int = 20) -> None:
        for r in self.take(n):
            print(r)

    def sum(self, on: Optional[Callable] = None):
        vals = [v for v in ray_tpu.get(
            [_remote(_block_agg).remote("sum", on, b)
             for b in self._blocks]) if v is not None]
        return builtins.sum(vals) if vals else 0

    def min(self, on: Optional[Callable] = None):
        vals = [v for v in ray_tpu.get(
            [_remote(_block_agg).remote("min", on, b)
             for b in self._blocks]) if v is not None]
        return builtins.min(vals)

    def max(self, on: Optional[Callable] = None):
        vals = [v for v in ray_tpu.get(
            [_remote(_block_agg).remote("max", on, b)
             for b in self._blocks]) if v is not None]
        return builtins.max(vals)

    def mean(self, on: Optional[Callable] = None):
        return self.sum(on) / max(1, self.count())

    def iter_rows(self):
        for b in self._blocks:
            yield from ray_tpu.get(b)

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "native"):
        buf: List[Any] = []
        for b in self._blocks:
            buf.extend(ray_tpu.get(b))
            while len(buf) >= batch_size:
                batch, buf = buf[:batch_size], buf[batch_size:]
                yield (np.array(batch) if batch_format == "numpy"
                       else batch)
        if buf:
            yield np.array(buf) if batch_format == "numpy" else buf

    def to_numpy(self) -> np.ndarray:
        return np.array(self.take_all())

    def to_jax(self, *, batch_size: Optional[int] = None):
        """Device-ready arrays: the whole dataset (batch_size=None) or
        an iterator of jnp batches."""
        import jax.numpy as jnp

        if batch_size is None:
            return jnp.asarray(self.to_numpy())
        return (jnp.asarray(b) for b in self.iter_batches(
            batch_size=batch_size, batch_format="numpy"))

    def to_torch(self, *, batch_size: Optional[int] = None):
        """Torch tensors (reference: python/ray/data/dataset.py:1047 to_torch):
        the whole dataset (batch_size=None) or an iterator of batches."""
        import torch

        if batch_size is None:
            return torch.as_tensor(self.to_numpy())
        return (torch.as_tensor(b) for b in self.iter_batches(
            batch_size=batch_size, batch_format="numpy"))

    # ------------------------------------------------------- pipeline

    def window(self, *, blocks_per_window: int = 2):
        from ray_tpu.data.pipeline import DatasetPipeline

        windows = [Dataset(self._blocks[i:i + blocks_per_window])
                   for i in range(0, self.num_blocks, blocks_per_window)]
        return DatasetPipeline(windows)

    def repeat(self, times: int):
        from ray_tpu.data.pipeline import DatasetPipeline

        return DatasetPipeline([self] * times)


# -------------------------------------------------------- block metadata

class BlockMetadata:
    """Per-block stats (reference: data/block.py BlockMetadata)."""

    __slots__ = ("num_rows", "size_bytes", "schema")

    def __init__(self, num_rows: int, size_bytes: int, schema):
        self.num_rows = num_rows
        self.size_bytes = size_bytes
        self.schema = schema

    def __repr__(self):
        return (f"BlockMetadata(rows={self.num_rows}, "
                f"bytes={self.size_bytes}, schema={self.schema})")


def _block_meta(block):
    import sys

    if block and isinstance(block[0], dict):
        schema = {k: type(v).__name__ for k, v in block[0].items()}
    elif block:
        schema = type(block[0]).__name__
    else:
        schema = None
    size = builtins.sum(sys.getsizeof(r) for r in block[:64])
    if len(block) > 64:  # extrapolate from the sampled prefix
        size = int(size * len(block) / 64)
    return [len(block), size, schema]


def _block_group(key_fn, agg_fn, on, block):
    # Partials NEVER apply the init seed: a key spanning blocks would
    # absorb it once per block. The seed folds in exactly once, after
    # the final merge (_group_dict_to_rows).
    out = {}
    for row in block:
        k = key_fn(row)
        v = on(row) if on else row
        out[k] = agg_fn(out[k], v) if k in out else v
    return out


def _merge_group_dicts(agg_fn, *dicts):
    out = {}
    for d in dicts:
        for k, v in d.items():
            out[k] = agg_fn(out[k], v) if k in out else v
    return out


class GroupedDataset:
    """``ds.groupby(key)`` → per-key aggregations (reference:
    data/grouped_dataset.py). Hash-combine per block, tree-merge."""

    def __init__(self, ds: "Dataset", key: Callable):
        self._ds = ds
        self._key = key

    def aggregate(self, agg_fn: Callable, *, on: Optional[Callable] = None,
                  init=None) -> "Dataset":
        part = _remote(_block_group)
        partials = [part.remote(self._key, agg_fn, on, b)
                    for b in self._ds._blocks]
        merge = _remote(_merge_group_dicts)
        while len(partials) > 1:  # tree reduce
            nxt = []
            for i in builtins.range(0, len(partials), 4):
                group = partials[i:i + 4]
                nxt.append(merge.remote(agg_fn, *group)
                           if len(group) > 1 else group[0])
            partials = nxt
        items = _remote(_group_dict_to_rows).remote(
            partials[0], agg_fn, init)
        return Dataset([items])

    def count(self) -> "Dataset":
        return self.aggregate(lambda a, b: a + b, on=lambda _: 1)

    def sum(self, on: Optional[Callable] = None) -> "Dataset":
        return self.aggregate(lambda a, b: a + b, on=on)


def _group_dict_to_rows(d, agg_fn=None, init=None):
    if init is not None:
        d = {k: agg_fn(init, v) for k, v in d.items()}
    return sorted(d.items())
