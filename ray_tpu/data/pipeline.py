"""DatasetPipeline: windowed, lazily-executed dataset sequences.

Reference: python/ray/data/dataset_pipeline.py — a pipeline is a
sequence of Datasets (windows); transforms apply per window as it is
consumed, overlapping stage execution with consumption.
"""

from __future__ import annotations

from typing import Callable, Iterator, List


class DatasetPipeline:
    def __init__(self, windows: List):
        self._windows = list(windows)
        self._stages: List[Callable] = []

    def _apply(self, stage: Callable) -> "DatasetPipeline":
        p = DatasetPipeline(self._windows)
        p._stages = self._stages + [stage]
        return p

    def map(self, fn):
        return self._apply(lambda ds: ds.map(fn))

    def map_batches(self, fn, batch_format: str = "native"):
        return self._apply(lambda ds: ds.map_batches(fn, batch_format))

    def filter(self, fn):
        return self._apply(lambda ds: ds.filter(fn))

    def random_shuffle_each_window(self, *, seed=None):
        return self._apply(lambda ds: ds.random_shuffle(seed=seed))

    def repeat(self, times: int) -> "DatasetPipeline":
        p = DatasetPipeline(self._windows * times)
        p._stages = list(self._stages)
        return p

    def iter_datasets(self) -> Iterator:
        for w in self._windows:
            ds = w
            for stage in self._stages:
                ds = stage(ds)
            yield ds

    def iter_rows(self):
        for ds in self.iter_datasets():
            yield from ds.iter_rows()

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "native"):
        for ds in self.iter_datasets():
            yield from ds.iter_batches(batch_size=batch_size,
                                       batch_format=batch_format)

    def take(self, n: int = 20):
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def count(self) -> int:
        return sum(ds.count() for ds in self.iter_datasets())
