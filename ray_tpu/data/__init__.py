"""Distributed datasets over the object store.

Parity target: the reference's ``ray.data`` (reference:
python/ray/data/dataset.py — Dataset :49, map_batches :131,
repartition :305, sort :612; impl/shuffle.py simple_shuffle :16;
impl/arrow_block.py:57 for the columnar block layer). Blocks are
ObjectRefs to COLUMNAR struct-of-numpy-arrays (block.ColumnBlock) with
exact byte sizes and vectorized sort/shuffle/groupby — rows only at
the API edge; non-columnizable rows fall back to plain lists.
``to_jax``/``iter_batches`` feed device-ready arrays.
"""

from ray_tpu.data.block import ColumnBlock  # noqa: F401
from ray_tpu.data.dataset import (  # noqa: F401
    BlockMetadata,
    Dataset,
    GroupedDataset,
)
from ray_tpu.data.pipeline import DatasetPipeline  # noqa: F401
from ray_tpu.data.read_api import (  # noqa: F401
    from_items,
    from_numpy,
    range as range_,  # "range" shadows the builtin; exported as both
    read_binary_files,
    read_csv,
    read_json,
    read_numpy,
    read_parquet,
    read_text,
)

range = range_  # noqa: A001 - mirror ray.data.range
