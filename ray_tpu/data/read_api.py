"""Dataset creation APIs.

Reference: python/ray/data/read_api.py (from_items, range, read_csv,
read_json, read_numpy, read_binary_files) + data/datasource/. Reads
are tasks: one per file (or per range shard), so loading scales with
the cluster.
"""

from __future__ import annotations

import builtins
import csv as _csv
import functools
import glob as _glob
import json as _json
from typing import Any, List, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu.data.block import SCALAR, ColumnBlock, from_rows, rows_of
from ray_tpu.data.dataset import Dataset, _remote


def _expand(paths: Union[str, List[str]]) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        hits = sorted(_glob.glob(p))
        out.extend(hits if hits else [p])
    return out


def from_items(items: List[Any], parallelism: int = 8) -> Dataset:
    n = max(1, min(parallelism, len(items) or 1))
    step, rem = divmod(len(items), n)
    blocks, i = [], 0
    for b in builtins.range(n):  # module defines its own range()
        cnt = step + (1 if b < rem else 0)
        blocks.append(ray_tpu.put(from_rows(items[i:i + cnt])))
        i += cnt
    return Dataset(blocks)


def _gen_range(start, stop):
    return ColumnBlock({SCALAR: np.arange(start, stop)})


def range(n: int, parallelism: int = 8) -> Dataset:  # noqa: A001
    k = max(1, min(parallelism, n or 1))
    step, rem = divmod(n, k)
    blocks, i = [], 0
    r = _remote(_gen_range)
    for b in builtins.range(k):
        cnt = step + (1 if b < rem else 0)
        blocks.append(r.remote(i, i + cnt))
        i += cnt
    return Dataset(blocks)


def from_numpy(arr: np.ndarray, parallelism: int = 8) -> Dataset:
    return from_items(list(arr), parallelism)


# per-file readers (module-level for pickling)

def _read_csv_file(path):
    with open(path, newline="") as f:
        return list(_csv.DictReader(f))


def _read_json_file(path):
    with open(path) as f:
        first = f.read(1)
        f.seek(0)
        if first == "[":
            return _json.load(f)
        return [_json.loads(line) for line in f if line.strip()]


def _read_numpy_file(path):
    return list(np.load(path))


def _read_text_file(path):
    with open(path) as f:
        return [line.rstrip("\n") for line in f]


def _read_binary_file(path):
    with open(path, "rb") as f:
        return [f.read()]


def _read(paths, reader) -> Dataset:
    r = _remote(_columnized_read)
    return Dataset([r.remote(reader, p) for p in _expand(paths)])


def _columnized_read(reader, path):
    """File rows land columnar whenever they columnize (csv/json dicts
    of scalars, numpy/text values); binary and nested rows stay lists."""
    return from_rows(reader(path))


def read_csv(paths) -> Dataset:
    return _read(paths, _read_csv_file)


def read_json(paths) -> Dataset:
    return _read(paths, _read_json_file)


def read_numpy(paths) -> Dataset:
    return _read(paths, _read_numpy_file)


def read_text(paths) -> Dataset:
    return _read(paths, _read_text_file)


def read_binary_files(paths) -> Dataset:
    return _read(paths, _read_binary_file)


# ------------------------------------------------------- parquet (arrow)

def _read_parquet_file(path, columns=None):
    import pyarrow.parquet as pq

    table = pq.read_table(path, columns=columns)
    # rows as dicts (consistent with read_csv), columnized on return
    return from_rows(table.to_pylist())


def read_parquet(paths, *, columns: Optional[List[str]] = None) -> Dataset:
    """One read task per file (reference: data/datasource/
    parquet_datasource.py over pyarrow)."""
    r = _remote(_read_parquet_file)
    return Dataset([r.remote(p, columns) for p in _expand(paths)])


# ---------------------------------------------------------- write APIs

def _write_block(path, fmt, block):
    import os

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if fmt == "parquet":
        import pyarrow as pa
        import pyarrow.parquet as pq

        if isinstance(block, ColumnBlock) and not block.scalar:
            # columnar -> arrow without a row trip
            pq.write_table(pa.table(
                {k: pa.array(v) for k, v in block.cols.items()}), path)
        else:
            pq.write_table(pa.Table.from_pylist(rows_of(block)), path)
    elif fmt == "csv":
        rows = rows_of(block)
        with open(path, "w", newline="") as f:
            if rows and isinstance(rows[0], dict):
                w = _csv.DictWriter(f, fieldnames=list(rows[0]))
                w.writeheader()
                w.writerows(rows)
            else:
                # scalar rows get a "value" header so read_csv
                # (DictReader) round-trips as {"value": ...} rows
                # instead of eating the first row as field names
                w = _csv.writer(f)
                w.writerow(["value"])
                w.writerows([[r] for r in rows])
    elif fmt == "json":
        with open(path, "w") as f:
            for r in rows_of(block):
                f.write(_json.dumps(r) + "\n")
    elif fmt == "numpy":
        if isinstance(block, ColumnBlock) and block.scalar:
            np.save(path, block.cols[SCALAR])
        else:
            np.save(path, np.asarray(rows_of(block)))
    else:
        raise ValueError(f"unknown write format {fmt!r}")
    return path


def _write(ds: Dataset, dir_path: str, fmt: str, ext: str) -> List[str]:
    w = _remote(_write_block)
    return ray_tpu.get([
        w.remote(f"{dir_path}/block_{i:05d}.{ext}", fmt, b)
        for i, b in enumerate(ds._blocks)])


def write_parquet(ds: Dataset, dir_path: str) -> List[str]:
    return _write(ds, dir_path, "parquet", "parquet")


def write_csv(ds: Dataset, dir_path: str) -> List[str]:
    return _write(ds, dir_path, "csv", "csv")


def write_json(ds: Dataset, dir_path: str) -> List[str]:
    return _write(ds, dir_path, "json", "json")


def write_numpy(ds: Dataset, dir_path: str) -> List[str]:
    return _write(ds, dir_path, "numpy", "npy")
