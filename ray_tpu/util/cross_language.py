"""Cross-language task invocation: non-Python clients call registered
Python functions by NAME over the client-server wire protocol.

Parity target: the reference's cross-language layer (reference:
python/ray/cross_language.py java_function/java_actor_class,
src/ray/core_worker/lib/java — functions addressed by descriptor, not
by pickled code). Redesigned for this runtime: a Python driver
registers functions under string names in the cluster KV; any client
that can speak framed msgpack (see ``cpp/`` for the native C++ client)
submits ``CCallNamed`` to the client server, which runs the function
as a normal task and returns the msgpack-encodable result.

Usage (Python side)::

    from ray_tpu.util import cross_language
    cross_language.register("add", lambda a, b: a + b)
    server = ray_tpu.util.client.server.ClientServer()
    addr = server.start()          # give addr to the C++ client

C++ side: ``RayTpuClient c; c.Connect(host, port);
c.CallNamed("add", {1, 2})`` (cpp/ray_tpu_client.hpp).

Arguments and results must be msgpack-native values (nil/bool/int/
float/str/bin/array/map) — the same contract as the reference's
cross-language serialization boundary.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

try:
    import cloudpickle
except ImportError:  # pragma: no cover
    import pickle as cloudpickle

_KV_PREFIX = b"__crosslang__/"


def register(name: str, fn: Callable) -> None:
    """Export ``fn`` cluster-wide under ``name`` for non-Python
    callers. Overwrites any previous registration."""
    import ray_tpu

    ray_tpu.experimental_internal_kv_put(
        _KV_PREFIX + name.encode(), cloudpickle.dumps(fn), overwrite=True)


def unregister(name: str) -> bool:
    import ray_tpu

    return ray_tpu.experimental_internal_kv_del(_KV_PREFIX + name.encode())


def list_registered() -> List[str]:
    import ray_tpu

    return sorted(
        k[len(_KV_PREFIX):].decode()
        for k in ray_tpu.experimental_internal_kv_list(_KV_PREFIX))


def lookup(name: str) -> Optional[Callable]:
    """Fetch + unpickle a registered function (used by the client
    server; results are cached per-process by the caller)."""
    data = lookup_raw(name)
    if data is None:
        return None
    return cloudpickle.loads(data)


def lookup_raw(name: str) -> Optional[bytes]:
    """Fetch the pickled registration bytes without unpickling — lets
    callers cache by content and notice re-``register()`` overwrites."""
    import ray_tpu

    return ray_tpu.experimental_internal_kv_get(_KV_PREFIX + name.encode())


def check_msgpack_value(value: Any) -> bool:
    """True if ``value`` crosses the language boundary losslessly."""
    if value is None or isinstance(value, (bool, int, float, str, bytes)):
        return True
    if isinstance(value, (list, tuple)):
        return all(check_msgpack_value(v) for v in value)
    if isinstance(value, dict):
        return all(isinstance(k, (str, int, bytes))
                   and check_msgpack_value(v) for k, v in value.items())
    return False
