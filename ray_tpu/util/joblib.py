"""joblib backend: scikit-learn workloads fan out over the cluster.

Parity target: the reference's joblib integration
(reference: python/ray/util/joblib/ — register_ray() +
ray_backend.py RayBackend): after ``register_ray()``,
``joblib.parallel_backend("ray_tpu")`` routes every joblib batch
(e.g. a scikit-learn grid search's fits) to cluster tasks instead of
local processes.

Usage::

    import joblib
    from ray_tpu.util.joblib import register_ray

    register_ray()
    with joblib.parallel_backend("ray_tpu"):
        GridSearchCV(...).fit(X, y)
"""

from __future__ import annotations

from typing import Optional

import ray_tpu

_batch_runner = None


def _get_batch_runner():
    """Lazily-decorated remote runner (decorating at import would
    require a connected driver)."""
    global _batch_runner
    if _batch_runner is None:
        @ray_tpu.remote
        def _run_joblib_batch(batch):
            # ``batch`` is joblib's BatchedCalls: a zero-arg callable
            # bundling one or more (fn, args, kwargs) items
            return batch()
        _batch_runner = _run_joblib_batch
    return _batch_runner


def register_ray() -> None:
    """Register the 'ray_tpu' joblib parallel backend."""
    from joblib.parallel import register_parallel_backend

    register_parallel_backend("ray_tpu", RayTpuBackend)


try:
    from joblib.parallel import ParallelBackendBase
except ImportError:  # pragma: no cover — joblib not installed
    ParallelBackendBase = object  # type: ignore[misc,assignment]


class RayTpuBackend(ParallelBackendBase):
    """Future-like joblib backend over the task runtime."""

    supports_retrieve_callback = True
    supports_timeout = True

    def configure(self, n_jobs: int = 1, parallel=None, **backend_kwargs):
        self.parallel = parallel
        return self.effective_n_jobs(n_jobs)

    def effective_n_jobs(self, n_jobs: Optional[int]) -> int:
        if n_jobs in (None, -1, 0):
            try:
                total = ray_tpu.cluster_resources().get("CPU", 1.0)
                return max(1, int(total))
            except Exception:  # noqa: BLE001 — not connected yet
                return 1
        return max(1, int(n_jobs))

    def submit(self, func, callback=None):
        ref = _get_batch_runner().remote(func)
        fut = ref.future()
        if callback is not None:
            fut.add_done_callback(callback)
        return fut

    def retrieve_result_callback(self, out):
        # ``out`` is the future the callback received
        return out.result()

    def abort_everything(self, ensure_ready: bool = True) -> None:
        # tasks already submitted run to completion (at-most-once
        # cancellation is cooperative in this runtime); nothing to tear
        # down — a fresh configure() is always valid
        if ensure_ready:
            self.configure(n_jobs=self.parallel.n_jobs,
                           parallel=self.parallel)
