"""Utility libraries over the core task/actor API.

Parity targets (reference python/ray/util/): ActorPool
(util/actor_pool.py), distributed Queue (util/queue.py),
ParallelIterator (util/iter.py), collective groups
(util/collective/), plus `ray_tpu.train` as the sgd/v2 equivalent.
"""

from ray_tpu.util.actor_pool import ActorPool  # noqa: F401
from ray_tpu.util.placement_group import (  # noqa: F401
    PlacementGroup,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from ray_tpu.util.queue import Empty, Full, Queue  # noqa: F401
from ray_tpu.util.iter import (  # noqa: F401
    ParallelIterator,
    from_items,
    from_iterators,
    from_range,
)
