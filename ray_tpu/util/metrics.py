"""User-defined application metrics.

Parity target: ``ray.util.metrics`` Counter/Gauge/Histogram
(reference: python/ray/util/metrics.py:18). Metrics recorded anywhere
(driver, workers, actors) flow to the GCS and appear on the cluster's
Prometheus endpoint (``ray_tpu.state.metrics_address()``).
"""

from ray_tpu._private.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
)

__all__ = ["Counter", "Gauge", "Histogram"]
