"""Dask-on-ray_tpu: a Dask scheduler that runs graph tasks as tasks.

Parity target: the reference's dask-on-ray scheduler
(reference: python/ray/util/dask/scheduler.py:54 ``ray_dask_get`` — a
drop-in ``scheduler=`` for ``dask.compute`` that executes every Dask
graph task as a Ray task). Re-design: the reference drives submission
through a thread pool + ``dask.local.get_async``; here the runtime's
OWN dependency resolution is the scheduler — each graph task becomes
one ``ray_tpu`` task whose upstream results arrive as ObjectRefs, so
the driver does a single memoized traversal and the cluster executes
the DAG with whatever parallelism the dependency structure allows. No
thread pool, no dask import required (the Dask graph protocol is plain
data: ``{key: (callable, *args) | key-alias | literal}`` with nested
lists/tuples; see dask.core in the public docs).

Use with dask installed::

    import dask
    from ray_tpu.util.dask import ray_dask_get
    dask.compute(obj, scheduler=ray_dask_get)

or call ``ray_dask_get(dsk, keys)`` directly on a raw graph dict.
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List

import ray_tpu


class _Ref:
    """Placeholder for a resolved upstream value: index into the
    flat ref list shipped as the task's real (runtime-resolved)
    arguments."""

    __slots__ = ("i",)

    def __init__(self, i: int):
        self.i = i


def _istask(x) -> bool:
    """Dask task detection (dask.core.istask): a tuple whose first
    element is callable."""
    return isinstance(x, tuple) and bool(x) and callable(x[0])


def _dask_exec(template, *values):
    """Execute one graph task on a worker: substitute resolved
    upstream values, then evaluate the (possibly nested) task tuple
    per Dask semantics."""
    def ev(t):
        if isinstance(t, _Ref):
            return values[t.i]
        if _istask(t):
            return t[0](*[ev(a) for a in t[1:]])
        if isinstance(t, list):
            return [ev(x) for x in t]
        if isinstance(t, tuple):
            return tuple(ev(x) for x in t)
        return t

    return ev(template)


_exec_remote = None


def ray_dask_get(dsk: Dict[Hashable, Any], keys, **kwargs):
    """Compute ``keys`` of the Dask graph ``dsk`` on the cluster.

    ``keys`` may be a single key or (nested) lists of keys, as
    ``dask.compute`` produces; the result mirrors its structure.
    Unrecognized kwargs (dask passes scheduler tuning options like
    ``num_workers``) are accepted and ignored — the runtime schedules.
    """
    global _exec_remote
    if _exec_remote is None:
        _exec_remote = ray_tpu.remote(_dask_exec)

    memo: Dict[Hashable, Any] = {}   # key -> ObjectRef | literal
    visiting: set = set()

    def is_key(x) -> bool:
        try:
            return x in dsk
        except TypeError:
            return False

    def resolve(key):
        if key in memo:
            return memo[key]
        if key in visiting:
            raise ValueError(f"cycle in dask graph at key {key!r}")
        visiting.add(key)
        try:
            memo[key] = build(dsk[key])
        finally:
            visiting.discard(key)
        return memo[key]

    def build(comp):
        """computation -> ObjectRef (submitted task) or literal."""
        if _istask(comp):
            refs: List[Any] = []

            def template_of(t):
                if _istask(t):
                    return (t[0],) + tuple(template_of(a)
                                           for a in t[1:])
                if is_key(t):
                    v = resolve(t)
                    if isinstance(v, ray_tpu.ObjectRef):
                        refs.append(v)
                        return _Ref(len(refs) - 1)
                    return v
                if isinstance(t, list):
                    return [template_of(x) for x in t]
                if isinstance(t, tuple):
                    return tuple(template_of(x) for x in t)
                return t

            template = (comp[0],) + tuple(template_of(a)
                                          for a in comp[1:])
            return _exec_remote.remote(template, *refs)
        if is_key(comp):
            return resolve(comp)
        if isinstance(comp, list):
            built = [build(x) for x in comp]
            if any(isinstance(b, ray_tpu.ObjectRef) for b in built):
                # materialize the list on the cluster so downstream
                # tasks receive plain values
                tmpl: List[Any] = []
                refs = []
                for b in built:
                    if isinstance(b, ray_tpu.ObjectRef):
                        refs.append(b)
                        tmpl.append(_Ref(len(refs) - 1))
                    else:
                        tmpl.append(b)
                return _exec_remote.remote((list, tmpl), *refs)
            return built
        return comp

    # Resolve every requested key, then ONE batched get for all refs
    # (dask.compute passes many partition keys; per-key gets would pay
    # O(N) driver round trips for work the cluster finished already).
    pending: List[Any] = []

    def collect(ks):
        if isinstance(ks, list):
            return [collect(k) for k in ks]
        v = resolve(ks)
        if isinstance(v, ray_tpu.ObjectRef):
            pending.append(v)
            return _Ref(len(pending) - 1)
        return v

    shape = collect(keys)
    values = ray_tpu.get(pending) if pending else []

    def splice(s):
        if isinstance(s, list):
            return [splice(x) for x in s]
        return values[s.i] if isinstance(s, _Ref) else s

    return splice(shape)


def enable_dask_on_ray() -> None:
    """Set ``ray_dask_get`` as dask's default scheduler (requires dask;
    reference: util/dask/__init__.py's enable_dask_on_ray)."""
    try:
        import dask
    except ImportError as e:
        raise ImportError(
            "enable_dask_on_ray requires the `dask` package; "
            "ray_dask_get(dsk, keys) works on raw graphs without it"
        ) from e
    dask.config.set(scheduler=ray_dask_get)
