"""multiprocessing.Pool API over cluster tasks.

Parity target: ``ray.util.multiprocessing.Pool``
(reference: python/ray/util/multiprocessing/pool.py) — drop-in Pool
whose work units run as tasks, so a Pool program scales past one
machine unchanged.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu

_CHUNK = 16


@ray_tpu.remote
def _run_chunk(fn: Callable, chunk: List[Any], star: bool) -> List[Any]:
    if star:
        return [fn(*args) for args in chunk]
    return [fn(a) for a in chunk]


class AsyncResult:
    def __init__(self, refs: List, single: bool = False):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None):
        chunks = ray_tpu.get(self._refs, timeout=timeout)
        out = [v for chunk in chunks for v in chunk]
        return out[0] if self._single else out

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs,
                                num_returns=len(self._refs), timeout=0)
        return len(ready) == len(self._refs)


class Pool:
    """Pool of cluster workers (processes come from the worker pool,
    not from this object — ``processes`` only bounds chunking)."""

    def __init__(self, processes: Optional[int] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self._processes = processes or 0

    def _chunks(self, iterable: Iterable, chunksize: Optional[int]):
        items = list(iterable)
        if chunksize is None:
            if self._processes:
                # spread the work ~4 chunks per "process" slot so
                # stragglers rebalance (same heuristic as stdlib Pool)
                chunksize = max(1, len(items) //
                                (self._processes * 4) or 1)
            else:
                chunksize = _CHUNK
        it = iter(items)
        while True:
            chunk = list(itertools.islice(it, chunksize))
            if not chunk:
                return
            yield chunk

    def _submit(self, fn, iterable, chunksize, star) -> AsyncResult:
        refs = [_run_chunk.remote(fn, chunk, star)
                for chunk in self._chunks(iterable, chunksize)]
        return AsyncResult(refs)

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self._submit(fn, iterable, chunksize, star=False).get()

    def map_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        return self._submit(fn, iterable, chunksize, star=False)

    def starmap(self, fn: Callable, iterable: Iterable,
                chunksize: Optional[int] = None) -> List[Any]:
        return self._submit(fn, iterable, chunksize, star=True).get()

    def apply(self, fn: Callable, args: tuple = (),
              kwargs: Optional[dict] = None) -> Any:
        return self.apply_async(fn, args, kwargs).get()

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwargs: Optional[dict] = None) -> AsyncResult:
        return AsyncResult([_apply.remote(fn, args, kwargs or {})],
                           single=True)

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: Optional[int] = None):
        refs = [_run_chunk.remote(fn, chunk, False)
                for chunk in self._chunks(iterable, chunksize)]
        for ref in refs:
            yield from ray_tpu.get(ref)

    def close(self) -> None:
        pass

    def join(self) -> None:
        pass

    def terminate(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


@ray_tpu.remote
def _apply(fn: Callable, args: tuple, kwargs: dict) -> List[Any]:
    return [fn(*args, **kwargs)]
