"""Distributed task tracing: span context propagated through task
submission, spans exported to the GCS.

Parity target: the reference's OpenTelemetry integration
(reference: python/ray/util/tracing/tracing_helper.py —
``_inject_tracing_into_function`` propagates the caller's span context
inside task metadata; ``_function_span_consumer_name`` names the
server-side span). This implementation is dependency-free: spans are
plain records, the context rides :attr:`TaskSpec.trace_ctx`, and
finished spans are exported to the cluster KV, where
:func:`get_trace` reassembles the tree from any driver. If the real
``opentelemetry`` package is installed, spans are additionally
mirrored to its current tracer (best-effort bridge).

Tracing is OFF by default (zero overhead on the submit hot path
beyond one falsy check); enable with ``RAY_TPU_TRACE=1`` or
:func:`enable`.

Usage::

    from ray_tpu.util import tracing

    tracing.enable()
    with tracing.trace("my pipeline"):
        out = ray_tpu.get(step.remote(x))    # worker spans auto-link

    spans = tracing.get_trace(trace_id)       # the whole tree
    tracing.to_chrome_trace(spans)            # chrome://tracing JSON
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

_KV_PREFIX = b"__traces__/"

_current: contextvars.ContextVar[Optional["Span"]] = \
    contextvars.ContextVar("ray_tpu_span", default=None)
_enabled: Optional[bool] = None


def enabled() -> bool:
    global _enabled
    if _enabled is None:
        _enabled = os.environ.get("RAY_TPU_TRACE", "") not in ("", "0")
    return _enabled


def enable() -> None:
    """Turn tracing on for this process AND future workers (the env var
    propagates through worker spawn)."""
    global _enabled
    _enabled = True
    os.environ["RAY_TPU_TRACE"] = "1"


def disable() -> None:
    global _enabled
    _enabled = False
    os.environ["RAY_TPU_TRACE"] = "0"


@dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    name: str
    kind: str = "internal"          # internal | producer | consumer
    start_ns: int = 0
    end_ns: int = 0
    attributes: Dict[str, Any] = field(default_factory=dict)
    status: str = "ok"

    def to_json(self) -> bytes:
        return json.dumps(self.__dict__, default=str).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "Span":
        return cls(**json.loads(data))


def current_context() -> Optional[Tuple[str, str]]:
    """(trace_id, span_id) of the active span, or None."""
    span = _current.get()
    return (span.trace_id, span.span_id) if span is not None else None


@contextlib.contextmanager
def trace(name: str, kind: str = "internal",
          parent_ctx: Optional[Tuple[str, str]] = None,
          attributes: Optional[Dict[str, Any]] = None):
    """Open a span. Nested ``trace``/task submissions become children.
    Yields the span (its ``trace_id`` is how you fetch the tree)."""
    parent = _current.get()
    if parent_ctx is not None:
        trace_id, parent_id = parent_ctx
    elif parent is not None:
        trace_id, parent_id = parent.trace_id, parent.span_id
    else:
        trace_id, parent_id = uuid.uuid4().hex, None
    span = Span(trace_id=trace_id, span_id=uuid.uuid4().hex[:16],
                parent_id=parent_id, name=name, kind=kind,
                start_ns=time.time_ns(), attributes=attributes or {})
    token = _current.set(span)
    try:
        yield span
    except BaseException as e:
        span.status = f"error: {type(e).__name__}"
        raise
    finally:
        span.end_ns = time.time_ns()
        _current.reset(token)
        _export(span)


def inject_context(attributes: Optional[Dict[str, Any]] = None
                   ) -> Optional[Tuple[str, str]]:
    """What the submit path stamps into TaskSpec.trace_ctx: a producer
    span is recorded for the submission and its context propagated
    (reference: tracing_helper.py _tracing_task_invocation)."""
    if not enabled():
        return None
    ctx = current_context()
    if ctx is None:
        # root: a submission outside any span still gets a trace
        return (uuid.uuid4().hex, "")
    return ctx


@contextlib.contextmanager
def task_execution_span(spec_name: str, task_id_hex: str,
                        trace_ctx: Optional[Tuple[str, str]]):
    """Worker-side consumer span around task execution (reference:
    tracing_helper.py _inject_tracing_into_function's server span).
    No-op when the submission carried no context."""
    if not trace_ctx:
        yield None
        return
    trace_id, parent_id = trace_ctx
    with trace(f"execute {spec_name}", kind="consumer",
               parent_ctx=(trace_id, parent_id or None),
               attributes={"task_id": task_id_hex,
                           "pid": os.getpid()}) as span:
        yield span


# ------------------------------------------------------------- export

def _export(span: Span) -> None:
    """Finished spans go to the cluster KV (fire-and-forget off the
    caller's thread); also mirrored to opentelemetry if present."""
    try:
        import ray_tpu.worker as worker_mod

        w = worker_mod.global_worker
        if w is not None and w.core is not None:
            key = (_KV_PREFIX + span.trace_id.encode() + b"/" +
                   span.span_id.encode())
            w.core.kv_put_nowait(key, span.to_json())
    except Exception:  # noqa: BLE001 — tracing must never break tasks
        pass
    try:  # pragma: no cover - otel not in this environment
        from opentelemetry import trace as otel_trace  # noqa: F401
        # presence-only bridge: real otel exporters pick spans up via
        # their own instrumentation; we avoid double-accounting.
    except ImportError:
        pass


def _spans_under(prefix: bytes) -> List[Span]:
    """All spans stored under ``prefix``, start-time ordered. ONE bulk
    GCS round-trip (KVGetPrefix): a per-key get loop over up to
    tracing_max_spans entries would issue 100k sequential RPCs. Falls
    back to the per-key path where the bulk RPC is unavailable
    (ray:// thin-client cores route the experimental KV API only)."""
    import ray_tpu
    import ray_tpu.worker as worker_mod

    try:
        core = worker_mod._require_connected().core
        reply = core.gcs_call_sync("KVGetPrefix", {"prefix": prefix})
        datas = [v for _k, v in reply.get("pairs", [])]
    except Exception:  # noqa: BLE001 — client mode / old GCS: fall back
        datas = [ray_tpu.experimental_internal_kv_get(key)
                 for key in ray_tpu.experimental_internal_kv_list(prefix)]
    spans = [Span.from_json(data) for data in datas if data]
    spans.sort(key=lambda s: s.start_ns)
    return spans


def get_trace(trace_id: str) -> List[Span]:
    """All exported spans of a trace, start-time ordered."""
    return _spans_under(_KV_PREFIX + trace_id.encode() + b"/")


def all_spans() -> List[Span]:
    """Every exported span across all traces, start-time ordered (the
    timeline export merges these with task states and data-plane
    transfer events — see ray_tpu.state.timeline)."""
    return _spans_under(_KV_PREFIX)


def dropped_span_count() -> int:
    """Spans evicted by the GCS span cap (config ``tracing_max_spans``)
    since cluster start — the honest counter behind oldest-trace
    eviction."""
    import ray_tpu

    raw = ray_tpu.experimental_internal_kv_get(b"__rtpu_trace_dropped__")
    return int(raw) if raw else 0


def clear_trace(trace_id: str) -> int:
    """Delete one trace's spans from the cluster KV. Span storage is
    bounded by the GCS ``tracing_max_spans`` cap (oldest-trace eviction,
    counted by :func:`dropped_span_count`); clearing traces you have
    consumed (or calling :func:`clear_all` periodically) still keeps
    the retained window focused on live work."""
    import ray_tpu

    n = 0
    prefix = _KV_PREFIX + trace_id.encode() + b"/"
    for key in ray_tpu.experimental_internal_kv_list(prefix):
        n += bool(ray_tpu.experimental_internal_kv_del(key))
    return n


def clear_all() -> int:
    """Delete every exported span (see :func:`clear_trace`)."""
    import ray_tpu

    n = 0
    for key in ray_tpu.experimental_internal_kv_list(_KV_PREFIX):
        n += bool(ray_tpu.experimental_internal_kv_del(key))
    return n


def to_chrome_trace(spans: List[Span]) -> List[dict]:
    """chrome://tracing 'X' events (complements the runtime's existing
    profile-event timeline)."""
    return [{
        "name": s.name, "cat": s.kind, "ph": "X",
        "ts": s.start_ns / 1e3, "dur": max(0, s.end_ns - s.start_ns) / 1e3,
        "pid": s.attributes.get("pid", 0), "tid": 0,
        "args": {**s.attributes, "trace_id": s.trace_id,
                 "span_id": s.span_id, "parent_id": s.parent_id,
                 "status": s.status},
    } for s in spans]


# Importing this module ARMS the submit-path trace hook: core_worker's
# _trace_ctx reads one global instead of probing sys.modules per task
# (see core_worker._trace_ctx docstring for the activation contract).
import sys as _sys

from ray_tpu._private import core_worker as _core_worker_mod

_core_worker_mod._tracing_mod = _sys.modules[__name__]
