"""Placement group client API.

Role parity: reference ray.util.placement_group
(reference: python/ray/util/placement_group.py — placement_group(),
PlacementGroup.ready(), remove_placement_group, placement_group_table).
The GCS runs the 2PC prepare/commit against raylets
(ray_tpu/_private/gcs.py handle_create_placement_group); tasks/actors
join a group via the ``placement_group=`` option.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional

from ray_tpu import worker as worker_mod
from ray_tpu._private.ids import PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID,
                 bundles: List[Dict[str, float]]):
        self.id = pg_id
        self.bundle_specs = bundles

    def ready(self, timeout: float = 30.0) -> bool:
        """Block until the group is placed (reference: pg.ready() — there
        it returns an ObjectRef; here it blocks directly).

        The poll loop runs as ONE coroutine on the worker's IO loop
        (asyncio.sleep between GCS calls): a single thread hop for the
        whole wait instead of two per poll, and — because nothing here
        blocks a thread — safe to call from async actors, where the old
        driver-thread time.sleep poll would have stalled the actor's
        event loop via the sync API bridge."""
        w = worker_mod._require_connected()

        async def _poll() -> bool:
            loop = asyncio.get_running_loop()
            deadline = loop.time() + timeout
            while loop.time() < deadline:
                reply, _ = await w.core._gcs_call(
                    "GetPlacementGroup", {"pg_id": self.id.binary()})
                if reply.get("found") and reply["state"] == "CREATED":
                    return True
                if reply.get("found") and reply["state"] == "REMOVED":
                    return False
                await asyncio.sleep(0.05)
            return False

        return w.core._run(_poll())

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def __repr__(self):
        return f"PlacementGroup({self.id.hex()[:12]}, {self.bundle_specs})"


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK",
                    name: str = "") -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"invalid strategy {strategy!r}; "
                         f"must be one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty "
                         "resource dicts")
    w = worker_mod._require_connected()
    pg_id = PlacementGroupID.from_random()
    w.core._run(w.core._gcs_call("CreatePlacementGroup", {
        "pg_id": pg_id.binary(), "bundles": bundles,
        "strategy": strategy, "name": name}))
    return PlacementGroup(pg_id, bundles)


def remove_placement_group(pg: PlacementGroup) -> None:
    w = worker_mod._require_connected()
    w.core._run(w.core._gcs_call(
        "RemovePlacementGroup", {"pg_id": pg.id.binary()}))


def placement_group_table() -> Dict[str, dict]:
    w = worker_mod._require_connected()
    reply, _ = w.core._run(w.core._gcs_call(
        "GetAllPlacementGroups", {}))
    return {PlacementGroupID(p["pg_id"]).hex(): {
        "state": p["state"], "bundles": p["bundles"],
        "strategy": p["strategy"], "name": p.get("name", ""),
    } for p in reply.get("placement_groups", [])}
