"""Pool of actor handles with pipelined task submission.

API parity with the reference's ``ray.util.ActorPool``
(reference: python/ray/util/actor_pool.py): map/map_unordered/submit/
get_next/get_next_unordered/has_next/has_free/push/pop_idle.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List

import ray_tpu


class ActorPool:
    def __init__(self, actors: Iterable[Any]):
        self._idle_actors: List[Any] = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits = []

    def map(self, fn: Callable, values: Iterable):
        """fn(actor, value) → ObjectRef; yields results in order."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def submit(self, fn: Callable, value):
        if self._idle_actors:
            actor = self._idle_actors.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._future_to_actor)

    def has_free(self) -> bool:
        return bool(self._idle_actors) and not self._pending_submits

    def get_next(self, timeout: float | None = None):
        """Next result in submission order."""
        if not self.has_next():
            raise StopIteration("no more results to get")
        future = self._index_to_future[self._next_return_index]
        if timeout is not None:
            ready, _ = ray_tpu.wait([future], timeout=timeout)
            if not ready:
                raise TimeoutError("timed out waiting for result")
        # bookkeeping before get(): a raising task must still return its
        # actor to the pool (reference: ray.util.actor_pool does the same)
        del self._index_to_future[self._next_return_index]
        self._next_return_index += 1
        self._return_actor(self._future_to_actor.pop(future)[1])
        return ray_tpu.get(future)

    def get_next_unordered(self, timeout: float | None = None):
        if not self.has_next():
            raise StopIteration("no more results to get")
        ready, _ = ray_tpu.wait(list(self._future_to_actor),
                                num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("timed out waiting for result")
        future = ready[0]
        i, actor = self._future_to_actor.pop(future)
        del self._index_to_future[i]
        self._return_actor(actor)
        return ray_tpu.get(future)

    def _return_actor(self, actor):
        self._idle_actors.append(actor)
        if self._pending_submits:
            self.submit(*self._pending_submits.pop(0))

    def push(self, actor):
        """Add an idle actor to the pool."""
        self._idle_actors.append(actor)
        if self._pending_submits:
            self.submit(*self._pending_submits.pop(0))

    def pop_idle(self):
        """Remove and return an idle actor, or None."""
        if self.has_free():
            return self._idle_actors.pop()
        return None
