"""ParallelIterator: lazy sharded iterators over actors.

API parity with the reference's ``ray.util.iter``
(reference: python/ray/util/iter.py — ParallelIterator :118,
from_items :30, from_range :54, from_iterators :77): each shard is a
worker actor producing items; transformations (for_each/filter/batch/
flatten) compose lazily per shard; ``gather_sync``/``gather_async``
merge shards on the driver; ``union`` concatenates iterators.
"""

from __future__ import annotations

import functools
import itertools
from typing import Any, Callable, Iterable, List

import ray_tpu

_SENTINEL = "__rtpu_iter_end__"


class _ShardWorker:
    def __init__(self, make_iter):
        self._make = make_iter
        self._ops: List = []
        self._it = None

    def reset(self, ops) -> None:
        """Install this gather's op chain and restart the source.
        Ops live on the ParallelIterator object (not the actor) so
        transformations never mutate iterators sharing these shards."""
        self._ops = list(ops)
        self._it = None

    def _build(self):
        it = iter(self._make())
        for op, fn in self._ops:
            if op == "for_each":
                it = map(fn, it)
            elif op == "filter":
                it = filter(fn, it)
            elif op == "batch":
                it = _batched(it, fn)
            elif op == "flatten":
                it = itertools.chain.from_iterable(it)
        return it

    def next_batch(self, n: int = 1):
        """Pull up to n items; appends the sentinel when exhausted."""
        if self._it is None:
            self._it = self._build()
        out = []
        for _ in range(n):
            try:
                out.append(next(self._it))
            except StopIteration:
                out.append(_SENTINEL)
                break
        return out


def _batched(it, n):
    buf = []
    for x in it:
        buf.append(x)
        if len(buf) >= n:
            yield buf
            buf = []
    if buf:
        yield buf


class ParallelIterator:
    """Transformations are LAZY and local to this object: each
    for_each/filter/... returns a new iterator carrying the op chain;
    the chain is shipped to the shard actors only when a gather starts
    (so sibling iterators over the same shards stay independent —
    concurrent gathers over shared shards are not supported)."""

    def __init__(self, actors: List[Any], name: str = "iter",
                 ops: List | None = None,
                 per_actor_ops: List[List] | None = None):
        self._actors = actors
        self.name = name
        # per_actor_ops[i] = ops baked in before a union; self._ops
        # apply after (to every shard).
        self._per_actor_ops = (per_actor_ops
                               if per_actor_ops is not None
                               else [[] for _ in actors])
        self._ops = list(ops or [])

    @property
    def num_shards(self) -> int:
        return len(self._actors)

    def _apply(self, op: str, fn, name: str) -> "ParallelIterator":
        return ParallelIterator(self._actors, f"{self.name}.{name}",
                                ops=self._ops + [(op, fn)],
                                per_actor_ops=self._per_actor_ops)

    def for_each(self, fn: Callable) -> "ParallelIterator":
        return self._apply("for_each", fn, "for_each()")

    def filter(self, fn: Callable) -> "ParallelIterator":
        return self._apply("filter", fn, "filter()")

    def batch(self, n: int) -> "ParallelIterator":
        return self._apply("batch", n, f"batch({n})")

    def flatten(self) -> "ParallelIterator":
        return self._apply("flatten", None, "flatten()")

    def union(self, other: "ParallelIterator") -> "ParallelIterator":
        return ParallelIterator(
            self._actors + other._actors, f"{self.name}+{other.name}",
            per_actor_ops=(
                [po + self._ops for po in self._per_actor_ops]
                + [po + other._ops for po in other._per_actor_ops]))

    def _reset_all(self):
        return ray_tpu.get([
            a.reset.remote(self._per_actor_ops[i] + self._ops)
            for i, a in enumerate(self._actors)])

    def gather_sync(self, batch: int = 16):
        """Round-robin over shards, in deterministic shard order."""
        self._reset_all()
        live = list(self._actors)
        while live:
            done = []
            for a in live:
                items = ray_tpu.get(a.next_batch.remote(batch))
                for x in items:
                    if isinstance(x, str) and x == _SENTINEL:
                        done.append(a)
                        break
                    yield x
            live = [a for a in live if a not in done]

    def gather_async(self, batch: int = 16):
        """Yield items from whichever shard returns first."""
        self._reset_all()
        pending = {a.next_batch.remote(batch): a for a in self._actors}
        while pending:
            ready, _ = ray_tpu.wait(list(pending), num_returns=1)
            fut = ready[0]
            a = pending.pop(fut)
            items = ray_tpu.get(fut)
            ended = False
            for x in items:
                if isinstance(x, str) and x == _SENTINEL:
                    ended = True
                    break
                yield x
            if not ended:
                pending[a.next_batch.remote(batch)] = a

    def take(self, n: int) -> List[Any]:
        return list(itertools.islice(self.gather_sync(), n))

    def __iter__(self):
        return self.gather_sync()

    def __repr__(self):
        return f"ParallelIterator[{self.name}, shards={self.num_shards}]"


def _make_shards(per_shard_factories, name) -> ParallelIterator:
    worker = ray_tpu.remote(_ShardWorker).options(num_cpus=0)
    actors = [worker.remote(f) for f in per_shard_factories]
    return ParallelIterator(actors, name)


# module-level factories: nested lambdas from an importable module don't
# pickle by value; functools.partial over these does.
def _iter_items(shard):
    return iter(shard)


def _iter_range(i, n, step):
    return iter(range(i, n, step))


def _iter_gen(g):
    return iter(g() if callable(g) else g)


def from_items(items: List[Any], num_shards: int = 2) -> ParallelIterator:
    shards = [items[i::num_shards] for i in range(num_shards)]
    return _make_shards(
        [functools.partial(_iter_items, s) for s in shards],
        f"from_items[{len(items)}]")


def from_range(n: int, num_shards: int = 2) -> ParallelIterator:
    return _make_shards(
        [functools.partial(_iter_range, i, n, num_shards)
         for i in range(num_shards)],
        f"from_range[{n}]")


def from_iterators(generators: List[Iterable],
                   name: str = "from_iterators") -> ParallelIterator:
    return _make_shards(
        [functools.partial(_iter_gen, g) for g in generators], name)
