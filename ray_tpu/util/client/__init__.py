"""Thin-client mode (``ray://``): use a remote cluster without being in it.

Parity target: reference python/ray/util/client/ (design doc
ARCHITECTURE.md, protocol ray_client.proto). ``ray_tpu.init(
address="ray://host:port")`` routes the whole public API through a
ClientCore speaking to a ClientServer proxy that runs as a driver
inside the cluster.
"""

from ray_tpu.util.client.client import ClientCore  # noqa: F401
from ray_tpu.util.client.server import ClientServer  # noqa: F401
