"""ClientCore: the thin-client adapter behind ``ray://`` connections.

Parity target: the reference's client worker
(reference: python/ray/util/client/worker.py — the API-compatible stub
layer every `ray.*` call routes through in client mode). Re-design:
instead of a parallel stub API, ClientCore implements the same method
surface the real CoreWorker exposes to the public layers
(submit_task / create_actor / submit_actor_task / get / put / wait /
kill_actor / function_manager / reference_counter / the _gcs_call
shim), so `worker.py`, `remote_function.py`, and `actor.py` run
UNCHANGED against a remote cluster.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Dict, List, Optional, Sequence

from ray_tpu._private import protocol, rpc
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu.util.client.common import dumps_args

try:
    import cloudpickle
except ImportError:  # pragma: no cover
    import pickle as cloudpickle


class _GcsCallSentinel(tuple):
    """What ClientCore._gcs_call returns; consumed by ClientCore._run."""


class ClientFunctionManager:
    def __init__(self, client: "ClientCore"):
        self._client = client
        self._exported = set()

    def prepare(self, fn):
        pickled = cloudpickle.dumps(fn)
        return hashlib.sha1(pickled).hexdigest(), pickled

    def export_prepickled(self, key: str, pickled: bytes,
                          fn: Any = None) -> None:
        if key in self._exported:
            return
        self._client._call("CFnPut", {"key": key}, bufs=[pickled])
        self._exported.add(key)


class ClientRefCounter:
    """Local counts only; zero → batched release push to the server.

    Releases are deferred through a pending set and flushed ids are
    re-checked against live counts under the lock — a ref re-acquired
    between the zero-crossing and the flush (e.g. the same id arriving
    nested in a get() reply) must not be released out from under the
    new holder."""

    def __init__(self, client: "ClientCore"):
        self._client = client
        self._lock = threading.Lock()
        self._counts: Dict[ObjectID, int] = {}
        self._adds: Dict[ObjectID, int] = {}  # cumulative bookings seen

    def add_local_reference(self, object_id: ObjectID) -> None:
        # Every add corresponds 1:1 to a server-side booking (a reply
        # id or a persistent-id resolve).
        with self._lock:
            self._counts[object_id] = self._counts.get(object_id, 0) + 1
            self._adds[object_id] = self._adds.get(object_id, 0) + 1

    def remove_local_reference(self, object_id: ObjectID) -> None:
        with self._lock:
            n = self._counts.get(object_id, 0) - 1
            if n > 0:
                self._counts[object_id] = n
                return
            self._counts.pop(object_id, None)
            booked = self._adds.pop(object_id, 1)
        # Release exactly the bookings this client consumed: the server
        # decrements a pin count, so a booking from an in-flight reply
        # the client hasn't processed yet survives the release instead
        # of being popped out from under the new holder.
        self._client._release([(object_id.binary(), booked)])


class ClientCore:
    """Connects to a ClientServer; plugs in as ``global_worker.core``."""

    mode = "client"
    task_executor = None  # RuntimeContext.current_actor_id probes this

    def __init__(self, server_address: str):
        self._loop_thread = rpc.EventLoopThread("rtpu-client-io")
        self.loop = self._loop_thread.loop
        self._conn = self._loop_thread.run(
            rpc.connect(server_address, peer_name="client-server"))
        self.function_manager = ClientFunctionManager(self)
        self.reference_counter = ClientRefCounter(self)
        self.address = f"ray-client:{server_address}"
        self.gcs_address = server_address
        # Valid-width ids so get_runtime_context() works in client mode
        # (the nil job id marks "no in-cluster job").
        self.job_id = b"\xff" * 4
        self.worker_id = b"\xff" * 28
        self.node_id = b"\xff" * 28
        self._shutdown = False

    def queue_local_decref(self, object_id: ObjectID) -> None:
        # ObjectRef.__del__ protocol (see core_worker.queue_local_decref);
        # the client releases synchronously — no loop to batch onto.
        self.reference_counter.remove_local_reference(object_id)

    # ------------------------------------------------------------- rpc

    def _call(self, method: str, header: dict, bufs=()):
        return self._loop_thread.run(
            self._conn.call(method, header, bufs=list(bufs)),
            timeout=None)

    def _release(self, id_bytes_list) -> None:
        if self._shutdown:
            return
        try:
            self._loop_thread.call_soon(
                self._conn.push("CRelease", {"ids": id_bytes_list}))
        except Exception:  # noqa: BLE001 — interpreter shutdown
            pass

    def _make_refs(self, ids: List[bytes]) -> List[ObjectRef]:
        refs = []
        for i in ids:
            oid = ObjectID(i)
            self.reference_counter.add_local_reference(oid)
            refs.append(ObjectRef(oid, owner_address="", worker=self,
                                  skip_adding_local_ref=True))
        return refs

    # -------------------------------------------------------- task api

    def submit_task(self, fn_key: str, name: str, args: List[Any],
                    num_returns: int = 1,
                    resources: Optional[Dict[str, float]] = None,
                    max_retries: Optional[int] = None,
                    retry_exceptions: bool = False,
                    placement_group_id: bytes = b"",
                    placement_group_bundle_index: int = -1,
                    scheduling_strategy: str = "DEFAULT",
                    runtime_env: Optional[Dict] = None) -> List[ObjectRef]:
        # fail fast on options the thin client doesn't carry yet,
        # instead of silently running with different semantics
        if placement_group_id or runtime_env or \
                scheduling_strategy != "DEFAULT":
            raise ValueError(
                "placement groups, runtime_env, and non-default "
                "scheduling strategies are not supported over ray:// "
                "client connections")
        reply, _ = self._call("CSubmitTask", {
            "fn_key": fn_key, "name": name, "num_returns": num_returns,
            "resources": resources, "max_retries": max_retries,
            "retry_exceptions": retry_exceptions,
        }, bufs=[dumps_args(list(args))])
        return self._make_refs(reply["ids"])

    def create_actor(self, fn_key: str, name: str, args: List[Any],
                     **opts) -> bytes:
        if opts.pop("placement_group_id", b""):
            raise ValueError("placement groups are not supported over "
                             "ray:// client connections")
        opts.pop("placement_group_bundle_index", None)
        reply, _ = self._call("CCreateActor", {
            "fn_key": fn_key, "name": name, "opts": opts,
        }, bufs=[dumps_args(list(args))])
        return reply["actor_id"]

    def submit_actor_task(self, actor_id: bytes, fn_key: str, name: str,
                          args: List[Any], num_returns: int = 1,
                          max_task_retries: int = 0) -> List[ObjectRef]:
        reply, _ = self._call("CActorCall", {
            "actor_id": actor_id, "fn_key": fn_key, "name": name,
            "num_returns": num_returns,
            "max_task_retries": max_task_retries,
        }, bufs=[dumps_args(list(args))])
        return self._make_refs(reply["ids"])

    # ------------------------------------------------------ object api

    def put(self, value: Any, _owner_ref=None) -> ObjectRef:
        reply, _ = self._call("CPut", {}, bufs=[dumps_args(value)])
        return self._make_refs([reply["id"]])[0]

    def _resolve_incoming(self, kind: str, payload):
        """Values may contain ObjectRefs / ActorHandles (persistent
        ids) — rebuild them as client objects (the server booked them
        during serialization)."""
        from ray_tpu.util.client.common import make_actor_handle

        if kind == "ref":
            return self._make_refs([payload])[0]
        if kind == "actor":
            return make_actor_handle(self, payload)
        raise KeyError(f"unknown persistent id kind {kind!r}")

    def get(self, refs: Sequence[ObjectRef],
            timeout: Optional[float] = None):
        from ray_tpu.util.client.common import loads_args

        reply, bufs = self._call("CGet", {
            "ids": [r.object_id.binary() for r in refs],
            "timeout": timeout})
        if not reply["ok"]:
            raise cloudpickle.loads(bufs[0])
        return [loads_args(b, self._resolve_incoming) for b in bufs]

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None, fetch_local: bool = True):
        by_id = {r.object_id.binary(): r for r in refs}
        reply, _ = self._call("CWait", {
            "ids": [r.object_id.binary() for r in refs],
            "num_returns": num_returns, "timeout": timeout})
        return ([by_id[i] for i in reply["ready"]],
                [by_id[i] for i in reply["not_ready"]])

    # ------------------------------------------------------- actor api

    def kill_actor(self, actor_id: bytes, no_restart: bool = True):
        self._call("CKill", {"actor_id": actor_id,
                             "no_restart": no_restart})

    def cancel(self, ref: ObjectRef, force: bool = False):
        self._call("CCancel", {"id": ref.object_id.binary(),
                               "force": force})

    # ---------------------------------------------- GCS passthrough shim

    def _gcs_call(self, method: str, header=None, bufs=(), timeout=None):
        """NOT a coroutine (unlike CoreWorker's): returns a sentinel the
        paired _run executes — so worker.py's
        ``core._run(core._gcs_call(...))`` idiom works unchanged."""
        return _GcsCallSentinel((method, header, list(bufs)))

    def _run(self, sentinel, timeout=None):
        if not isinstance(sentinel, _GcsCallSentinel):
            raise TypeError(
                "ClientCore._run only executes _gcs_call sentinels")
        method, header, bufs = sentinel
        reply, rbufs = self._call("CGcs", {"method": method,
                                           "header": header}, bufs=bufs)
        return reply, rbufs

    def gcs_call_sync(self, method: str, header: dict) -> dict:
        reply, _ = self._run(self._gcs_call(method, header))
        return reply

    def _kv_put_sync(self, key: bytes, value: bytes):
        self._run(self._gcs_call(
            "KVPut", protocol.KVPutRequest(key=key).to_header(),
            bufs=[value]))

    def _kv_get_sync(self, key: bytes):
        header, bufs = self._run(self._gcs_call(
            "KVGet", protocol.KVGetRequest(key=key).to_header()))
        return bufs[0] if header.get("found") else None

    # ------------------------------------------------------- lifecycle

    def shutdown(self):
        if self._shutdown:
            return
        self._shutdown = True
        try:
            self._loop_thread.run(self._conn.close(), timeout=3)
        except Exception:  # noqa: BLE001
            pass
        self._loop_thread.stop()
