"""Client server: the cluster-side proxy for ``ray://`` clients.

Parity target: the reference's client server / proxier
(reference: python/ray/util/client/server/server.py, proxier.py,
protocol src/ray/protobuf/ray_client.proto). One process connected to
the cluster as a driver serves many thin clients; per-connection state
(object refs, actor handles, exported functions) is dropped — and the
refs released — when a client disconnects.

Handlers run on the driver's IO loop, so every blocking core-worker
call hops to the default executor (the sync API must not run on the
IO loop thread).
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
from typing import Dict

from ray_tpu._private import rpc
from ray_tpu.util.client.common import dumps_args, loads_args

logger = logging.getLogger(__name__)

try:
    import cloudpickle
except ImportError:  # pragma: no cover
    import pickle as cloudpickle


class _ConnState:
    def __init__(self):
        # id -> [ObjectRef, pin_count]: every id the server hands the
        # client (reply ids, persistent ids inside values) adds a pin;
        # client releases carry the number of bookings consumed, so a
        # release can never drop a booking from a reply the client has
        # not processed yet.
        self.refs: Dict[bytes, list] = {}
        self.actors: Dict[bytes, object] = {}     # actor_id -> handle

    def book(self, ref) -> bytes:
        id_bytes = ref.object_id.binary()
        entry = self.refs.get(id_bytes)
        if entry is None:
            self.refs[id_bytes] = [ref, 1]
        else:
            entry[1] += 1
        return id_bytes

    def release(self, id_bytes: bytes, n: int) -> None:
        entry = self.refs.get(id_bytes)
        if entry is not None:
            entry[1] -= n
            if entry[1] <= 0:
                del self.refs[id_bytes]


class ClientServer:
    """Serve thin clients from a process already connected as a driver."""

    def __init__(self):
        self._states: Dict[object, _ConnState] = {}
        self._server = rpc.RpcServer({
            "CFnPut": self.handle_fn_put,
            "CSubmitTask": self.handle_submit_task,
            "CCreateActor": self.handle_create_actor,
            "CActorCall": self.handle_actor_call,
            "CGet": self.handle_get,
            "CPut": self.handle_put,
            "CWait": self.handle_wait,
            "CKill": self.handle_kill,
            "CCancel": self.handle_cancel,
            "CRelease": self.handle_release,
            "CGcs": self.handle_gcs,
            # cross-language entry points (the C++ client in cpp/):
            # call a registered Python function by NAME, put/get
            # msgpack-native objects (ObjectRef = opaque id), and drive
            # NAMED actors — all with msgpack-native values only
            "CCallNamed": self.handle_call_named,
            "CXPut": self.handle_x_put,
            "CXGet": self.handle_x_get,
            "CXActorCall": self.handle_x_actor_call,
            "CPing": self.handle_ping,
        }, name="client-server")
        self._named_fn_cache: Dict[str, object] = {}
        self._server.on_connect.append(
            lambda conn: conn.on_disconnect.append(self._on_disconnect))
        self.address = ""

    def start(self, listen: str = "tcp://127.0.0.1:0") -> str:
        """Blocking start from the driver thread; serves on the
        connected core worker's IO loop."""
        import ray_tpu.worker as worker_mod

        core = worker_mod._require_connected().core
        self._core = core
        self.address = core._run(self._server.listen(listen))
        logger.info("client server listening at %s", self.address)
        return self.address

    def stop(self) -> None:
        self._core._run(self._server.close())

    # ------------------------------------------------------------ state

    def _state(self, conn) -> _ConnState:
        st = self._states.get(conn)
        if st is None:
            st = self._states[conn] = _ConnState()
        return st

    def _on_disconnect(self, conn) -> None:
        # Dropping the maps releases every ObjectRef/handle the client
        # held (their __del__ decrements this driver's refcounts) —
        # the reference's per-client cleanup.
        self._states.pop(conn, None)

    def _resolver(self, st: _ConnState):
        from ray_tpu.util.client.common import make_actor_handle

        def resolve(kind: str, payload):
            if kind == "ref":
                entry = st.refs.get(payload)
                if entry is None:
                    raise KeyError(
                        f"client referenced unknown object "
                        f"{payload.hex()[:16]} (already released?)")
                return entry[0]
            if kind == "actor":
                actor_id = payload[0]
                handle = st.actors.get(actor_id)
                if handle is None:
                    handle = st.actors[actor_id] = make_actor_handle(
                        self._core, payload)
                return handle
            raise KeyError(f"unknown persistent id kind {kind!r}")
        return resolve

    def _resolve_ref(self, st: _ConnState, id_bytes: bytes):
        return self._resolver(st)("ref", id_bytes)

    def _book(self, st: _ConnState, refs) -> list:
        return [st.book(r) for r in refs]

    @staticmethod
    async def _offload(fn):
        """Run a blocking core call off the IO loop."""
        return await asyncio.get_running_loop().run_in_executor(None, fn)

    # ---------------------------------------------------------- handlers

    async def handle_fn_put(self, conn, header, bufs):
        key, pickled = header["key"], bufs[0]
        await self._offload(
            lambda: self._core.function_manager.export_prepickled(
                key, pickled))
        return {}

    async def handle_submit_task(self, conn, header, bufs):
        st = self._state(conn)
        args = loads_args(bufs[0], self._resolver(st))
        refs = await self._offload(lambda: self._core.submit_task(
            fn_key=header["fn_key"], name=header["name"], args=args,
            num_returns=header.get("num_returns", 1),
            resources=header.get("resources") or None,
            max_retries=header.get("max_retries"),
            retry_exceptions=header.get("retry_exceptions", False)))
        return {"ids": self._book(st, refs)}

    async def handle_create_actor(self, conn, header, bufs):
        st = self._state(conn)
        args = loads_args(bufs[0], self._resolver(st))
        actor_id = await self._offload(lambda: self._core.create_actor(
            fn_key=header["fn_key"], name=header["name"], args=args,
            **header.get("opts", {})))
        # hold a handle so per-call handles on the client stay valid
        from ray_tpu.actor import ActorHandle
        st.actors[actor_id] = ActorHandle(
            self._core, actor_id, header["name"], header["fn_key"])
        return {"actor_id": actor_id}

    async def handle_actor_call(self, conn, header, bufs):
        st = self._state(conn)
        args = loads_args(bufs[0], self._resolver(st))
        refs = await self._offload(
            lambda: self._core.submit_actor_task(
                header["actor_id"], header["fn_key"], header["name"],
                args, num_returns=header.get("num_returns", 1),
                max_task_retries=header.get("max_task_retries", 0)))
        return {"ids": self._book(st, refs)}

    async def handle_put(self, conn, header, bufs):
        st = self._state(conn)
        value = loads_args(bufs[0], self._resolver(st))
        ref = await self._offload(lambda: self._core.put(value))
        return {"id": self._book(st, [ref])[0]}

    async def handle_get(self, conn, header, bufs):
        st = self._state(conn)
        refs = [self._resolve_ref(st, i) for i in header["ids"]]
        timeout = header.get("timeout")

        def book(ref):
            # a returned value may CONTAIN ObjectRefs (nested remote
            # calls): book them so the client can use them later
            st.book(ref)

        def book_actor(handle):
            st.actors.setdefault(handle._actor_id, handle)

        try:
            # handlers already run ON the core's IO loop: await the
            # async path directly — an unbounded blocking get would
            # otherwise pin a default-executor thread per waiting
            # client and can starve the loop's executor users
            values = await self._core.get_objects_async(
                refs, timeout=timeout)
            return ({"ok": True},
                    [dumps_args(v, on_ref=book, on_actor=book_actor)
                     for v in values])
        except Exception as e:  # noqa: BLE001 — ship to the client
            # raylint: disable=async-blocking — bounded error reply (one exception object)
            return ({"ok": False}, [cloudpickle.dumps(e)])

    async def handle_wait(self, conn, header, bufs):
        st = self._state(conn)
        refs = [self._resolve_ref(st, i) for i in header["ids"]]
        ready, not_ready = await self._core._wait_async(
            refs, header["num_returns"], header.get("timeout"))
        return {"ready": [r.object_id.binary() for r in ready],
                "not_ready": [r.object_id.binary() for r in not_ready]}

    async def handle_kill(self, conn, header, bufs):
        actor_id = header["actor_id"]
        no_restart = header.get("no_restart", True)
        await self._offload(
            lambda: self._core.kill_actor(actor_id,
                                          no_restart=no_restart))
        self._state(conn).actors.pop(actor_id, None)
        return {}

    async def handle_cancel(self, conn, header, bufs):
        st = self._state(conn)
        ref = self._resolve_ref(st, header["id"])
        force = header.get("force", False)
        await self._offload(lambda: self._core.cancel(ref, force=force))
        return {}

    async def handle_release(self, conn, header, bufs):
        st = self._state(conn)
        for id_bytes, n in header["ids"]:
            st.release(id_bytes, n)
        return {}

    async def handle_gcs(self, conn, header, bufs):
        reply, rbufs = await self._core._gcs_call(
            header["method"], header["header"], bufs=list(bufs))
        return reply, list(rbufs)

    # ------------------------------------------------- cross-language

    async def handle_ping(self, conn, header, bufs):
        return {"ok": True, "server": "ray_tpu"}

    async def handle_call_named(self, conn, header, bufs):
        """Cross-language call: run the function registered under
        ``name`` (ray_tpu.util.cross_language) as a task with
        msgpack-native args; the result must be msgpack-native too."""
        from ray_tpu.util import cross_language

        name = header["name"]
        args = header.get("args") or []
        kwargs = header.get("kwargs") or {}
        import ray_tpu

        # Cache keyed by the pickled registration bytes so a
        # re-register() overwrite (or unregister) takes effect on a
        # live server instead of serving the first-cached function.
        data = await self._offload(lambda: cross_language.lookup_raw(name))
        if data is None:
            self._named_fn_cache.pop(name, None)
            return {"error": f"no function registered as {name!r}"}
        digest = hashlib.sha1(data).digest()
        cached = self._named_fn_cache.get(name)
        if cached is not None and cached[0] == digest:
            remote_fn = cached[1]
        else:
            # unpickling can run arbitrary import-time code — keep it
            # off the IO loop like every other blocking call here
            remote_fn = await self._offload(
                lambda: ray_tpu.remote(cloudpickle.loads(data)))
            self._named_fn_cache[name] = (digest, remote_fn)

        st = self._state(conn)
        try:
            args = self._decode_x_args(st, args)
            kwargs = {k: self._decode_x_arg(st, v)
                      for k, v in kwargs.items()}
        except KeyError as e:
            return {"error": str(e)}

        if header.get("ret_ref"):
            # hand back the ObjectRef (opaque id) instead of the value:
            # the client can pass it to later calls / CXGet it
            def submit():
                return remote_fn.remote(*args, **kwargs)

            try:
                ref = await self._offload(submit)
            except Exception as e:  # noqa: BLE001
                return {"error": f"{type(e).__name__}: {e}"}
            return {"id": self._book(st, [ref])[0]}

        def run():
            ref = remote_fn.remote(*args, **kwargs)
            return ray_tpu.get(ref, timeout=header.get("timeout", 300))

        try:
            value = await self._offload(run)
        except Exception as e:  # noqa: BLE001 — client sees the error
            return {"error": f"{type(e).__name__}: {e}"}
        if not cross_language.check_msgpack_value(value):
            return {"error":
                    f"result of {name!r} is not msgpack-serializable "
                    f"({type(value).__name__})"}
        return {"value": value}

    # ObjectRefs cross the language boundary as one-key maps
    # {"__rtpu_ref__": <28-byte id>} (reference role: cross-language
    # ObjectRef exchange, python/ray/cross_language.py — the id is the
    # only portable representation).
    def _decode_x_arg(self, st: _ConnState, a):
        if isinstance(a, dict) and len(a) == 1 and "__rtpu_ref__" in a:
            return self._resolve_ref(st, self._coerce_id(a["__rtpu_ref__"]))
        return a

    def _decode_x_args(self, st: _ConnState, args):
        return [self._decode_x_arg(st, a) for a in args]

    @staticmethod
    def _coerce_id(id_bytes) -> bytes:
        """Client-controlled ref ids must be bytes before they reach
        the resolver (whose miss path formats them with .hex())."""
        if isinstance(id_bytes, bytes):
            return id_bytes
        raise KeyError(
            f"ObjectRef id must be msgpack bin, got "
            f"{type(id_bytes).__name__}")

    async def handle_x_put(self, conn, header, bufs):
        """msgpack-native put: value -> opaque ObjectRef id, held by
        this connection's booking state until CRelease/disconnect."""
        st = self._state(conn)
        value = header.get("value")
        ref = await self._offload(lambda: self._core.put(value))
        return {"id": self._book(st, [ref])[0]}

    async def handle_x_get(self, conn, header, bufs):
        from ray_tpu.util import cross_language

        st = self._state(conn)
        try:
            ref = self._resolve_ref(st, self._coerce_id(header["id"]))
        except KeyError as e:
            return {"error": str(e)}
        try:
            values = await self._core.get_objects_async(
                [ref], timeout=header.get("timeout", 300))
        except Exception as e:  # noqa: BLE001 — client sees the error
            return {"error": f"{type(e).__name__}: {e}"}
        value = values[0]
        if not cross_language.check_msgpack_value(value):
            return {"error": f"object is not msgpack-serializable "
                             f"({type(value).__name__})"}
        return {"value": value}

    async def handle_x_actor_call(self, conn, header, bufs):
        """Drive a NAMED actor from another language: look the handle
        up by name, invoke a method with msgpack-native args, return
        the msgpack-native result (reference role: cross-language
        actors, python/ray/cross_language.py java_actor_class /
        core_worker/lib/java — here by name over the wire protocol)."""
        from ray_tpu.util import cross_language

        st = self._state(conn)
        name = header["actor_name"]
        method = header["method"]
        namespace = header.get("namespace") or None
        try:
            args = self._decode_x_args(st, header.get("args") or [])
        except KeyError as e:
            return {"error": str(e)}
        import ray_tpu

        def submit():
            # the name lookup is a GCS round trip: cache the resolved
            # handle per connection, dropping it on failure so a
            # restarted/recreated actor re-resolves
            key = ("named", name, namespace)
            handle = st.actors.get(key)
            if handle is None:
                handle = ray_tpu.get_actor(name, namespace=namespace)
                st.actors[key] = handle
            m = getattr(handle, method, None)
            if m is None:
                raise AttributeError(
                    f"actor {name!r} has no method {method!r}")
            return m.remote(*args)

        try:
            ref = await self._offload(submit)
            # blocking gets stay OFF the executor (handle_get's
            # rationale): await the async path on the loop instead of
            # pinning a thread per in-flight actor call
            values = await self._core.get_objects_async(
                [ref], timeout=header.get("timeout", 300))
            value = values[0]
        except Exception as e:  # noqa: BLE001 — client sees the error
            st.actors.pop(("named", name, namespace), None)
            return {"error": f"{type(e).__name__}: {e}"}
        if not cross_language.check_msgpack_value(value):
            return {"error": f"result of {name}.{method} is not "
                             f"msgpack-serializable "
                             f"({type(value).__name__})"}
        return {"value": value}
