"""Shared pickling helpers for the thin client.

Parity target: the reference's client_pickler
(reference: python/ray/util/client/client_pickler.py) — ObjectRefs
cross the wire as persistent ids, resolved against the server-side
per-connection ref table, so refs nested anywhere inside argument
structures round-trip correctly.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Callable, Dict

try:
    import cloudpickle
except ImportError:  # pragma: no cover
    cloudpickle = pickle

from ray_tpu._private.object_ref import ObjectRef


class ClientArgPickler(cloudpickle.Pickler):
    """ObjectRefs become persistent ids (both directions). ``on_ref``
    lets the server book refs it serializes into a reply, so the
    client can use them later."""

    def __init__(self, file, protocol=None,
                 on_ref: Callable[[ObjectRef], None] | None = None):
        super().__init__(file, protocol)
        self._on_ref = on_ref

    def persistent_id(self, obj):
        if isinstance(obj, ObjectRef):
            if self._on_ref is not None:
                self._on_ref(obj)
            return ("ref", obj.object_id.binary())
        return None


class ServerArgUnpickler(pickle.Unpickler):
    """Server side: persistent ids resolve to the connection's refs."""

    def __init__(self, file, resolver: Callable[[bytes], Any]):
        super().__init__(file)
        self._resolver = resolver

    def persistent_load(self, pid):
        kind, id_bytes = pid
        if kind != "ref":
            raise pickle.UnpicklingError(f"unknown persistent id {kind}")
        return self._resolver(id_bytes)


def dumps_args(obj: Any,
               on_ref: Callable[[ObjectRef], None] | None = None) -> bytes:
    buf = io.BytesIO()
    ClientArgPickler(buf, protocol=pickle.HIGHEST_PROTOCOL,
                     on_ref=on_ref).dump(obj)
    return buf.getvalue()


def loads_args(data: bytes, resolver: Callable[[bytes], Any]) -> Any:
    return ServerArgUnpickler(io.BytesIO(data), resolver).load()
