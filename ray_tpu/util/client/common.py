"""Shared pickling helpers for the thin client.

Parity target: the reference's client_pickler
(reference: python/ray/util/client/client_pickler.py) — ObjectRefs and
ActorHandles cross the wire as pickle persistent ids, resolved against
the server-side per-connection tables, so refs/handles nested anywhere
inside argument or value structures round-trip correctly.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Callable, Optional

try:
    import cloudpickle
except ImportError:  # pragma: no cover
    cloudpickle = pickle

from ray_tpu._private.object_ref import ObjectRef


class ClientArgPickler(cloudpickle.Pickler):
    """ObjectRefs / ActorHandles become persistent ids (both
    directions). ``on_ref``/``on_actor`` let the server book objects it
    serializes into a reply, so the client can use them later."""

    def __init__(self, file, protocol=None,
                 on_ref: Optional[Callable] = None,
                 on_actor: Optional[Callable] = None):
        super().__init__(file, protocol)
        self._on_ref = on_ref
        self._on_actor = on_actor

    def persistent_id(self, obj):
        from ray_tpu.actor import ActorHandle

        if isinstance(obj, ObjectRef):
            if self._on_ref is not None:
                self._on_ref(obj)
            return ("ref", obj.object_id.binary())
        if isinstance(obj, ActorHandle):
            if self._on_actor is not None:
                self._on_actor(obj)
            st = obj._serialization_state()
            return ("actor", (st["actor_id"], st["class_name"],
                              st["fn_key"], st["max_task_retries"],
                              tuple(st["method_num_returns"].items())))
        return None


class ServerArgUnpickler(pickle.Unpickler):
    """Persistent ids resolve through ``resolver(kind, payload)``."""

    def __init__(self, file, resolver: Callable[[str, Any], Any]):
        super().__init__(file)
        self._resolver = resolver

    def persistent_load(self, pid):
        kind, payload = pid
        return self._resolver(kind, payload)


def make_actor_handle(core, payload):
    """Rebuild an ActorHandle (either side) from its persistent id."""
    from ray_tpu.actor import ActorHandle

    actor_id, class_name, fn_key, max_task_retries, mnr = payload
    return ActorHandle(core, actor_id, class_name, fn_key,
                       max_task_retries=max_task_retries,
                       method_num_returns=dict(mnr))


def dumps_args(obj: Any, on_ref: Optional[Callable] = None,
               on_actor: Optional[Callable] = None) -> bytes:
    buf = io.BytesIO()
    ClientArgPickler(buf, protocol=pickle.HIGHEST_PROTOCOL,
                     on_ref=on_ref, on_actor=on_actor).dump(obj)
    return buf.getvalue()


def loads_args(data: bytes, resolver: Callable[[str, Any], Any]) -> Any:
    return ServerArgUnpickler(io.BytesIO(data), resolver).load()
