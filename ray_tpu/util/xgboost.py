"""Distributed gradient-boosting on the task/actor runtime.

Parity target: the reference's xgboost/lightgbm integrations
(reference: the xgboost_ray/lightgbm_ray packages surfaced through
ray.util — ``RayDMatrix`` sharding + ``train`` fanning boosting
actors over the cluster; python/ray/util/__init__.py re-exports).
Re-design for this runtime: ``train`` shards the data, runs one
boosting actor per shard, and aggregates by best-of / round-robin
model voting ("bagged boosting") rather than rabit's histogram
AllReduce — the tracker-based collective protocol is xgboost-internal
and adds nothing on a runtime whose own collective layer serves the
JAX path. Each actor trains a REAL ``xgboost.train`` booster when
xgboost is installed; the orchestration (sharding, actor fan-out,
aggregation, prediction) is library-agnostic and tested with an
injected trainer, so CI without xgboost still covers everything but
the library call itself (same policy as the optuna searcher / conda
stub seams).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import ray_tpu


class RayDMatrix:
    """Sharded training data (reference role: xgboost_ray.RayDMatrix).
    Accepts (X, y) arrays or a ``ray_tpu.data.Dataset`` of dict rows
    with a label column."""

    def __init__(self, data, label=None, *, label_column: str = "label"):
        if label is not None:
            self.X = np.asarray(data)
            self.y = np.asarray(label)
        else:  # a Dataset of dict rows
            rows = data.take_all()
            names = [k for k in rows[0] if k != label_column]
            self.X = np.asarray([[r[k] for k in names] for r in rows])
            self.y = np.asarray([r[label_column] for r in rows])
        if len(self.X) != len(self.y):
            raise ValueError("data/label length mismatch")

    def shards(self, n: int) -> List[Tuple[np.ndarray, np.ndarray]]:
        idx = np.array_split(np.arange(len(self.X)), n)
        return [(self.X[i], self.y[i]) for i in idx if len(i)]


def _default_trainer(params: Dict[str, Any], X, y, num_rounds: int):
    """Train one real xgboost booster on a shard (runs in an actor)."""
    try:
        import xgboost as xgb
    except ImportError as e:
        raise ImportError(
            "ray_tpu.util.xgboost.train requires the `xgboost` package "
            "(or pass trainer= for another library)") from e
    dtrain = xgb.DMatrix(X, label=y)
    return xgb.train(params, dtrain, num_boost_round=num_rounds)


class _BoostActor:
    """One shard's trainer (reference role: xgboost_ray RayXGBoostActor)."""

    def __init__(self, trainer: Callable):
        self._trainer = trainer
        self.model = None

    def fit(self, params, X, y, num_rounds):
        self.model = self._trainer(params, X, y, num_rounds)
        return True

    def get_model(self):
        return self.model


class TrainResult:
    """Ensemble of per-shard boosters with mean-prediction voting."""

    def __init__(self, models: Sequence[Any],
                 predict_fn: Optional[Callable] = None):
        self.models = list(models)
        self._predict_fn = predict_fn

    def predict(self, X) -> np.ndarray:
        X = np.asarray(X)
        if self._predict_fn is not None:
            preds = [self._predict_fn(m, X) for m in self.models]
        else:
            import xgboost as xgb

            dm = xgb.DMatrix(X)
            preds = [m.predict(dm) for m in self.models]
        return np.mean(np.stack(preds), axis=0)


def train(params: Dict[str, Any], dtrain: RayDMatrix, *,
          num_rounds: int = 10, num_actors: int = 2,
          trainer: Optional[Callable] = None,
          predict_fn: Optional[Callable] = None) -> TrainResult:
    """Data-parallel boosting: one actor per shard, models ensembled
    (reference API shape: xgboost_ray.train(params, RayDMatrix,
    num_boost_round, ray_params=RayParams(num_actors=N))).

    ``trainer(params, X, y, num_rounds) -> model`` overrides the
    xgboost call (tests inject one; lightgbm users pass a lgb.train
    adapter — the orchestration is identical, matching the reference's
    twin lightgbm_ray package).
    """
    shards = dtrain.shards(num_actors)
    cls = ray_tpu.remote(_BoostActor)
    actors = [cls.remote(trainer or _default_trainer) for _ in shards]
    try:
        ray_tpu.get([a.fit.remote(params, X, y, num_rounds)
                     for a, (X, y) in zip(actors, shards)])
        models = ray_tpu.get([a.get_model.remote() for a in actors])
    finally:
        for a in actors:
            ray_tpu.kill(a)
    return TrainResult(models, predict_fn)
