"""Device-mesh execution backend for ray_tpu.util.collective.

Parity target: the reference's NCCL collective groups
(reference: python/ray/util/collective/collective_group/
nccl_collective_group.py — device-resident allreduce/allgather/
broadcast/reducescatter between ranks). The TPU-native replacement is
NOT a port of NCCL rendezvous: XLA owns the ICI fabric, so the device
work is a jitted ``shard_map`` over a ``jax.sharding.Mesh`` whose
collectives (``lax.psum`` / ``pmin`` / ``pmax`` / ``all_gather``)
compile onto ICI links. Ranks exchange contributions through the
host rendezvous (the object plane every rank already reaches — the
analog of the reference's gloo path), then run the same compiled mesh
reduction, so the arithmetic itself is an XLA collective and the
result lands device-resident.

On a CPU-only worker the same kernels run over the virtual host mesh
(``--xla_force_host_platform_device_count``), which is exactly how the
multi-chip path is validated in tests.
"""

from __future__ import annotations

import math
from functools import lru_cache

import numpy as np

_AXIS = "ranks"


@lru_cache(maxsize=1)
def _mesh():
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()), (_AXIS,))


def device_count() -> int:
    return len(_mesh().devices.ravel())


@lru_cache(maxsize=None)
def _allreduce_fn(op: str):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.collectives import shard_map

    mesh = _mesh()

    def kernel(x):
        # local shard: [1, groups, ...] of the global [n_dev, groups, ...]
        if op == "sum":
            return jax.lax.psum(jnp.sum(x, axis=(0, 1)), _AXIS)[None]
        if op == "min":
            return jax.lax.pmin(jnp.min(x, axis=(0, 1)), _AXIS)[None]
        if op == "max":
            return jax.lax.pmax(jnp.max(x, axis=(0, 1)), _AXIS)[None]
        if op == "product":
            # no lax.pprod: gather shards over the fabric, fold on device
            every = jax.lax.all_gather(x, _AXIS)  # [n_dev, 1, groups, ...]
            return jnp.prod(every, axis=(0, 1, 2))[None]
        raise ValueError(f"unknown reduce op {op!r}")

    return jax.jit(shard_map(
        kernel, mesh=mesh, in_specs=P(_AXIS), out_specs=P(_AXIS)))


def _shard_world(arrays, identity):
    """Stack per-rank arrays and pad the rank axis with the op identity
    to [n_dev, groups, ...] so it shards evenly over the mesh."""
    stacked = np.stack([np.asarray(a) for a in arrays])
    world = stacked.shape[0]
    n_dev = device_count()
    groups = max(1, math.ceil(world / n_dev))
    pad = groups * n_dev - world
    if pad:
        filler = np.full((pad,) + stacked.shape[1:], identity,
                         dtype=stacked.dtype)
        stacked = np.concatenate([stacked, filler])
    return stacked.reshape((n_dev, groups) + stacked.shape[1:]), world


def _identity_for(op: str, dtype: np.dtype):
    """The op's padding identity, representable in ``dtype`` (np.inf
    would silently wrap to INT64_MIN for integer mins)."""
    if op == "sum":
        return 0
    if op == "product":
        return 1
    info = (np.iinfo(dtype) if np.issubdtype(dtype, np.integer)
            else np.finfo(dtype))
    return info.max if op == "min" else info.min


def mesh_reduce(contributions, op: str):
    """Reduce per-rank arrays with a compiled mesh collective: each
    device folds its local slice of ranks, one psum/pmin/pmax finishes
    the tree over the interconnect. Returns the device-resident array."""
    import jax.numpy as jnp

    dtype = np.asarray(contributions[0]).dtype
    shaped, _ = _shard_world(contributions, _identity_for(op, dtype))
    return _allreduce_fn(op)(jnp.asarray(shaped))[0]


@lru_cache(maxsize=1)
def _allgather_fn():
    import jax
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.collectives import shard_map

    mesh = _mesh()

    def kernel(x):  # [1, groups, ...]
        every = jax.lax.all_gather(x, _AXIS)   # [n_dev, 1, groups, ...]
        flat = every.reshape((-1,) + x.shape[2:])  # [n_dev*groups, ...]
        return flat[None]

    return jax.jit(shard_map(
        kernel, mesh=mesh, in_specs=P(_AXIS), out_specs=P(_AXIS)))


def mesh_allgather(contributions) -> list:
    """All-gather via lax.all_gather over the mesh; returns per-rank
    arrays (device-resident)."""
    import jax.numpy as jnp

    shaped, world = _shard_world(contributions, 0)
    flat = _allgather_fn()(jnp.asarray(shaped))[0]
    return [flat[i] for i in range(world)]
