"""Collective communication groups between tasks/actors.

API parity with the reference's ``ray.util.collective``
(reference: python/ray/util/collective/collective.py —
init_collective_group :111, allreduce :244, broadcast :358,
allgather :409, reducescatter :457, send/recv :514+, GroupManager :39).

TPU-native stance (SURVEY.md §5.8): *device* collectives are XLA
collectives over the ICI mesh (``ray_tpu.parallel``) — compiled, not a
runtime service. This module is the **host** backend (the reference's
gloo path): rendezvous through a named coordinator actor, data moving
through the object store. Use it for control-plane sync, param
broadcast between actor trainers, and CPU tensors.

Ordering contract (same as NCCL's): every rank must issue the same
collectives in the same order; each op gets a sequence number and the
coordinator matches contributions by (group, seq).
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu


class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


def _reduce(arrays: List[np.ndarray], op: str) -> np.ndarray:
    out = np.asarray(arrays[0]).copy()
    for a in arrays[1:]:
        a = np.asarray(a)
        if op == ReduceOp.SUM:
            out = out + a
        elif op == ReduceOp.PRODUCT:
            out = out * a
        elif op == ReduceOp.MIN:
            out = np.minimum(out, a)
        elif op == ReduceOp.MAX:
            out = np.maximum(out, a)
        else:
            raise ValueError(f"unknown reduce op {op!r}")
    return out


class _Coordinator:
    """Named ASYNC actor holding per-group rendezvous state: ranks
    park on a server-side Condition instead of client-side polling
    (the reference's NCCL groups rendezvous through a named actor the
    same way, collective.py:39 GroupManager; the 2 ms poll this
    replaces was a latency floor on every collective)."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.rounds: Dict[int, Dict[int, Any]] = {}
        self.complete: Dict[int, Dict[int, Any]] = {}
        self.fetched: Dict[int, int] = {}
        self.mailbox: Dict[tuple, Any] = {}   # (seq, src, dst) → payload
        self.members: set = set()
        self._cond = asyncio.Condition()

    async def join(self, rank: int, world_size: Optional[int] = None) -> int:
        if world_size is not None and world_size != self.world_size:
            if not self.members:
                # stale coordinator left over from a group whose ranks
                # died without leaving: adopt the new group's config
                self.world_size = world_size
                self.rounds.clear()
                self.complete.clear()
                self.fetched.clear()
                self.mailbox.clear()
            else:
                raise RuntimeError(
                    f"collective group already active with world_size="
                    f"{self.world_size}, cannot join with {world_size}")
        self.members.add(rank)
        return len(self.members)

    async def leave(self, rank: int) -> int:
        """Membership ref-count for destroy: only the LAST member's
        destroy_collective_group may kill the coordinator, else ranks
        still mid-collective would hang on a dead actor."""
        self.members.discard(rank)
        return len(self.members)

    async def exchange(self, seq: int, rank: int, payload,
                       timeout: float | None = None):
        """Contribute + wait for the full round in ONE call. Exactly
        world_size calls per seq; the last publishes to ``complete``
        (so late wakers never see a half-gc'd round) and the
        world_size-th fetch garbage-collects. ``timeout=None`` waits
        unboundedly, matching collective semantics (a straggler rank
        mid-compile must not fail the round)."""
        async with self._cond:
            rnd = self.rounds.setdefault(seq, {})
            rnd[rank] = payload
            if len(rnd) >= self.world_size:
                self.complete[seq] = rnd
                self._cond.notify_all()
            waiter = self._cond.wait_for(lambda: seq in self.complete)
            if timeout is None:
                await waiter
            else:
                await asyncio.wait_for(waiter, timeout)
            out = self.complete[seq]
            n = self.fetched.get(seq, 0) + 1
            if n >= self.world_size:
                self.complete.pop(seq, None)
                self.rounds.pop(seq, None)
                self.fetched.pop(seq, None)
            else:
                self.fetched[seq] = n
            return out

    async def p2p_put(self, seq: int, src: int, dst: int, payload) -> None:
        async with self._cond:
            self.mailbox[(seq, src, dst)] = payload
            self._cond.notify_all()

    async def p2p_take(self, seq: int, src: int, dst: int,
                       timeout: float | None = None):
        """Wait server-side for the matching send (unbounded by
        default — see exchange())."""
        key = (seq, src, dst)
        async with self._cond:
            waiter = self._cond.wait_for(lambda: key in self.mailbox)
            if timeout is None:
                await waiter
            else:
                await asyncio.wait_for(waiter, timeout)
            return [self.mailbox.pop(key)]


class _Group:
    def __init__(self, name: str, rank: int, world_size: int, coordinator,
                 backend: str = "host"):
        self.name = name
        self.rank = rank
        self.world_size = world_size
        self.coord = coordinator
        self.backend = backend
        self.seq = 0
        self.p2p_seq: Dict[tuple, int] = {}

    def _next_seq(self) -> int:
        self.seq += 1
        return self.seq

    def _exchange(self, payload) -> Dict[int, Any]:
        seq = self._next_seq()
        try:
            # one RPC: contribute + server-side wait for the round
            return ray_tpu.get(
                self.coord.exchange.remote(seq, self.rank, payload))
        except Exception as e:  # noqa: BLE001 — coordinator died/destroyed
            raise RuntimeError(
                f"collective group {self.name!r} coordinator unavailable "
                f"(group destroyed or coordinator died): {e}") from e


# per-process registry: group name → _Group
_groups: Dict[str, _Group] = {}

_COORD_PREFIX = "rtpu_collective:"


def init_collective_group(world_size: int, rank: int,
                          backend: str = "host",
                          group_name: str = "default") -> None:
    """Declare membership; rank 0's process may pre-create the
    coordinator, otherwise whoever arrives first creates it.

    Backends (reference: collective.py:111 backend param — nccl/gloo):
    ``host``/``object_store`` — rendezvous + numpy reduction on host;
    ``tpu``/``xla``/``device`` — same rendezvous, but the reduction is
    a compiled XLA collective over the local device mesh and the result
    is device-resident (see util/collective/device.py).
    """
    if backend not in ("host", "object_store", "tpu", "xla", "device"):
        raise ValueError(
            f"backend {backend!r} not supported; expected host/"
            f"object_store or tpu/xla/device")
    if group_name in _groups:
        raise RuntimeError(f"group {group_name!r} already initialized")
    name = _COORD_PREFIX + group_name
    coord_cls = ray_tpu.remote(_Coordinator).options(
        num_cpus=0, name=name, get_if_exists=True, lifetime="detached")
    coord = coord_cls.remote(world_size)
    ray_tpu.get(coord.join.remote(rank, world_size))
    _groups[group_name] = _Group(group_name, rank, world_size, coord,
                                 backend=backend)


def destroy_collective_group(group_name: str = "default",
                             force: bool = False) -> None:
    """Drop the local membership; the LAST member to leave kills the
    (detached) coordinator — killing it earlier would strand peers that
    are mid-collective, and leaking it would let a later same-named group
    with a different world size attach to the stale one.

    ``force=True`` kills the coordinator unconditionally — the recovery
    path for groups whose members died without leaving (an owner that
    already tore down every rank, e.g. Trainer.shutdown, uses this)."""
    g = _groups.pop(group_name, None)
    coord = g.coord if g is not None else None
    if coord is None:
        try:
            coord = ray_tpu.get_actor(_COORD_PREFIX + group_name)
        except Exception:  # noqa: BLE001 - not found / not connected
            return
    try:
        if force:
            ray_tpu.kill(coord)
            return
        remaining = ray_tpu.get(coord.leave.remote(g.rank if g else -1))
        if remaining == 0:
            ray_tpu.kill(coord)
    except Exception:  # noqa: BLE001 - already dead
        pass


def get_rank(group_name: str = "default") -> int:
    return _groups[group_name].rank if group_name in _groups else -1


def get_collective_group_size(group_name: str = "default") -> int:
    return (_groups[group_name].world_size
            if group_name in _groups else -1)


def _group(group_name: str) -> _Group:
    if group_name not in _groups:
        raise RuntimeError(
            f"collective group {group_name!r} is not initialized in this "
            "process; call init_collective_group() first")
    return _groups[group_name]


def _is_device_backend(g: _Group) -> bool:
    return g.backend in ("tpu", "xla", "device")


def allreduce(tensor, group_name: str = "default",
              op: str = ReduceOp.SUM) -> np.ndarray:
    g = _group(group_name)
    rnd = g._exchange(np.asarray(tensor))
    contributions = [rnd[r] for r in sorted(rnd)]
    if _is_device_backend(g):
        from ray_tpu.util.collective.device import mesh_reduce
        return mesh_reduce(contributions, op)
    return _reduce(contributions, op)


def allgather(tensor, group_name: str = "default") -> List[np.ndarray]:
    g = _group(group_name)
    rnd = g._exchange(np.asarray(tensor))
    contributions = [rnd[r] for r in sorted(rnd)]
    if _is_device_backend(g):
        from ray_tpu.util.collective.device import mesh_allgather
        return mesh_allgather(contributions)
    return [np.asarray(c) for c in contributions]


def broadcast(tensor, src_rank: int = 0,
              group_name: str = "default") -> np.ndarray:
    g = _group(group_name)
    payload = np.asarray(tensor) if g.rank == src_rank else None
    rnd = g._exchange(payload)
    if _is_device_backend(g):
        import jax.numpy as jnp
        return jnp.asarray(rnd[src_rank])  # device-resident copy
    return np.asarray(rnd[src_rank])


def reducescatter(tensor, group_name: str = "default",
                  op: str = ReduceOp.SUM) -> np.ndarray:
    """Reduce then return this rank's 1/world_size slice (dim 0)."""
    g = _group(group_name)
    rnd = g._exchange(np.asarray(tensor))
    contributions = [rnd[r] for r in sorted(rnd)]
    if _is_device_backend(g):
        from ray_tpu.util.collective.device import mesh_reduce
        full = mesh_reduce(contributions, op)
    else:
        full = _reduce(contributions, op)
    return np.array_split(np.asarray(full), g.world_size,
                          axis=0)[g.rank]


def barrier(group_name: str = "default") -> None:
    _group(group_name)._exchange(None)


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    g = _group(group_name)
    key = (g.rank, dst_rank)
    seq = g.p2p_seq.get(key, 0) + 1
    g.p2p_seq[key] = seq
    ray_tpu.get(g.coord.p2p_put.remote(seq, g.rank, dst_rank,
                                       np.asarray(tensor)))


def recv(src_rank: int, group_name: str = "default") -> np.ndarray:
    g = _group(group_name)
    key = (src_rank, g.rank)
    seq = g.p2p_seq.get(key, 0) + 1
    g.p2p_seq[key] = seq
    got = ray_tpu.get(g.coord.p2p_take.remote(seq, src_rank, g.rank))
    return np.asarray(got[0])
