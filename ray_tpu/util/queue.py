"""Distributed FIFO queue backed by a single ASYNC actor.

API parity with the reference's ``ray.util.queue.Queue``
(reference: python/ray/util/queue.py): put/get with block+timeout,
*_nowait, *_nowait_batch, qsize/empty/full, Empty/Full exceptions.
Blocking put/get wait SERVER-SIDE on an asyncio.Condition inside the
actor (the reference wraps asyncio.Queue the same way) — one RPC per
operation instead of a client-side poll loop, so a blocked consumer
wakes on the producer's notify, not on a 5 ms timer.
"""

from __future__ import annotations

import asyncio
from collections import deque
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    """Async actor: blocking ops park on a Condition in the actor."""

    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self.items: deque = deque()
        self._cond = asyncio.Condition()

    def _has_room(self, n: int = 1) -> bool:
        return self.maxsize <= 0 or len(self.items) + n <= self.maxsize

    async def qsize(self) -> int:
        return len(self.items)

    async def put(self, items: List[Any]) -> int:
        """Append as many as fit; returns how many were accepted."""
        accepted = 0
        async with self._cond:
            for it in items:
                if not self._has_room():
                    break
                self.items.append(it)
                accepted += 1
            if accepted:
                self._cond.notify_all()
        return accepted

    async def put_block(self, item: Any,
                        timeout: Optional[float]) -> bool:
        """Wait (server-side) for room, then append. False on timeout.
        The predicate is checked BEFORE the timeout applies — timeout=0
        with room available succeeds (stdlib queue semantics)."""
        async with self._cond:
            if not self._has_room():
                if timeout is not None and timeout <= 0:
                    return False
                try:
                    await asyncio.wait_for(
                        self._cond.wait_for(self._has_room), timeout)
                except asyncio.TimeoutError:
                    return False
            self.items.append(item)
            self._cond.notify_all()
            return True

    async def put_all_or_nothing(self, items: List[Any]) -> bool:
        """Atomic batch put: accept every item or none (a partial accept
        would duplicate the accepted prefix when the caller retries)."""
        async with self._cond:
            if not self._has_room(len(items)):
                return False
            self.items.extend(items)
            self._cond.notify_all()
            return True

    async def get(self, n: int = 1) -> List[Any]:
        out = []
        async with self._cond:
            while self.items and len(out) < n:
                out.append(self.items.popleft())
            if out:
                self._cond.notify_all()
        return out

    async def get_block(self, timeout: Optional[float]):
        """Wait (server-side) for an item. None on timeout. The
        predicate is checked BEFORE the timeout applies — timeout=0
        with items present succeeds (stdlib queue semantics)."""
        async with self._cond:
            if not self.items:
                if timeout is not None and timeout <= 0:
                    return None
                try:
                    await asyncio.wait_for(
                        self._cond.wait_for(lambda: bool(self.items)),
                        timeout)
                except asyncio.TimeoutError:
                    return None
            item = self.items.popleft()
            self._cond.notify_all()
            return [item]

    async def get_exact(self, n: int):
        """All-or-nothing batch take (atomic server-side)."""
        async with self._cond:
            if len(self.items) < n:
                return None
            out = [self.items.popleft() for _ in range(n)]
            self._cond.notify_all()
            return out


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0)
        self.maxsize = maxsize
        self.actor = ray_tpu.remote(_QueueActor).options(**opts).remote(
            maxsize)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        return self.maxsize > 0 and self.qsize() >= self.maxsize

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if not block:
            if ray_tpu.get(self.actor.put.remote([item])) != 1:
                raise Full
            return
        if not ray_tpu.get(self.actor.put_block.remote(item, timeout)):
            raise Full

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def put_nowait_batch(self, items: List[Any]) -> None:
        items = list(items)
        if not ray_tpu.get(self.actor.put_all_or_nothing.remote(items)):
            raise Full(f"{len(items)} items do not fit "
                       f"(maxsize={self.maxsize})")

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        if not block:
            got = ray_tpu.get(self.actor.get.remote(1))
            if not got:
                raise Empty
            return got[0]
        got = ray_tpu.get(self.actor.get_block.remote(timeout))
        if got is None:
            raise Empty
        return got[0]

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        got = ray_tpu.get(self.actor.get_exact.remote(num_items))
        if got is None:
            raise Empty(f"queue has fewer than {num_items} items")
        return got

    def shutdown(self) -> None:
        ray_tpu.kill(self.actor)
