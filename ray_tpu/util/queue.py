"""Distributed FIFO queue backed by a single actor.

API parity with the reference's ``ray.util.queue.Queue``
(reference: python/ray/util/queue.py): put/get with block+timeout,
*_nowait, *_nowait_batch, qsize/empty/full, Empty/Full exceptions.
The queue actor is polled rather than long-blocked so a sync actor
suffices; poll interval 5 ms.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, List, Optional

import ray_tpu

_POLL_S = 0.005


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self.items: deque = deque()

    def qsize(self) -> int:
        return len(self.items)

    def put(self, items: List[Any]) -> int:
        """Append as many as fit; returns how many were accepted."""
        accepted = 0
        for it in items:
            if self.maxsize > 0 and len(self.items) >= self.maxsize:
                break
            self.items.append(it)
            accepted += 1
        return accepted

    def put_all_or_nothing(self, items: List[Any]) -> bool:
        """Atomic batch put: accept every item or none (a partial accept
        would duplicate the accepted prefix when the caller retries)."""
        if self.maxsize > 0 and len(self.items) + len(items) > self.maxsize:
            return False
        self.items.extend(items)
        return True

    def get(self, n: int = 1) -> List[Any]:
        out = []
        while self.items and len(out) < n:
            out.append(self.items.popleft())
        return out

    def get_exact(self, n: int):
        """All-or-nothing batch take (atomic server-side)."""
        if len(self.items) < n:
            return None
        return [self.items.popleft() for _ in range(n)]


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0)
        self.maxsize = maxsize
        self.actor = ray_tpu.remote(_QueueActor).options(**opts).remote(
            maxsize)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        return self.maxsize > 0 and self.qsize() >= self.maxsize

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if ray_tpu.get(self.actor.put.remote([item])) == 1:
                return
            if not block:
                raise Full
            if deadline is not None and time.monotonic() >= deadline:
                raise Full
            time.sleep(_POLL_S)

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def put_nowait_batch(self, items: List[Any]) -> None:
        items = list(items)
        if not ray_tpu.get(self.actor.put_all_or_nothing.remote(items)):
            raise Full(f"{len(items)} items do not fit "
                       f"(maxsize={self.maxsize})")

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            got = ray_tpu.get(self.actor.get.remote(1))
            if got:
                return got[0]
            if not block:
                raise Empty
            if deadline is not None and time.monotonic() >= deadline:
                raise Empty
            time.sleep(_POLL_S)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        got = ray_tpu.get(self.actor.get_exact.remote(num_items))
        if got is None:
            raise Empty(f"queue has fewer than {num_items} items")
        return got

    def shutdown(self) -> None:
        ray_tpu.kill(self.actor)
