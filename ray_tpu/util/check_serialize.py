"""Serializability inspection: find WHY an object won't pickle.

Parity target: ``ray.util.check_serialize.inspect_serializability``
(reference: python/ray/util/check_serialize.py) — walk an object's
closure/attribute graph and report the leaf members that fail, instead
of one opaque pickling error.
"""

from __future__ import annotations

import inspect
from typing import Any, Set, Tuple

try:
    import cloudpickle
except ImportError:  # pragma: no cover
    import pickle as cloudpickle


class FailureTuple:
    def __init__(self, obj: Any, name: str, parent: Any):
        self.obj = obj
        self.name = name
        self.parent = parent

    def __repr__(self):
        return f"FailureTuple({self.name} [obj={self.obj!r}])"


def _serializable(obj: Any) -> bool:
    try:
        cloudpickle.dumps(obj)
        return True
    except Exception:  # noqa: BLE001 — that's the question being asked
        return False


def _walk(obj: Any, name: str, parent: Any, failures: list,
          seen: Set[int], depth: int) -> None:
    if id(obj) in seen:
        return
    seen.add(id(obj))
    if depth > 4:
        # too deep to keep walking — still record THIS node so the
        # caller always gets at least one named failure (seen-marked
        # above: a shared deep object reports once, not once per path)
        failures.append(FailureTuple(obj, name, parent))
        return
    if _serializable(obj):
        return

    children: list = []
    if inspect.isfunction(obj):
        if obj.__closure__:
            children += [
                (f"{name}.<closure>.{v}", c.cell_contents)
                for v, c in zip(obj.__code__.co_freevars, obj.__closure__)
            ]
        children += [(f"{name}.<globals>.{k}", v)
                     for k, v in obj.__globals__.items()
                     if k in obj.__code__.co_names]
    elif hasattr(obj, "__dict__") and isinstance(obj.__dict__, dict):
        children += [(f"{name}.{k}", v) for k, v in obj.__dict__.items()]
    elif isinstance(obj, (list, tuple, set)):
        children += [(f"{name}[{i}]", v) for i, v in enumerate(obj)]
    elif isinstance(obj, dict):
        children += [(f"{name}[{k!r}]", v) for k, v in obj.items()]

    found_deeper = False
    for child_name, child in children:
        if not _serializable(child):
            found_deeper = True
            _walk(child, child_name, obj, failures, seen, depth + 1)
    if not found_deeper:
        failures.append(FailureTuple(obj, name, parent))


def inspect_serializability(obj: Any, name: str = None
                            ) -> Tuple[bool, Set[FailureTuple]]:
    """→ (is_serializable, failure_set). Failures name the deepest
    unpicklable members reachable from ``obj``."""
    name = name or getattr(obj, "__name__", repr(obj)[:40])
    if _serializable(obj):
        return True, set()
    failures: list = []
    _walk(obj, name, None, failures, set(), 0)
    return False, set(failures)
