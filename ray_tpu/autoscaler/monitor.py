"""Monitor: the daemon loop driving the autoscaler from GCS state.

Parity target: the reference's Monitor daemon
(reference: python/ray/autoscaler/_private/monitor.py:87 — polls load
from the GCS, calls StandardAutoscaler.update()). Runs as a thread in
whatever process wants scaling (the driver, or a head-node sidecar);
it speaks plain GCS RPC, so it works against any cluster.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ray_tpu.autoscaler.autoscaler import (
    AutoscalerConfig, LoadMetrics, StandardAutoscaler,
)
from ray_tpu.autoscaler.node_provider import NodeProvider

logger = logging.getLogger(__name__)


class Monitor:
    def __init__(self, provider: NodeProvider,
                 config: Optional[AutoscalerConfig] = None,
                 poll_interval_s: float = 1.0):
        self.autoscaler = StandardAutoscaler(
            provider, config or AutoscalerConfig())
        self.poll_interval_s = poll_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Monitor":
        self._thread = threading.Thread(
            target=self._run, name="rtpu-autoscaler", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        from ray_tpu import worker as worker_mod

        consecutive_failures = 0
        while not self._stop.wait(self.poll_interval_s):
            try:
                core = worker_mod._require_connected().core
                reply = core.gcs_call_sync("GetNodeStatsSummary", {})
                metrics = LoadMetrics.from_node_stats(
                    reply.get("nodes", []))
                self.autoscaler.update(metrics)
                consecutive_failures = 0
            except Exception:  # noqa: BLE001 — keep the daemon alive
                consecutive_failures += 1
                # a persistently failing autoscaler must be VISIBLE,
                # but not once per tick
                if consecutive_failures in (1, 10) or \
                        consecutive_failures % 100 == 0:
                    logger.warning(
                        "autoscaler tick failed (%d consecutive)",
                        consecutive_failures, exc_info=True)
