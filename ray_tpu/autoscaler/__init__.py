"""Autoscaler: demand-driven cluster elasticity.

Parity target: reference python/ray/autoscaler/ (StandardAutoscaler
autoscaler.py:67, Monitor monitor.py:87, NodeProvider plugins, tested
through a mock provider in python/ray/tests/test_autoscaler.py).
"""

from ray_tpu.autoscaler.autoscaler import (  # noqa: F401
    AutoscalerConfig,
    LoadMetrics,
    StandardAutoscaler,
)
from ray_tpu.autoscaler.monitor import Monitor  # noqa: F401
from ray_tpu.autoscaler.node_provider import (  # noqa: F401
    FakeNodeProvider,
    LocalSubprocessProvider,
    NodeProvider,
)
