"""StandardAutoscaler: demand-driven worker-node scaling.

Parity target: the reference's StandardAutoscaler + LoadMetrics +
resource_demand_scheduler (reference:
python/ray/autoscaler/_private/autoscaler.py:67, load_metrics.py:66,
resource_demand_scheduler.py:49). Demand comes from the GCS's per-node
heartbeat stats (pending lease count + resource occupancy); the policy
is deliberately simple and fully unit-testable through the
NodeProvider seam:

* scale UP when leases are pending or CPUs are saturated, by
  ``upscaling_speed`` × current size (at least 1), bounded by
  ``max_workers``;
* scale DOWN a provider node that has been idle (no busy CPUs, no
  pending leases) for ``idle_timeout_s``, bounded by ``min_workers``.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import time
from typing import Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class AutoscalerConfig:
    min_workers: int = 0
    max_workers: int = 4
    cpus_per_worker: int = 1
    idle_timeout_s: float = 10.0
    upscaling_speed: float = 1.0


@dataclasses.dataclass
class LoadMetrics:
    """One snapshot of cluster load (from GCS node stats)."""
    pending_leases: int = 0
    cpus_total: float = 0.0
    cpus_used: float = 0.0
    # node_name → is the node fully idle right now
    idle_by_name: Dict[str, bool] = dataclasses.field(default_factory=dict)

    @classmethod
    def from_node_stats(cls, nodes: List[dict]) -> "LoadMetrics":
        m = cls()
        for n in nodes:
            if not n.get("alive"):
                continue
            stats = n.get("stats", {})
            m.pending_leases += stats.get("num_pending_leases", 0)
            total = n.get("resources_total", {}).get("CPU", 0.0)
            avail = n.get("resources_available", {}).get("CPU", 0.0)
            m.cpus_total += total
            m.cpus_used += total - avail
            name = n.get("node_name", "")
            m.idle_by_name[name] = (
                total == avail and
                stats.get("num_pending_leases", 0) == 0)
        return m


class StandardAutoscaler:
    def __init__(self, provider: NodeProvider, config: AutoscalerConfig):
        self.provider = provider
        self.config = config
        self._idle_since: Dict[str, float] = {}

    def update(self, metrics: LoadMetrics,
               now: Optional[float] = None) -> None:
        """One reconcile tick. ``now`` injectable for tests."""
        now = time.time() if now is None else now
        cfg = self.config
        nodes = self.provider.non_terminated_nodes()

        # ---- scale up ----
        if len(nodes) < cfg.min_workers:
            for _ in range(cfg.min_workers - len(nodes)):
                self._launch()
            return
        saturated = (metrics.cpus_total > 0 and
                     metrics.cpus_used >= metrics.cpus_total)
        if metrics.pending_leases > 0 or saturated:
            by_demand = math.ceil(
                metrics.pending_leases / max(1, cfg.cpus_per_worker))
            by_speed = max(1, int(cfg.upscaling_speed *
                                  max(1, len(nodes))))
            want_new = min(max(1, min(by_demand or 1, by_speed)),
                           cfg.max_workers - len(nodes))
            for _ in range(max(0, want_new)):
                self._launch()
            if want_new > 0:
                logger.info("autoscaler: +%d worker nodes "
                            "(pending=%d, cpus %g/%g)", want_new,
                            metrics.pending_leases, metrics.cpus_used,
                            metrics.cpus_total)
            return

        # ---- scale down ----
        live = set(nodes)
        for stale in [n for n in self._idle_since if n not in live]:
            del self._idle_since[stale]  # crashed/externally removed
        remaining = len(nodes)
        for nid in nodes:
            if remaining <= cfg.min_workers:
                break
            if metrics.idle_by_name.get(nid, False):
                since = self._idle_since.setdefault(nid, now)
                if now - since >= cfg.idle_timeout_s:
                    logger.info("autoscaler: terminating idle node %s",
                                nid)
                    self.provider.terminate_node(nid)
                    self._idle_since.pop(nid, None)
                    remaining -= 1
            else:
                self._idle_since.pop(nid, None)

    def _launch(self) -> None:
        self.provider.create_node(self.config.cpus_per_worker)
