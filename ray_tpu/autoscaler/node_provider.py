"""NodeProvider: the pluggable boundary between scaling logic and infra.

Parity target: the reference's NodeProvider plugin interface + the
MockProvider test seam (reference: python/ray/autoscaler/node_provider.py,
python/ray/tests/test_autoscaler.py MockProvider). Two built-ins:

* ``FakeNodeProvider`` — records create/terminate calls; the unit-test
  seam (no processes).
* ``LocalSubprocessProvider`` — real elasticity on one host: each
  "node" is a ``python -m ray_tpu._private.node`` worker subprocess
  joining the cluster's GCS (the analog of the reference's local/
  on-prem provider).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional


class NodeProvider:
    """Interface. Node ids are opaque strings."""

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def create_node(self, num_cpus: int,
                    resources: Optional[Dict[str, float]] = None) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def node_resources(self, node_id: str) -> Dict[str, float]:
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """In-memory provider for tests: records every call."""

    def __init__(self, cpus_per_node: int = 2):
        self.cpus_per_node = cpus_per_node
        self._next = 0
        self.nodes: Dict[str, Dict[str, float]] = {}
        self.created: List[str] = []
        self.terminated: List[str] = []

    def non_terminated_nodes(self) -> List[str]:
        return list(self.nodes)

    def create_node(self, num_cpus: int, resources=None) -> str:
        self._next += 1
        nid = f"fake-{self._next}"
        self.nodes[nid] = {"CPU": float(num_cpus), **(resources or {})}
        self.created.append(nid)
        return nid

    def terminate_node(self, node_id: str) -> None:
        self.nodes.pop(node_id, None)
        self.terminated.append(node_id)

    def node_resources(self, node_id: str) -> Dict[str, float]:
        return dict(self.nodes.get(node_id, {}))


class LocalSubprocessProvider(NodeProvider):
    """Real worker-node subprocesses joining an existing GCS."""

    def __init__(self, gcs_address: str, cpus_per_node: int = 1):
        self.gcs_address = gcs_address
        self.cpus_per_node = cpus_per_node
        self._procs: Dict[str, subprocess.Popen] = {}
        self._next = 0

    def non_terminated_nodes(self) -> List[str]:
        for nid, proc in list(self._procs.items()):
            if proc.poll() is not None:
                del self._procs[nid]
        return list(self._procs)

    def create_node(self, num_cpus: int, resources=None) -> str:
        self._next += 1
        nid = f"auto-{os.getpid()}-{self._next}"
        cmd = [sys.executable, "-m", "ray_tpu._private.node",
               "--gcs-address", self.gcs_address,
               "--num-cpus", str(num_cpus),
               "--node-name", nid]
        if resources:
            cmd += ["--resources",
                    ",".join(f"{k}={v}" for k, v in resources.items())]
        self._procs[nid] = subprocess.Popen(
            cmd, start_new_session=True, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        return nid

    def terminate_node(self, node_id: str) -> None:
        proc = self._procs.pop(node_id, None)
        if proc is None:
            return
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            proc.terminate()
        deadline = time.time() + 5
        while time.time() < deadline and proc.poll() is None:
            # raylint: disable=async-blocking — autoscaler thread waiting on SIGTERM of a local child
            time.sleep(0.05)
        if proc.poll() is None:
            proc.kill()

    def node_resources(self, node_id: str) -> Dict[str, float]:
        return {"CPU": float(self.cpus_per_node)}

    def shutdown(self) -> None:
        for nid in list(self._procs):
            self.terminate_node(nid)
