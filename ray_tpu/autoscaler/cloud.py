"""Cloud NodeProviders: AWS / GCP (TPU VMs) / Kubernetes.

Parity target: the reference's cloud provider plugins
(reference: python/ray/autoscaler/_private/aws/node_provider.py,
_private/gcp/node_provider.py, _private/_kubernetes/node_provider.py)
— tag-scoped instance discovery, create-from-template with a startup
command that joins the cluster, and idempotent termination.

TPU-first notes: ``GCPNodeProvider`` is the pod bring-up path — its
node config can name a TPU accelerator type, and the startup script
joins the worker to the head's GCS over DCN (``python -m ray_tpu start
--address ...``); ICI-mesh topology inside the slice is the job of the
training libraries, not the autoscaler.

Cloud SDK clients are INJECTED (constructor argument). The default
factory imports the real SDK (boto3 / googleapiclient / kubernetes)
and raises a clear error when it isn't installed; tests inject fakes —
the same seam the reference uses for its moto/mock-based provider
tests (reference: python/ray/tests/test_autoscaler.py MockProvider
strategy applied to real provider logic).
"""

from __future__ import annotations

import copy
import time
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

# Tag keys (reference: ray-cluster-name / ray-node-type tag scheme).
TAG_CLUSTER = "ray-tpu-cluster"
TAG_NODE_KIND = "ray-tpu-node-kind"
KIND_WORKER = "worker"


def default_start_command(gcs_address: str, num_cpus: int,
                          resources: Optional[Dict[str, float]] = None
                          ) -> str:
    """The join-the-cluster command baked into instance startup
    (reference: the ray start invocation in the autoscaler YAML's
    worker_start_ray_commands)."""
    cmd = (f"python -m ray_tpu start --address {gcs_address} "
           f"--num-cpus {num_cpus}")
    if resources:
        pairs = ",".join(f"{k}={v}" for k, v in sorted(resources.items()))
        cmd += f" --resources {pairs}"
    return cmd


class AWSNodeProvider(NodeProvider):
    """EC2-backed workers (reference:
    _private/aws/node_provider.py AWSNodeProvider — run_instances with
    cluster tags, DescribeInstances filtered by tag + state,
    terminate_instances)."""

    def __init__(self, cluster_name: str, gcs_address: str,
                 node_config: Dict[str, Any], ec2=None):
        self.cluster_name = cluster_name
        self.gcs_address = gcs_address
        # e.g. {"ImageId": ..., "InstanceType": "m5.16xlarge",
        #       "SubnetId": ..., "KeyName": ...}
        self.node_config = dict(node_config)
        self._ec2 = ec2 if ec2 is not None else self._real_client()
        self._resources: Dict[str, Dict[str, float]] = {}

    @staticmethod
    def _real_client():
        try:
            import boto3  # type: ignore
        except ImportError as e:
            raise RuntimeError(
                "AWSNodeProvider needs boto3 (not bundled); pass ec2= "
                "explicitly or install boto3") from e
        return boto3.client("ec2")

    def non_terminated_nodes(self) -> List[str]:
        reply = self._ec2.describe_instances(Filters=[
            {"Name": f"tag:{TAG_CLUSTER}", "Values": [self.cluster_name]},
            {"Name": "instance-state-name",
             "Values": ["pending", "running"]},
        ])
        out = []
        for res in reply.get("Reservations", []):
            for inst in res.get("Instances", []):
                out.append(inst["InstanceId"])
        return out

    def create_node(self, num_cpus: int, resources=None) -> str:
        cfg = copy.deepcopy(self.node_config)
        cfg.setdefault("MinCount", 1)
        cfg.setdefault("MaxCount", 1)
        cfg["UserData"] = "#!/bin/bash\n" + default_start_command(
            self.gcs_address, num_cpus, resources)
        tags = [{"Key": TAG_CLUSTER, "Value": self.cluster_name},
                {"Key": TAG_NODE_KIND, "Value": KIND_WORKER}]
        cfg["TagSpecifications"] = [
            {"ResourceType": "instance", "Tags": tags}]
        reply = self._ec2.run_instances(**cfg)
        nid = reply["Instances"][0]["InstanceId"]
        self._resources[nid] = {"CPU": float(num_cpus),
                                **(resources or {})}
        return nid

    def terminate_node(self, node_id: str) -> None:
        try:
            self._ec2.terminate_instances(InstanceIds=[node_id])
        except Exception:  # noqa: BLE001 — already gone: idempotent
            pass
        self._resources.pop(node_id, None)

    def node_resources(self, node_id: str) -> Dict[str, float]:
        return dict(self._resources.get(node_id, {"CPU": 1.0}))


class GCPNodeProvider(NodeProvider):
    """GCE / Cloud-TPU-VM workers (reference:
    _private/gcp/node_provider.py GCPNodeProvider — labeled instances,
    insert with metadata startup-script, delete). A node_config with
    ``acceleratorType`` (e.g. v4-8) provisions TPU VMs — the path to a
    real TPU-pod cluster bring-up."""

    def __init__(self, cluster_name: str, gcs_address: str,
                 project: str, zone: str, node_config: Dict[str, Any],
                 compute=None):
        self.cluster_name = cluster_name
        self.gcs_address = gcs_address
        self.project = project
        self.zone = zone
        self.node_config = dict(node_config)
        self._compute = compute if compute is not None \
            else self._real_client()
        self._resources: Dict[str, Dict[str, float]] = {}

    @staticmethod
    def _real_client():
        try:
            import googleapiclient.discovery  # type: ignore
        except ImportError as e:
            raise RuntimeError(
                "GCPNodeProvider needs google-api-python-client (not "
                "bundled); pass compute= explicitly or install it") from e
        return googleapiclient.discovery.build("compute", "v1")

    def non_terminated_nodes(self) -> List[str]:
        reply = self._compute.instances().list(
            project=self.project, zone=self.zone,
            filter=(f"labels.{TAG_CLUSTER}={self.cluster_name} AND "
                    f"(status=RUNNING OR status=PROVISIONING OR "
                    f"status=STAGING)")).execute()
        return [item["name"] for item in reply.get("items", [])]

    def create_node(self, num_cpus: int, resources=None) -> str:
        name = f"ray-tpu-{self.cluster_name}-{uuid.uuid4().hex[:8]}"
        body = copy.deepcopy(self.node_config)
        body["name"] = name
        body.setdefault("labels", {})[TAG_CLUSTER] = self.cluster_name
        body["labels"][TAG_NODE_KIND] = KIND_WORKER
        res = dict(resources or {})
        accel = body.pop("acceleratorType", None)
        if accel:
            # TPU VM: the accelerator becomes a schedulable resource on
            # the joining node (chips count from the type suffix)
            try:
                res.setdefault("TPU", float(accel.rsplit("-", 1)[1]))
            except (IndexError, ValueError):
                res.setdefault("TPU", 1.0)
            body.setdefault("guestAccelerators", []).append(
                {"acceleratorType": accel, "acceleratorCount": 1})
        items = body.setdefault("metadata", {}).setdefault("items", [])
        items.append({"key": "startup-script",
                      "value": "#!/bin/bash\n" + default_start_command(
                          self.gcs_address, num_cpus, res)})
        self._compute.instances().insert(
            project=self.project, zone=self.zone, body=body).execute()
        self._resources[name] = {"CPU": float(num_cpus), **res}
        return name

    def terminate_node(self, node_id: str) -> None:
        try:
            self._compute.instances().delete(
                project=self.project, zone=self.zone,
                instance=node_id).execute()
        except Exception:  # noqa: BLE001 — already gone: idempotent
            pass
        self._resources.pop(node_id, None)

    def node_resources(self, node_id: str) -> Dict[str, float]:
        return dict(self._resources.get(node_id, {"CPU": 1.0}))


class KubernetesNodeProvider(NodeProvider):
    """Pod-per-node workers (reference:
    _private/_kubernetes/node_provider.py KubernetesNodeProvider —
    label-selected pods in one namespace, create from a pod template,
    delete_namespaced_pod)."""

    def __init__(self, cluster_name: str, gcs_address: str,
                 namespace: str, pod_template: Dict[str, Any],
                 core_api=None):
        self.cluster_name = cluster_name
        self.gcs_address = gcs_address
        self.namespace = namespace
        self.pod_template = dict(pod_template)
        self._api = core_api if core_api is not None \
            else self._real_client()
        self._resources: Dict[str, Dict[str, float]] = {}

    @staticmethod
    def _real_client():
        try:
            import kubernetes  # type: ignore
        except ImportError as e:
            raise RuntimeError(
                "KubernetesNodeProvider needs the kubernetes package "
                "(not bundled); pass core_api= explicitly") from e
        kubernetes.config.load_incluster_config()
        return kubernetes.client.CoreV1Api()

    def _selector(self) -> str:
        return f"{TAG_CLUSTER}={self.cluster_name}"

    def non_terminated_nodes(self) -> List[str]:
        reply = self._api.list_namespaced_pod(
            self.namespace, label_selector=self._selector())
        out = []
        for pod in reply.items:
            phase = pod.status.phase if pod.status else None
            if phase in ("Pending", "Running"):
                out.append(pod.metadata.name)
        return out

    def create_node(self, num_cpus: int, resources=None) -> str:
        name = f"ray-tpu-{self.cluster_name}-{uuid.uuid4().hex[:8]}"
        body = copy.deepcopy(self.pod_template)
        meta = body.setdefault("metadata", {})
        meta["name"] = name
        meta.setdefault("labels", {})[TAG_CLUSTER] = self.cluster_name
        meta["labels"][TAG_NODE_KIND] = KIND_WORKER
        spec = body.setdefault("spec", {})
        containers = spec.setdefault("containers", [{}])
        c0 = containers[0]
        c0.setdefault("name", "ray-tpu-node")
        c0["command"] = ["/bin/bash", "-lc"]
        c0["args"] = [default_start_command(
            self.gcs_address, num_cpus, resources) + " --block"]
        self._api.create_namespaced_pod(self.namespace, body)
        self._resources[name] = {"CPU": float(num_cpus),
                                 **(resources or {})}
        return name

    def terminate_node(self, node_id: str) -> None:
        try:
            self._api.delete_namespaced_pod(node_id, self.namespace)
        except Exception:  # noqa: BLE001 — already gone: idempotent
            pass
        self._resources.pop(node_id, None)

    def node_resources(self, node_id: str) -> Dict[str, float]:
        return dict(self._resources.get(node_id, {"CPU": 1.0}))


def wait_for_nodes(provider: NodeProvider, count: int,
                   timeout: float = 300.0, poll: float = 2.0) -> bool:
    """Block until the provider reports ``count`` live nodes
    (reference: the updater's wait-for-ready loop)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if len(provider.non_terminated_nodes()) >= count:
            return True
        # raylint: disable=async-blocking — autoscaler control thread; provider APIs are sync HTTP
        time.sleep(poll)
    return False
