"""ImpalaTrainer: async rollouts + an importance-weighted learner.

Parity target: the reference's IMPALA
(reference: rllib/agents/impala/impala.py — async sample collection
feeding a learner, execution plan built from rollout/train ops on
trainer_template.py:53). Lite here: the learner applies the
truncated-rho importance-weighted objective (policy.py impala_loss)
to every batch as it lands — one jitted Adam step per batch — instead
of the reference's multi-GPU learner thread; the point proven is that
the ASYNC execution-plan shape (ParallelRollouts(mode="async") |>
TrainOneStep) is one plan away once the ops exist.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp

from ray_tpu.rllib import execution
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.policy import impala_loss, init_policy_params
from ray_tpu.rllib.rollout_worker import WorkerSet

DEFAULT_CONFIG: Dict[str, Any] = {
    "env": "CartPole-v0",
    "num_workers": 2,
    "num_envs_per_worker": 8,
    "rollout_len": 64,
    "gamma": 0.99,
    "lambda": 0.95,
    "lr": 5e-4,
    "rho_clip": 1.0,
    "vf_coeff": 0.5,
    "entropy_coeff": 0.01,
    "model": None,                # model-catalog config (models.py)
    "seed": 0,
}


@functools.partial(jax.jit, static_argnames=("rho_clip", "vf_coeff",
                                             "ent_coeff", "lr",
                                             "model"))
def _impala_update(params, opt_state, batch, *, rho_clip, vf_coeff,
                   ent_coeff, lr, model=None):
    """One importance-weighted Adam step as a single compiled program
    (mirrors _ppo_update/_dqn_update — no per-leaf host dispatches)."""
    import optax

    optimizer = optax.adam(lr)
    (loss, aux), grads = jax.value_and_grad(
        impala_loss, has_aux=True)(params, batch, rho_clip=rho_clip,
                                   vf_coeff=vf_coeff,
                                   ent_coeff=ent_coeff, model=model)
    updates, opt_state = optimizer.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss, aux["entropy"]


class ImpalaTrainer(execution.Trainer):
    """Async on-policy-ish shape of the execution-plan substrate."""

    default_config = DEFAULT_CONFIG

    def setup(self, cfg: Dict[str, Any]) -> None:
        import optax

        from ray_tpu.rllib.models import freeze_model_config

        probe = make_env(cfg["env"], 1)
        self.model = freeze_model_config(cfg["model"]) \
            if cfg.get("model") else None
        self.params = init_policy_params(
            jax.random.key(cfg["seed"]), probe.observation_size,
            probe.num_actions, model=self.model)
        self._opt_state = optax.adam(cfg["lr"]).init(self.params)
        self.workers = WorkerSet(
            cfg["env"], cfg["num_workers"], cfg["num_envs_per_worker"],
            cfg["rollout_len"], cfg["gamma"], cfg["lambda"],
            model=self.model)
        self._counters = {"timesteps_total": 0}

    def execution_plan(self):
        rollouts = execution.ParallelRollouts(
            self.workers.workers, mode="async",
            weights=lambda: self.params)

        def count(batch):
            self._counters["timesteps_total"] += len(batch["obs"])
            return batch

        it = execution.ForEach(rollouts, count)
        it = execution.TrainOneStep(it, self._learn_on_batch)
        return execution.StandardMetricsReporting(
            it, self.workers.workers, self._counters)

    def _learn_on_batch(self, batch) -> Dict[str, Any]:
        cfg = self.config
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        self.params, self._opt_state, loss, entropy = _impala_update(
            self.params, self._opt_state, jb, rho_clip=cfg["rho_clip"],
            vf_coeff=cfg["vf_coeff"], ent_coeff=cfg["entropy_coeff"],
            lr=cfg["lr"], model=self.model)
        return {"loss": float(loss), "entropy": float(entropy)}

    def get_state(self) -> dict:
        return {"params": self.params, "opt_state": self._opt_state,
                "timesteps": self._counters["timesteps_total"]}

    def set_state(self, state: dict) -> None:
        self.params = state["params"]
        self._opt_state = state["opt_state"]
        self._counters["timesteps_total"] = state["timesteps"]
