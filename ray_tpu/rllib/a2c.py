"""A2C and vanilla policy gradient on the execution-plan substrate.

Parity targets: the reference's A2C/A3C family and PG trainer
(reference: rllib/agents/a3c/a2c.py, rllib/agents/pg/pg.py — both are
trainer_template compositions over ParallelRollouts + TrainOneStep).
Here each is literally ``build_trainer`` plus one jitted loss: A2C is
the synchronous advantage actor-critic step; PG is REINFORCE with the
value head as a baseline.  Both reuse the PPO rollout workers (GAE
advantages computed worker-side) — algorithm #N is a config + a loss,
which is the point of the execution-plan layer.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp

from ray_tpu.rllib import execution
from ray_tpu.rllib.common import (
    actor_critic_get_state,
    actor_critic_set_state,
    actor_critic_setup,
    onpolicy_execution_plan,
)
from ray_tpu.rllib.policy import logits_and_value

A2C_CONFIG: Dict[str, Any] = {
    "env": "CartPole-v0",
    "num_workers": 2,
    "num_envs_per_worker": 8,
    "rollout_len": 32,
    "gamma": 0.99,
    "lambda": 1.0,
    "lr": 1e-3,
    "vf_coeff": 0.5,
    "entropy_coeff": 0.01,
    "model": None,                # model-catalog config (models.py)
    "seed": 0,
    # PG mode: drop the critic term from the gradient (value head
    # still trains as a baseline) — this flag IS the difference
    # between the two reference trainers.
    "use_critic": True,
}

PG_CONFIG = dict(A2C_CONFIG, use_critic=False, entropy_coeff=0.0)


@functools.partial(jax.jit, static_argnames=("vf_coeff", "ent_coeff",
                                             "use_critic", "lr",
                                             "model"))
def _a2c_update(params, opt_state, batch, *, vf_coeff, ent_coeff,
                use_critic, lr, model=None):
    import optax

    optimizer = optax.adam(lr)

    def loss_fn(p):
        logits, value = logits_and_value(p, batch["obs"], model)
        logp_all = jax.nn.log_softmax(logits)
        logp = logp_all[jnp.arange(logits.shape[0]), batch["actions"]]
        if use_critic:
            adv = batch["advantages"]
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        else:
            # REINFORCE: discounted returns, baseline-subtracted but
            # not bootstrapped
            adv = batch["returns"] - jax.lax.stop_gradient(value)
        pg = -(adv * logp).mean()
        vf = jnp.mean((value - batch["returns"]) ** 2)
        entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1).mean()
        return pg + vf_coeff * vf - ent_coeff * entropy, (pg, entropy)

    (loss, (pg, entropy)), grads = jax.value_and_grad(
        loss_fn, has_aux=True)(params)
    updates, opt_state = optimizer.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss, entropy


def _learn(self, batch) -> Dict[str, Any]:
    cfg = self.config
    self.params, self._opt_state, loss, entropy = _a2c_update(
        self.params, self._opt_state,
        {k: jnp.asarray(v) for k, v in batch.items()},
        vf_coeff=cfg["vf_coeff"], ent_coeff=cfg["entropy_coeff"],
        use_critic=cfg["use_critic"], lr=cfg["lr"], model=self.model)
    return {"loss": float(loss), "entropy": float(entropy)}


def _execution_plan(self):
    return onpolicy_execution_plan(self, lambda b: _learn(self, b))


A2CTrainer = execution.build_trainer(
    name="A2CTrainer", default_config=A2C_CONFIG, setup=actor_critic_setup,
    execution_plan=_execution_plan, get_state=actor_critic_get_state,
    set_state=actor_critic_set_state)

PGTrainer = execution.build_trainer(
    name="PGTrainer", default_config=PG_CONFIG, setup=actor_critic_setup,
    execution_plan=_execution_plan, get_state=actor_critic_get_state,
    set_state=actor_critic_set_state)
