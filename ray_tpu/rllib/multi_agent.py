"""Multi-agent RL: env protocol, per-agent policy mapping, MA-PPO.

Parity target: the reference's multi-agent stack
(reference: rllib/env/multi_agent_env.py:9 — dict-keyed obs/action/
reward spaces per agent — and the policy-mapping machinery in
rllib/evaluation/rollout_worker.py:105 ``policy_mapping_fn`` +
``MultiAgentSampleBatchBuilder`` grouping transitions per POLICY).
TPU-first re-design: every agent's env slice is BATCHED ([B, ...] like
the single-agent VectorEnv), so each policy still does one fused
device sampling step per rollout tick, and the learner runs one jitted
PPO update per policy over the concatenation of all agents mapped to
it. Policies may have DIFFERENT observation/action spaces.
"""

from __future__ import annotations

import zlib
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rllib import execution
from ray_tpu.rllib.policy import (compute_gae, init_policy_params,
                                  sample_actions)
from ray_tpu.rllib.ppo import _ppo_update


class MultiAgentVectorEnv:
    """Batched multi-agent env protocol (synchronized steps: every
    agent acts each tick; episodes end together — the "__all__" done
    of the reference's MultiAgentEnv).

    ``agents``: {agent_id: (observation_size, num_actions)}.
    ``reset(seed) -> {agent_id: obs [B, obs_size]}``
    ``step({agent_id: actions [B]}) -> (obs_dict, reward_dict,
    done [B])`` — done episodes auto-reset.
    """

    num_envs: int
    agents: Dict[str, Tuple[int, int]]

    def reset(self, seed: int = 0) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: Dict[str, np.ndarray]):
        raise NotImplementedError


class MultiTarget(MultiAgentVectorEnv):
    """Two-policy debug env (reference role: rllib/examples/env/
    multi_agent.py debug envs): each agent sees a one-hot target drawn
    from ITS OWN action space (different sizes per agent — proves the
    per-policy spaces really are independent) and earns +1 for matching
    it. Optimal per-agent return = MAX_STEPS. Deterministic
    learnability oracle for the mapping + per-policy learners."""

    MAX_STEPS = 8
    AGENT_SPECS = {"alpha": 3, "beta": 5}  # agent -> num_actions

    def __init__(self, num_envs: int = 8):
        self.num_envs = num_envs
        self.agents = {aid: (n, n) for aid, n in self.AGENT_SPECS.items()}
        self._rng = np.random.default_rng(0)
        self._targets: Dict[str, np.ndarray] = {}
        self._steps = None

    def _draw(self) -> None:
        self._targets = {
            aid: self._rng.integers(0, n, size=self.num_envs)
            for aid, n in self.AGENT_SPECS.items()}

    def _obs(self) -> Dict[str, np.ndarray]:
        out = {}
        for aid, n in self.AGENT_SPECS.items():
            eye = np.eye(n, dtype=np.float32)
            out[aid] = eye[self._targets[aid]]
        return out

    def reset(self, seed: int = 0) -> Dict[str, np.ndarray]:
        self._rng = np.random.default_rng(seed)
        self._steps = np.zeros(self.num_envs, dtype=np.int32)
        self._draw()
        return self._obs()

    def step(self, actions: Dict[str, np.ndarray]):
        rewards = {
            aid: (np.asarray(actions[aid]) == self._targets[aid])
            .astype(np.float32)
            for aid in self.AGENT_SPECS}
        self._steps += 1
        done = self._steps >= self.MAX_STEPS
        if done.any():
            self._steps[done] = 0
        self._draw()  # fresh targets every tick (and for new episodes)
        return self._obs(), rewards, done


MULTI_ENV_REGISTRY = {"MultiTarget-v0": MultiTarget}


def make_multi_env(name_or_cls, num_envs: int) -> MultiAgentVectorEnv:
    if isinstance(name_or_cls, str):
        name_or_cls = MULTI_ENV_REGISTRY[name_or_cls]
    return name_or_cls(num_envs=num_envs)


def validate_policy_spaces(agents: Dict[str, Tuple[int, int]],
                           mapping: Dict[str, str]) -> None:
    """Agents sharing a policy must share observation/action spaces
    (reference: the policy-spec validation in
    rllib/agents/trainer.py validate_config) — fail at setup with a
    clear error instead of a shape mismatch deep in a worker."""
    by_policy: Dict[str, Tuple[str, Tuple[int, int]]] = {}
    for aid, pid in mapping.items():
        spaces = agents[aid]
        seen = by_policy.setdefault(pid, (aid, spaces))
        if seen[1] != spaces:
            raise ValueError(
                f"agents {seen[0]!r} {seen[1]} and {aid!r} {spaces} "
                f"map to policy {pid!r} but have different "
                f"(obs_size, num_actions) spaces")


class MultiAgentRolloutWorker:
    """Steps a MultiAgentVectorEnv with one policy per mapping entry,
    grouping trajectories per POLICY (reference:
    MultiAgentSampleBatchBuilder.postprocess_batch_so_far). Returns
    {policy_id: sample batch} with GAE computed per agent stream
    before grouping."""

    def __init__(self, env_name, num_envs: int, rollout_len: int,
                 policy_mapping: Dict[str, str], seed: int = 0,
                 gamma: float = 0.99, lam: float = 0.95):
        self.env = make_multi_env(env_name, num_envs)
        self.mapping = dict(policy_mapping)
        unknown = set(self.env.agents) - set(self.mapping)
        if unknown:
            raise ValueError(f"agents without a policy: {sorted(unknown)}")
        self.rollout_len = rollout_len
        self.gamma, self.lam = gamma, lam
        self._key = jax.random.key(seed)
        self.obs = self.env.reset(seed)
        validate_policy_spaces(self.env.agents, self.mapping)
        self.policies: Dict[str, Any] = {}
        for aid, pid in self.mapping.items():
            obs_size, num_actions = self.env.agents[aid]
            if pid in self.policies:
                continue
            self.policies[pid] = init_policy_params(
                jax.random.key(zlib.crc32(pid.encode()) & 0xFFFF),
                obs_size, num_actions)
        self._ep_return = np.zeros(num_envs, dtype=np.float32)
        self._finished_returns: List[float] = []

    def set_weights(self, policies: Dict[str, Any]) -> None:
        self.policies.update(policies)

    def sample(self) -> Dict[str, Dict[str, np.ndarray]]:
        T, B = self.rollout_len, self.env.num_envs
        aids = list(self.env.agents)
        buf = {aid: {"obs": [], "actions": [], "logp": [], "value": [],
                     "reward": []} for aid in aids}
        dones = []
        for _ in range(T):
            acts = {}
            for aid in aids:
                self._key, sub = jax.random.split(self._key)
                params = self.policies[self.mapping[aid]]
                a, logp, value = sample_actions(
                    params, jnp.asarray(self.obs[aid]), sub)
                acts[aid] = np.asarray(a)
                b = buf[aid]
                b["obs"].append(self.obs[aid])
                b["actions"].append(acts[aid])
                b["logp"].append(np.asarray(logp))
                b["value"].append(np.asarray(value))
            self.obs, rewards, done = self.env.step(acts)
            step_total = np.zeros(B, dtype=np.float32)
            for aid in aids:
                buf[aid]["reward"].append(rewards[aid])
                step_total += rewards[aid]
            dones.append(done.astype(np.float32))
            self._ep_return += step_total
            if done.any():
                self._finished_returns.extend(
                    self._ep_return[done].tolist())
                self._ep_return[done] = 0.0
        done_arr = np.stack(dones)

        # per-agent GAE, then group by policy
        per_policy: Dict[str, List[dict]] = {}
        for aid in aids:
            b = {k: np.stack(v) for k, v in buf[aid].items()}
            # terminal bootstrap: value of the CURRENT obs under the
            # agent's policy
            _, _, last_value = sample_actions(
                self.policies[self.mapping[aid]],
                jnp.asarray(self.obs[aid]), self._key)
            adv, ret = compute_gae(b["reward"], b["value"], done_arr,
                                   np.asarray(last_value),
                                   gamma=self.gamma, lam=self.lam)
            flat = lambda a: a.reshape(-1, *a.shape[2:])  # noqa: E731
            per_policy.setdefault(self.mapping[aid], []).append({
                "obs": flat(b["obs"]), "actions": flat(b["actions"]),
                "logp_old": flat(b["logp"]), "advantages": flat(adv),
                "returns": flat(ret)})
        return {pid: execution.concat_batches(parts)
                for pid, parts in per_policy.items()}

    def episode_returns(self, clear: bool = True) -> List[float]:
        out = list(self._finished_returns)
        if clear:
            self._finished_returns.clear()
        return out


MA_PPO_CONFIG: Dict[str, Any] = {
    "env": "MultiTarget-v0",
    "num_workers": 1,
    "num_envs_per_worker": 8,
    "rollout_len": 32,
    "gamma": 0.99,
    "lambda": 0.95,
    "lr": 3e-3,
    "clip": 0.2,
    "vf_coeff": 0.5,
    "entropy_coeff": 0.01,
    "num_sgd_epochs": 4,
    "minibatch_size": 128,
    "seed": 0,
    "multiagent": {
        # agent_id -> policy_id (reference: policy_mapping_fn; a dict
        # here so it ships to worker actors without pickling closures)
        "policy_mapping": None,   # default: each agent its own policy
    },
}


def _ma_setup(self, cfg: Dict[str, Any]) -> None:
    import optax

    probe = make_multi_env(cfg["env"], 1)
    mapping = (cfg.get("multiagent") or {}).get("policy_mapping") or \
        {aid: aid for aid in probe.agents}
    validate_policy_spaces(probe.agents, mapping)
    self.policy_mapping = mapping
    self.params: Dict[str, Any] = {}
    self._opt_states: Dict[str, Any] = {}
    self._optimizer = optax.adam(cfg["lr"])
    for aid, pid in mapping.items():
        if pid in self.params:
            continue
        obs_size, num_actions = probe.agents[aid]
        self.params[pid] = init_policy_params(
            jax.random.key(cfg["seed"] + (zlib.crc32(pid.encode())
                                          & 0xFFFF)),
            obs_size, num_actions)
        self._opt_states[pid] = self._optimizer.init(self.params[pid])
    cls = ray_tpu.remote(MultiAgentRolloutWorker)
    self.workers = [
        cls.remote(cfg["env"], cfg["num_envs_per_worker"],
                   cfg["rollout_len"], mapping, seed=i + 1,
                   gamma=cfg["gamma"], lam=cfg["lambda"])
        for i in range(cfg["num_workers"])]
    self._counters = {"timesteps_total": 0}
    self._key = jax.random.key(cfg["seed"] + 1)


def _ma_learn(self, batches: Dict[str, dict]) -> Dict[str, Any]:
    """One PPO update per policy (reference: Trainer._train over the
    policy map — each policy optimizes only its own experience)."""
    cfg = self.config
    out: Dict[str, Any] = {}
    for pid, batch in batches.items():
        num_minibatches = max(1, len(batch["obs"]) //
                              cfg["minibatch_size"])
        self._key, sub = jax.random.split(self._key)
        (self.params[pid], self._opt_states[pid], loss,
         entropy) = _ppo_update(
            self.params[pid], self._opt_states[pid],
            {k: jnp.asarray(v) for k, v in batch.items()}, sub,
            num_epochs=cfg["num_sgd_epochs"],
            num_minibatches=num_minibatches, clip=cfg["clip"],
            vf_coeff=cfg["vf_coeff"], ent_coeff=cfg["entropy_coeff"],
            lr=cfg["lr"])
        out[f"policy_{pid}_loss"] = float(loss)
        out[f"policy_{pid}_entropy"] = float(entropy)
    return out


def _ma_execution_plan(self):
    def merge(dicts: List[Dict[str, dict]]) -> Dict[str, dict]:
        merged: Dict[str, List[dict]] = {}
        for d in dicts:
            for pid, b in d.items():
                merged.setdefault(pid, []).append(b)
        return {pid: execution.concat_batches(bs)
                for pid, bs in merged.items()}

    def rollouts():
        while True:
            ray_tpu.get([w.set_weights.remote(self.params)
                         for w in self.workers])
            batches = merge(ray_tpu.get(
                [w.sample.remote() for w in self.workers]))
            self._counters["timesteps_total"] += sum(
                len(b["obs"]) for b in batches.values())
            yield batches

    it = execution.TrainOneStep(rollouts(), lambda b: _ma_learn(self, b))
    return execution.StandardMetricsReporting(
        it, self.workers, self._counters)


def _ma_get_state(self) -> dict:
    return {"params": self.params, "opt_states": self._opt_states,
            "timesteps": self._counters["timesteps_total"]}


def _ma_set_state(self, state: dict) -> None:
    self.params = state["params"]
    self._opt_states = state["opt_states"]
    self._counters["timesteps_total"] = state["timesteps"]


MultiAgentPPOTrainer = execution.build_trainer(
    name="MultiAgentPPOTrainer", default_config=MA_PPO_CONFIG,
    setup=_ma_setup, execution_plan=_ma_execution_plan,
    get_state=_ma_get_state, set_state=_ma_set_state)
