"""JAX policy: actor-critic MLP with jitted action sampling + PPO loss.

Parity target: the reference's Policy abstraction
(reference: rllib/policy/policy.py, torch_policy.py — compute_actions,
loss, get/set_weights). TPU-first re-design: the policy is a pytree of
params plus PURE jitted functions (sample, value, loss) — batched
matmuls on the MXU, no per-step Python in the learner.
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np


def init_policy_params(key, obs_size: int, num_actions: int,
                       hidden: int = 64, model=None) -> Dict:
    """``model``: a frozen catalog spec (models.freeze_model_config)
    switches the trunk to the catalog network (reference:
    rllib/models/catalog.py:71); None keeps the classic tanh MLP."""
    if model is not None:
        from ray_tpu.rllib.models import init_actor_critic

        return init_actor_critic(model, key, obs_size, num_actions)
    k1, k2, k3, k4 = jax.random.split(key, 4)
    init = jax.nn.initializers.orthogonal(np.sqrt(2))
    zinit = jax.nn.initializers.orthogonal(0.01)
    return {
        "w1": init(k1, (obs_size, hidden), jnp.float32),
        "b1": jnp.zeros((hidden,)),
        "w2": init(k2, (hidden, hidden), jnp.float32),
        "b2": jnp.zeros((hidden,)),
        "pi": zinit(k3, (hidden, num_actions), jnp.float32),
        "pi_b": jnp.zeros((num_actions,)),
        "vf": init(k4, (hidden, 1), jnp.float32),
        "vf_b": jnp.zeros((1,)),
    }


def _trunk(params, obs):
    h = jnp.tanh(obs @ params["w1"] + params["b1"])
    return jnp.tanh(h @ params["w2"] + params["b2"])


def logits_and_value(params, obs, model=None):
    if model is not None:
        from ray_tpu.rllib.models import actor_critic_forward

        return actor_critic_forward(model, params, obs)
    h = _trunk(params, obs)
    return (h @ params["pi"] + params["pi_b"],
            (h @ params["vf"] + params["vf_b"])[..., 0])


@functools.partial(jax.jit, static_argnames=("model",))
def sample_actions(params, obs, key, model=None):
    """→ (actions, logp, value): one fused device step per env batch."""
    logits, value = logits_and_value(params, obs, model)
    actions = jax.random.categorical(key, logits)
    logp = jax.nn.log_softmax(logits)[
        jnp.arange(logits.shape[0]), actions]
    return actions, logp, value


@functools.partial(jax.jit, static_argnames=("clip", "vf_coeff",
                                             "ent_coeff", "model"))
def ppo_loss(params, batch, *, clip=0.2, vf_coeff=0.5, ent_coeff=0.01,
             model=None):
    """Clipped-surrogate PPO objective (standard public formulation)."""
    logits, value = logits_and_value(params, batch["obs"], model)
    logp_all = jax.nn.log_softmax(logits)
    logp = logp_all[jnp.arange(logits.shape[0]), batch["actions"]]
    ratio = jnp.exp(logp - batch["logp_old"])
    adv = batch["advantages"]
    pg = -jnp.minimum(
        ratio * adv,
        jnp.clip(ratio, 1 - clip, 1 + clip) * adv).mean()
    vf = jnp.mean((value - batch["returns"]) ** 2)
    entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1).mean()
    total = pg + vf_coeff * vf - ent_coeff * entropy
    return total, {"policy_loss": pg, "vf_loss": vf, "entropy": entropy}


@functools.partial(jax.jit, static_argnames=("rho_clip", "vf_coeff",
                                             "ent_coeff", "model"))
def impala_loss(params, batch, *, rho_clip=1.0, vf_coeff=0.5,
                ent_coeff=0.01, model=None):
    """Off-policy actor-critic with clipped importance weights — the
    V-trace-lite objective for async (stale-policy) batches (standard
    public IMPALA formulation, truncated-rho policy gradient; the
    value targets reuse the workers' GAE returns)."""
    logits, value = logits_and_value(params, batch["obs"], model)
    logp_all = jax.nn.log_softmax(logits)
    logp = logp_all[jnp.arange(logits.shape[0]), batch["actions"]]
    rho = jnp.minimum(jnp.exp(logp - batch["logp_old"]), rho_clip)
    adv = batch["advantages"]
    adv = (adv - adv.mean()) / (adv.std() + 1e-8)
    pg = -(jax.lax.stop_gradient(rho) * adv * logp).mean()
    vf = jnp.mean((value - batch["returns"]) ** 2)
    entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1).mean()
    total = pg + vf_coeff * vf - ent_coeff * entropy
    return total, {"policy_loss": pg, "vf_loss": vf, "entropy": entropy}


def compute_gae(rewards, values, dones, last_value, *, gamma=0.99,
                lam=0.95):
    """Generalized advantage estimation over a [T, B] rollout (numpy —
    runs on the rollout worker, scan-free and cheap)."""
    T = rewards.shape[0]
    adv = np.zeros_like(rewards)
    last_gae = np.zeros(rewards.shape[1], dtype=np.float32)
    next_value = last_value
    for t in range(T - 1, -1, -1):
        nonterminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        last_gae = delta + gamma * lam * nonterminal * last_gae
        adv[t] = last_gae
        next_value = values[t]
    returns = adv + values
    return adv, returns
