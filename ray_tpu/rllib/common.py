"""Shared actor-critic trainer plumbing for the on-policy algorithms.

The sync on-policy trainers (PPO, A2C, PG) differ only in their update
function; setup / execution plan / checkpoint state are identical
(reference: the shared trainer_template defaults in
rllib/agents/trainer_template.py — common pieces live once, algorithms
supply callables)."""

from __future__ import annotations

from typing import Any, Callable, Dict

import jax

from ray_tpu.rllib import execution
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.policy import init_policy_params
from ray_tpu.rllib.rollout_worker import WorkerSet


def actor_critic_setup(self, cfg: Dict[str, Any]) -> None:
    """Probe env → policy params + Adam state + WorkerSet + counters.
    ``cfg["model"]`` (a model-catalog config dict) selects the network
    (reference: catalog.py get_model_v2 feeding every agent)."""
    import optax

    from ray_tpu.rllib.models import freeze_model_config

    probe = make_env(cfg["env"], 1)
    self.model = freeze_model_config(cfg["model"]) \
        if cfg.get("model") else None
    self.params = init_policy_params(
        jax.random.key(cfg["seed"]), probe.observation_size,
        probe.num_actions, model=self.model)
    self._opt_state = optax.adam(cfg["lr"]).init(self.params)
    self.workers = WorkerSet(
        cfg["env"], cfg["num_workers"], cfg["num_envs_per_worker"],
        cfg["rollout_len"], cfg["gamma"], cfg["lambda"],
        model=self.model)
    self._counters = {"timesteps_total": 0}


def onpolicy_execution_plan(self, learn_fn: Callable[[Any], dict]):
    """ParallelRollouts |> count |> TrainOneStep |> metrics — the sync
    on-policy shape (reference: ppo.py's execution_plan)."""
    rollouts = execution.ParallelRollouts(
        self.workers.workers, mode="bulk_sync",
        weights=lambda: self.params)

    def count(batch):
        self._counters["timesteps_total"] += len(batch["obs"])
        return batch

    it = execution.ForEach(rollouts, count)
    it = execution.TrainOneStep(it, learn_fn)
    return execution.StandardMetricsReporting(
        it, self.workers.workers, self._counters)


def actor_critic_get_state(self) -> dict:
    return {"params": self.params, "opt_state": self._opt_state,
            "timesteps": self._counters["timesteps_total"]}


def actor_critic_set_state(self, state: dict) -> None:
    self.params = state["params"]
    self._opt_state = state["opt_state"]
    self._counters["timesteps_total"] = state["timesteps"]
