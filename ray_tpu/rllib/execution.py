"""Execution-plan dataflow: RL training loops as composed iterators.

Parity target: the reference's execution ops
(reference: rllib/execution/rollout_ops.py ParallelRollouts /
ConcatBatches, replay_ops StoreToReplayBuffer / Replay,
train_ops.py TrainOneStep / UpdateTargetNetwork,
concurrency_ops.py Concurrently, metric_ops StandardMetricsReporting)
powering 20+ algorithms through the trainer template
(reference: rllib/agents/trainer_template.py:53 build_trainer).

TPU-first re-design: ops are plain Python generators over the task/
actor runtime — no LocalIterator class hierarchy. Sampling fans out as
actor calls (``ray_tpu.wait`` drives the async mode), while the
learner stays ONE jitted device program per train step (the lax.scan
update fns in ppo.py / dqn.py), so composing ops never fragments the
device work. An algorithm is: an ``execution_plan`` generator wiring
these ops + a jitted update — see PPOTrainer / DQNTrainer /
ImpalaTrainer for the three shapes (sync on-policy, replay off-policy,
async on-policy).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional

import numpy as np

import ray_tpu


def concat_batches(batches: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    """Row-concatenate sample batches (reference: SampleBatch.concat_samples)."""
    if len(batches) == 1:
        return batches[0]
    return {k: np.concatenate([np.asarray(b[k]) for b in batches])
            for k in batches[0]}


def ParallelRollouts(workers: List[Any], *, mode: str = "bulk_sync",
                     sample_args: Callable[[], tuple] = tuple,
                     weights: Callable[[], Any] | None = None
                     ) -> Iterator[Dict[str, np.ndarray]]:
    """Stream sample batches from rollout-worker actors
    (reference: rollout_ops.py ParallelRollouts).

    ``bulk_sync``: broadcast current weights, gather one batch from
    every worker, yield their concatenation — the on-policy shape.
    ``async``: keep one sample call in flight per worker and yield
    batches as they land (weights broadcast before each resubmission,
    so a batch may be one policy version stale — the IMPALA shape).
    ``weights()`` supplies the current parameters each round.
    """
    if mode == "bulk_sync":
        while True:
            if weights is not None:
                w = weights()
                ray_tpu.get([a.set_weights.remote(w) for a in workers])
            batches = ray_tpu.get(
                [a.sample.remote(*sample_args()) for a in workers])
            yield concat_batches(batches)
    elif mode == "async":
        inflight = {}
        for a in workers:
            if weights is not None:
                a.set_weights.remote(weights())
            inflight[a.sample.remote(*sample_args())] = a
        while True:
            done, _ = ray_tpu.wait(list(inflight), num_returns=1)
            ref = done[0]
            actor = inflight.pop(ref)
            batch = ray_tpu.get(ref)
            if weights is not None:
                actor.set_weights.remote(weights())
            inflight[actor.sample.remote(*sample_args())] = actor
            yield batch
    else:
        raise ValueError(f"unknown rollout mode {mode!r}")


def ConcatBatches(it: Iterable, min_rows: int) -> Iterator:
    """Buffer upstream batches until at least ``min_rows`` rows, then
    yield one concatenated batch (reference: rollout_ops ConcatBatches)."""
    buf: List[dict] = []
    rows = 0
    for batch in it:
        buf.append(batch)
        rows += len(next(iter(batch.values())))
        if rows >= min_rows:
            yield concat_batches(buf)
            buf, rows = [], 0


def ForEach(it: Iterable, fn: Callable[[Any], Any]) -> Iterator:
    """Map an op over the stream (reference: LocalIterator.for_each)."""
    for item in it:
        yield fn(item)


def StoreToReplayBuffer(it: Iterable, buffer: Any) -> Iterator:
    """Tee batches into a replay-buffer actor, passing them through
    (reference: replay_ops.py StoreToReplayBuffer)."""
    for batch in it:
        buffer.add.remote(batch)
        yield batch


def Replay(buffer: Any, *, train_batch_size: int, num_steps: int,
           learning_starts: int = 0,
           size_fn: Callable[[], int] | None = None
           ) -> Iterator[Optional[dict]]:
    """Sample ``num_steps`` minibatches per round from the replay actor,
    yielding them stacked [K, batch, ...] for a single lax.scan update
    — or None while the buffer is warming up (reference:
    replay_ops.py Replay; the stacking keeps the learner one compiled
    program instead of K host round trips). ``size_fn`` supplies a
    locally-known buffer size (e.g. the return of the same round's
    add()) to skip the per-round size RPC."""
    import jax.numpy as jnp

    while True:
        size = size_fn() if size_fn is not None \
            else ray_tpu.get(buffer.size.remote())
        if size < max(learning_starts, 1):
            yield None
            continue
        minibatches = ray_tpu.get(
            [buffer.sample.remote(train_batch_size)
             for _ in range(num_steps)])
        yield {k: jnp.stack([m[k] for m in minibatches])
               for k in minibatches[0]}


def TrainOneStep(it: Iterable, train_fn: Callable[[Any], dict]) -> Iterator:
    """Apply the jitted learner update to each upstream item
    (reference: train_ops.py TrainOneStep — minus the GPU-loader
    machinery: on TPU the update IS one device program)."""
    for item in it:
        yield train_fn(item)


def UpdateTargetNetwork(it: Iterable, update_fn: Callable[[], None],
                        every: int) -> Iterator:
    """Invoke ``update_fn`` every N upstream items (reference:
    train_ops.py UpdateTargetNetwork). The update runs BEFORE the
    boundary item is yielded, so it lands inside the same train()
    iteration (a checkpoint taken right after the Nth iteration holds
    the freshly-synced target)."""
    count = 0
    for item in it:
        count += 1
        if count % every == 0:
            update_fn()
        yield item


def Concurrently(iters: List[Iterable], *, output: int = -1) -> Iterator:
    """Round-robin several sub-plans, yielding the designated one's
    items (reference: concurrency_ops.py Concurrently round_robin).
    Each round advances every sub-plan once; the ``output`` plan's
    item is yielded (default: the last, conventionally the learner)."""
    its = [iter(i) for i in iters]
    if output < 0:
        output = len(its) + output
    while True:
        out = None
        for i, it in enumerate(its):
            item = next(it)
            if i == output:
                out = item
        yield out


def StandardMetricsReporting(it: Iterable, workers: List[Any],
                             counters: Dict[str, Any]) -> Iterator[dict]:
    """Fold rollout-worker episode stats into each learner result
    (reference: metric_ops.py StandardMetricsReporting /
    CollectMetrics)."""
    for result in it:
        returns: List[float] = []
        if workers:
            for rs in ray_tpu.get(
                    [w.episode_returns.remote() for w in workers]):
                returns.extend(rs)
        out = dict(result or {})
        out.update(counters)
        out["episode_reward_mean"] = \
            float(np.mean(returns)) if returns else float("nan")
        out["episodes_this_iter"] = len(returns)
        yield out


class Trainer:
    """Trainer template (reference: trainer_template.py:53
    build_trainer): an algorithm provides ``default_config``,
    ``setup(config)`` (build params/workers/buffers), an
    ``execution_plan()`` generator of result dicts, and
    ``get_state``/``set_state`` for checkpointing. The template owns
    train() bookkeeping and the Tune trainable contract."""

    default_config: Dict[str, Any] = {}

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        self.config = {**self.default_config, **(config or {})}
        self._iteration = 0
        self.setup(self.config)
        self._plan = self.execution_plan()

    # -- algorithm hooks ----------------------------------------------------

    def setup(self, config: Dict[str, Any]) -> None:
        raise NotImplementedError

    def execution_plan(self) -> Iterator[dict]:
        raise NotImplementedError

    def get_state(self) -> dict:
        raise NotImplementedError

    def set_state(self, state: dict) -> None:
        raise NotImplementedError

    # -- template -----------------------------------------------------------

    def train(self) -> Dict[str, Any]:
        result = next(self._plan)
        self._iteration += 1
        result["training_iteration"] = self._iteration
        return result

    def save(self, path: str) -> str:
        import pickle

        with open(path, "wb") as f:
            pickle.dump({"state": self.get_state(),
                         "iteration": self._iteration}, f)
        return path

    def restore(self, path: str) -> None:
        import pickle

        with open(path, "rb") as f:
            blob = pickle.load(f)
        self.set_state(blob["state"])
        self._iteration = blob["iteration"]

    def stop(self) -> None:
        pass


def build_trainer(*, name: str, default_config: Dict[str, Any],
                  setup: Callable, execution_plan: Callable,
                  get_state: Callable, set_state: Callable) -> type:
    """Functional trainer construction (reference:
    trainer_template.py:53): algorithm #N is a config + four callables,
    not a hand-wired class."""
    cls = type(name, (Trainer,), {
        "default_config": default_config,
        "setup": setup,
        "execution_plan": execution_plan,
        "get_state": get_state,
        "set_state": set_state,
    })
    return cls
