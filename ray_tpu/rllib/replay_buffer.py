"""Replay buffer — runs as an actor shared by workers and the learner.

Parity target: the reference's replay machinery
(reference: rllib/execution/replay_buffer.py — ReplayBuffer :71,
LocalReplayBuffer actor wrapper :17/:302 used by DQN-family agents).
TPU-first posture: storage is preallocated contiguous numpy rings per
key, so sample() is one fancy-index gather producing exactly the
[batch, ...] layout the jitted learner consumes — no per-transition
Python objects.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    """Uniform ring-buffer replay. Use directly, or as an actor via
    ``ray_tpu.remote(ReplayBuffer).remote(capacity)``."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = int(capacity)
        self._store: Optional[Dict[str, np.ndarray]] = None
        self._next = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)
        self.num_added = 0

    def _allocate(self, batch: Dict[str, np.ndarray]) -> None:
        self._store = {
            k: np.zeros((self.capacity,) + v.shape[1:], dtype=v.dtype)
            for k, v in batch.items()}

    def add(self, batch: Dict[str, np.ndarray]) -> int:
        """Append a batch of transitions ({key: [n, ...]}); returns the
        current size."""
        batch = {k: np.asarray(v) for k, v in batch.items()}
        n = len(next(iter(batch.values())))
        if self._store is None:
            self._allocate(batch)
        for start in range(0, n, self.capacity):
            chunk = {k: v[start:start + self.capacity]
                     for k, v in batch.items()}
            m = len(next(iter(chunk.values())))
            idx = (self._next + np.arange(m)) % self.capacity
            for k, v in chunk.items():
                self._store[k][idx] = v
            self._next = int((self._next + m) % self.capacity)
            self._size = int(min(self._size + m, self.capacity))
        self.num_added += n
        return self._size

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        """Uniform sample with replacement → {key: [batch, ...]}."""
        if self._size == 0:
            raise ValueError("cannot sample from an empty buffer")
        idx = self._rng.integers(0, self._size, size=batch_size)
        return {k: v[idx] for k, v in self._store.items()}

    def __len__(self) -> int:
        return self._size

    def size(self) -> int:
        return self._size

    def stats(self) -> dict:
        return {"size": self._size, "capacity": self.capacity,
                "num_added": self.num_added}


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (reference:
    rllib/execution/replay_buffer.py PrioritizedReplayBuffer over a
    sum-tree; standard public formulation of Schaul et al. 2016).

    TPU-first posture kept: the sum tree is one numpy array and both
    sampling (stratified draw + vectorized level-by-level descent) and
    priority updates (unique-parent recompute per level) are batched
    numpy — no per-transition Python objects. ``sample`` returns the
    transitions plus ``weights`` (importance-sampling corrections,
    normalized to max 1) and ``indices`` for ``update_priorities``.
    """

    def __init__(self, capacity: int = 100_000, seed: int = 0,
                 alpha: float = 0.6, beta: float = 0.4,
                 eps: float = 1e-6):
        super().__init__(capacity, seed)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.eps = float(eps)
        self._cap2 = 1
        while self._cap2 < self.capacity:
            self._cap2 *= 2
        self._tree = np.zeros(2 * self._cap2, dtype=np.float64)
        self._max_prio = 1.0

    # -- sum tree -------------------------------------------------------

    def _set_leaves(self, slots: np.ndarray, prios: np.ndarray) -> None:
        if len(slots) == 0:
            return  # empty batch: nothing to propagate
        leaf = slots + self._cap2
        self._tree[leaf] = prios
        level = np.unique(leaf // 2)
        while level[0] >= 1:
            self._tree[level] = (self._tree[2 * level]
                                 + self._tree[2 * level + 1])
            if level[0] == 1:
                break
            level = np.unique(level // 2)

    def _descend(self, targets: np.ndarray) -> np.ndarray:
        idx = np.ones(len(targets), dtype=np.int64)
        while idx[0] < self._cap2:  # perfect tree: uniform depth
            left = 2 * idx
            left_sum = self._tree[left]
            go_right = targets > left_sum
            targets = targets - np.where(go_right, left_sum, 0.0)
            idx = left + go_right
        return idx - self._cap2

    # -- ReplayBuffer surface -------------------------------------------

    def add(self, batch: Dict[str, np.ndarray]) -> int:
        n = len(next(iter(batch.values())))
        start_next = self._next
        size = super().add(batch)
        # fresh transitions enter at the current max priority so each
        # is sampled at least once before TD errors demote it
        slots = (start_next + np.arange(min(n, self.capacity))) \
            % self.capacity
        self._set_leaves(slots, np.full(len(slots),
                                        self._max_prio ** self.alpha))
        return size

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        if self._size == 0:
            raise ValueError("cannot sample from an empty buffer")
        total = self._tree[1]
        # stratified: one draw per equal segment of the priority mass
        seg = total / batch_size
        targets = (np.arange(batch_size) + self._rng.random(batch_size)
                   ) * seg
        idx = np.minimum(self._descend(targets), self._size - 1)
        probs = self._tree[idx + self._cap2] / max(total, 1e-12)
        weights = (self._size * np.maximum(probs, 1e-12)) ** -self.beta
        out = {k: v[idx] for k, v in self._store.items()}
        out["weights"] = (weights / weights.max()).astype(np.float32)
        out["indices"] = idx.astype(np.int64)
        return out

    def update_priorities(self, indices: np.ndarray,
                          td_errors: np.ndarray) -> None:
        indices = np.asarray(indices).reshape(-1)
        if len(indices) == 0:
            return
        prios = np.abs(np.asarray(td_errors)).reshape(-1) + self.eps
        self._max_prio = max(self._max_prio, float(prios.max()))
        self._set_leaves(indices % self.capacity, prios ** self.alpha)
