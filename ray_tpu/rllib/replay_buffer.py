"""Replay buffer — runs as an actor shared by workers and the learner.

Parity target: the reference's replay machinery
(reference: rllib/execution/replay_buffer.py — ReplayBuffer :71,
LocalReplayBuffer actor wrapper :17/:302 used by DQN-family agents).
TPU-first posture: storage is preallocated contiguous numpy rings per
key, so sample() is one fancy-index gather producing exactly the
[batch, ...] layout the jitted learner consumes — no per-transition
Python objects.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    """Uniform ring-buffer replay. Use directly, or as an actor via
    ``ray_tpu.remote(ReplayBuffer).remote(capacity)``."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = int(capacity)
        self._store: Optional[Dict[str, np.ndarray]] = None
        self._next = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)
        self.num_added = 0

    def _allocate(self, batch: Dict[str, np.ndarray]) -> None:
        self._store = {
            k: np.zeros((self.capacity,) + v.shape[1:], dtype=v.dtype)
            for k, v in batch.items()}

    def add(self, batch: Dict[str, np.ndarray]) -> int:
        """Append a batch of transitions ({key: [n, ...]}); returns the
        current size."""
        batch = {k: np.asarray(v) for k, v in batch.items()}
        n = len(next(iter(batch.values())))
        if self._store is None:
            self._allocate(batch)
        for start in range(0, n, self.capacity):
            chunk = {k: v[start:start + self.capacity]
                     for k, v in batch.items()}
            m = len(next(iter(chunk.values())))
            idx = (self._next + np.arange(m)) % self.capacity
            for k, v in chunk.items():
                self._store[k][idx] = v
            self._next = int((self._next + m) % self.capacity)
            self._size = int(min(self._size + m, self.capacity))
        self.num_added += n
        return self._size

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        """Uniform sample with replacement → {key: [batch, ...]}."""
        if self._size == 0:
            raise ValueError("cannot sample from an empty buffer")
        idx = self._rng.integers(0, self._size, size=batch_size)
        return {k: v[idx] for k, v in self._store.items()}

    def __len__(self) -> int:
        return self._size

    def size(self) -> int:
        return self._size

    def stats(self) -> dict:
        return {"size": self._size, "capacity": self.capacity,
                "num_added": self.num_added}
