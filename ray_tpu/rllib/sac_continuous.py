"""SAC (continuous actions): squashed-Gaussian actor + twin Q(s, a).

Parity target: the reference's SAC proper
(reference: rllib/agents/sac/sac.py + sac_torch_policy.py — the
continuous-control algorithm: tanh-squashed Gaussian policy with
reparameterized sampling, twin critics over state-action pairs, Polyak
targets, entropy regularization; standard public formulation of
Haarnoja et al. 2018). The discrete variant lives in sac.py; this
module proves the NON-discrete action path of the library.

TPU-first: the optimization phase — K minibatch steps of actor +
twin-critic Adam updates with the Polyak blend — is ONE jitted
lax.scan program, like every other learner in the package. Sampling
runs on ContinuousTransitionWorker actors with the same replay
substrate (ReplayBuffer actor + execution-plan ops) as DQN/SAC-d.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rllib import execution
from ray_tpu.rllib.env import VectorEnv, make_env
from ray_tpu.rllib.replay_buffer import ReplayBuffer

LOG_STD_MIN, LOG_STD_MAX = -5.0, 2.0

DEFAULT_CONFIG: Dict[str, Any] = {
    "env": "Pendulum-v0",
    "num_workers": 1,
    "num_envs_per_worker": 16,
    "rollout_len": 8,
    "gamma": 0.99,
    "lr": 1e-3,
    "alpha": 0.2,                 # entropy temperature (fixed)
    "tau": 0.005,                 # Polyak target blend per sgd step
    "buffer_size": 100_000,
    "learning_starts": 512,
    # 32 updates per 128 env steps: SAC wants the update:env-step
    # ratio near 1:4 or denser — at 1:64 pendulum never improves
    "train_batch_size": 256,
    "num_sgd_steps": 32,
    "hidden": 64,
    "seed": 0,
}


def init_actor_params(key, obs_size: int, action_dim: int,
                      hidden: int = 64) -> Dict:
    from ray_tpu.rllib.models import _dense_init

    k1, k2, k3, k4 = jax.random.split(key, 4)
    return {"l1": _dense_init(k1, obs_size, hidden),
            "l2": _dense_init(k2, hidden, hidden),
            "mu": _dense_init(k3, hidden, action_dim, scale=0.01),
            "log_std": _dense_init(k4, hidden, action_dim, scale=0.01)}


def init_critic_params(key, obs_size: int, action_dim: int,
                       hidden: int = 64) -> Dict:
    from ray_tpu.rllib.models import _dense_init

    k1, k2, k3 = jax.random.split(key, 3)
    return {"l1": _dense_init(k1, obs_size + action_dim, hidden),
            "l2": _dense_init(k2, hidden, hidden),
            "q": _dense_init(k3, hidden, 1, scale=0.01)}


def actor_forward(params, obs):
    h = jnp.tanh(obs @ params["l1"]["w"] + params["l1"]["b"])
    h = jnp.tanh(h @ params["l2"]["w"] + params["l2"]["b"])
    mu = h @ params["mu"]["w"] + params["mu"]["b"]
    log_std = jnp.clip(h @ params["log_std"]["w"] +
                       params["log_std"]["b"], LOG_STD_MIN, LOG_STD_MAX)
    return mu, log_std


def critic_forward(params, obs, actions):
    x = jnp.concatenate([obs, actions], axis=-1)
    h = jnp.tanh(x @ params["l1"]["w"] + params["l1"]["b"])
    h = jnp.tanh(h @ params["l2"]["w"] + params["l2"]["b"])
    return (h @ params["q"]["w"] + params["q"]["b"])[..., 0]


def sample_squashed(params, obs, key, scale: float):
    """Reparameterized tanh-Gaussian sample with its log-prob:
    a = scale * tanh(u), u ~ N(mu, std) — the standard squashed
    log-density with the tanh + scale change-of-variables terms."""
    mu, log_std = actor_forward(params, obs)
    std = jnp.exp(log_std)
    u = mu + std * jax.random.normal(key, mu.shape)
    a = jnp.tanh(u)
    # N(u; mu, std) log-density
    logp = (-0.5 * ((u - mu) / std) ** 2 - log_std
            - 0.5 * jnp.log(2 * jnp.pi)).sum(-1)
    # tanh + scale jacobian: da = scale * (1 - tanh(u)^2) du
    logp -= (jnp.log(scale * (1 - a ** 2) + 1e-6)).sum(-1)
    return scale * a, logp


@functools.partial(jax.jit, static_argnames=("gamma", "alpha", "tau",
                                             "lr", "scale"))
def _sacc_update(params, target_params, opt_state, batches, key, *,
                 gamma, alpha, tau, lr, scale):
    """K SAC steps as one compiled program; ``params`` is the pytree
    {"pi": ..., "q1": ..., "q2": ...}, targets hold q1/q2."""
    import optax

    optimizer = optax.adam(lr)

    def losses(p, tp, mb, k):
        k1, k2 = jax.random.split(k)
        # critic target: soft value of s' under the CURRENT policy
        a_next, logp_next = sample_squashed(p["pi"], mb["next_obs"],
                                            k1, scale)
        q_t = jnp.minimum(
            critic_forward(tp["q1"], mb["next_obs"], a_next),
            critic_forward(tp["q2"], mb["next_obs"], a_next))
        target = mb["rewards"] + gamma * (1.0 - mb["dones"]) * \
            jax.lax.stop_gradient(q_t - alpha * logp_next)
        acts = mb["actions"].reshape(mb["rewards"].shape[0], -1)
        critic = ((critic_forward(p["q1"], mb["obs"], acts) - target)
                  ** 2).mean() + \
                 ((critic_forward(p["q2"], mb["obs"], acts) - target)
                  ** 2).mean()
        # actor: maximize E[min Q(s, a_new) - alpha logp]
        a_new, logp_new = sample_squashed(p["pi"], mb["obs"], k2, scale)
        q_new = jnp.minimum(
            critic_forward(jax.lax.stop_gradient(p["q1"]), mb["obs"],
                           a_new),
            critic_forward(jax.lax.stop_gradient(p["q2"]), mb["obs"],
                           a_new))
        actor = (alpha * logp_new - q_new).mean()
        return critic + actor, -logp_new.mean()

    def step(carry, inp):
        p, tp, opt_state = carry
        mb, k = inp
        (loss, entropy), grads = jax.value_and_grad(
            losses, has_aux=True)(p, tp, mb, k)
        updates, opt_state = optimizer.update(grads, opt_state, p)
        p = optax.apply_updates(p, updates)
        tp = jax.tree.map(lambda t, o: (1 - tau) * t + tau * o,
                          tp, {"q1": p["q1"], "q2": p["q2"]})
        return (p, tp, opt_state), (loss, entropy)

    n_steps = jax.tree.leaves(batches)[0].shape[0]
    keys = jax.random.split(key, n_steps)
    (params, target_params, opt_state), (losses_k, entropies) = \
        jax.lax.scan(step, (params, target_params, opt_state),
                     (batches, keys))
    return params, target_params, opt_state, jnp.mean(losses_k), \
        jnp.mean(entropies)


class ContinuousTransitionWorker:
    """Transition sampler for continuous actions: the behavior policy
    is the actor's own tanh-Gaussian (reference: rollout_worker
    sampling with the SAC policy's stochastic forward). Shares the
    (obs, action, reward, next_obs, done) layout with
    TransitionWorker so the ReplayBuffer and execution ops are
    unchanged."""

    def __init__(self, env_name, num_envs: int, rollout_len: int,
                 seed: int = 0):
        self.env = make_env(env_name, num_envs)
        if not isinstance(self.env, VectorEnv) or \
                not getattr(self.env, "continuous", False):
            raise ValueError("needs a continuous-action VectorEnv")
        self.num_envs = num_envs
        self.rollout_len = rollout_len
        self._key = jax.random.key(seed)
        self._scale = float(self.env.action_high)
        self._sample = jax.jit(functools.partial(
            sample_squashed, scale=self._scale))
        self.obs = self.env.reset(seed)
        self.params = None
        self._ep_return = np.zeros(num_envs, dtype=np.float32)
        self._finished_returns: List[float] = []

    def set_weights(self, params) -> None:
        self.params = params

    def sample(self) -> Dict[str, np.ndarray]:
        T, B = self.rollout_len, self.num_envs
        obs_dim = self.env.observation_size
        adim = self.env.action_dim
        out = {
            "obs": np.zeros((T * B, obs_dim), np.float32),
            "actions": np.zeros((T * B, adim), np.float32),
            "rewards": np.zeros((T * B,), np.float32),
            "next_obs": np.zeros((T * B, obs_dim), np.float32),
            "dones": np.zeros((T * B,), np.float32),
        }
        for t in range(T):
            self._key, sub = jax.random.split(self._key)
            actions, _ = self._sample(self.params, self.obs, sub)
            actions = np.asarray(actions)
            nxt, reward, done = self.env.step(actions)
            sl = slice(t * B, (t + 1) * B)
            out["obs"][sl] = self.obs
            out["actions"][sl] = actions.reshape(B, adim)
            out["rewards"][sl] = reward
            out["next_obs"][sl] = nxt
            out["dones"][sl] = done
            self._ep_return += reward
            if done.any():
                self._finished_returns.extend(
                    self._ep_return[done].tolist())
                self._ep_return[done] = 0.0
            self.obs = nxt
        return out

    def episode_returns(self, clear: bool = True) -> List[float]:
        out = list(self._finished_returns)
        if clear:
            self._finished_returns.clear()
        return out


def _setup(self, cfg: Dict[str, Any]) -> None:
    import optax

    probe = make_env(cfg["env"], 1)
    keys = jax.random.split(jax.random.key(cfg["seed"]), 3)
    self.params = {
        "pi": init_actor_params(keys[0], probe.observation_size,
                                probe.action_dim, cfg["hidden"]),
        "q1": init_critic_params(keys[1], probe.observation_size,
                                 probe.action_dim, cfg["hidden"]),
        "q2": init_critic_params(keys[2], probe.observation_size,
                                 probe.action_dim, cfg["hidden"]),
    }
    self.target_params = {"q1": self.params["q1"],
                          "q2": self.params["q2"]}
    self._opt_state = optax.adam(cfg["lr"]).init(self.params)
    self._scale = float(probe.action_high)
    self._key = jax.random.key(cfg["seed"] + 7)
    self.buffer = ray_tpu.remote(ReplayBuffer).options(
        num_cpus=0).remote(cfg["buffer_size"], seed=cfg["seed"])
    cls = ray_tpu.remote(ContinuousTransitionWorker)
    self.workers = [
        cls.remote(cfg["env"], cfg["num_envs_per_worker"],
                   cfg["rollout_len"], seed=i + 1)
        for i in range(cfg["num_workers"])]
    self._counters = {"timesteps_total": 0, "buffer_size": 0}


def _ingest(self, batch):
    self._counters["timesteps_total"] += len(batch["obs"])
    self._counters["buffer_size"] = int(
        ray_tpu.get(self.buffer.add.remote(batch)))
    return batch


def _learn(self, stacked) -> Dict[str, Any]:
    if stacked is None:
        return {"loss": float("nan")}
    cfg = self.config
    self._key, sub = jax.random.split(self._key)
    (self.params, self.target_params, self._opt_state, loss,
     entropy) = _sacc_update(
        self.params, self.target_params, self._opt_state, stacked, sub,
        gamma=cfg["gamma"], alpha=cfg["alpha"], tau=cfg["tau"],
        lr=cfg["lr"], scale=self._scale)
    return {"loss": float(loss), "entropy": float(entropy)}


def _execution_plan(self):
    cfg = self.config
    replay = execution.Replay(
        self.buffer, train_batch_size=cfg["train_batch_size"],
        num_steps=cfg["num_sgd_steps"],
        learning_starts=cfg["learning_starts"],
        size_fn=lambda: self._counters["buffer_size"])
    learn = execution.TrainOneStep(replay, lambda b: _learn(self, b))
    rollouts = execution.ParallelRollouts(
        self.workers, mode="bulk_sync",
        weights=lambda: self.params["pi"])
    store = execution.ForEach(rollouts, lambda b: _ingest(self, b))
    plan = execution.Concurrently([store, learn], output=1)
    return execution.StandardMetricsReporting(
        plan, self.workers, self._counters)


def _get_state(self) -> dict:
    return {"params": self.params, "target_params": self.target_params,
            "opt_state": self._opt_state,
            "timesteps": self._counters["timesteps_total"]}


def _set_state(self, state: dict) -> None:
    self.params = state["params"]
    self.target_params = state["target_params"]
    self._opt_state = state["opt_state"]
    self._counters["timesteps_total"] = state["timesteps"]


ContinuousSACTrainer = execution.build_trainer(
    name="ContinuousSACTrainer", default_config=DEFAULT_CONFIG,
    setup=_setup, execution_plan=_execution_plan, get_state=_get_state,
    set_state=_set_state)
