"""RL library: distributed rollouts + jitted JAX learners.

Parity target: the reference's RLlib layering (reference: rllib/ —
Trainer agents/trainer.py:513, RolloutWorker
evaluation/rollout_worker.py:105, WorkerSet evaluation/worker_set.py,
Policy policy/policy.py). Scope: the architecture (vector envs →
rollout-worker actors → WorkerSet → jitted learner → Tune-compatible
Trainer) with the execution-plan dataflow layer (execution.py,
reference: rllib/execution/* ops + trainer_template.py) and the
algorithm families proving it generalizes: PPO (sync on-policy), A2C
and PG (build_trainer compositions, reference: rllib/agents/a3c/a2c.py
+ agents/pg/pg.py), DQN with double-Q (replay off-policy + offline IO,
reference: rllib/agents/dqn + rllib/execution/replay_buffer.py +
rllib/offline/), SAC-discrete (twin critics + entropy regularization,
reference: rllib/agents/sac), SAC-continuous (squashed-Gaussian actor
+ twin Q(s, a) — the non-discrete action path, reference:
rllib/agents/sac continuous), TD3 (deterministic actor, smoothed
targets, delayed policy updates — reference: rllib/agents/ddpg/td3.py),
and IMPALA-lite (async on-policy with importance weighting). Cross-cutting seams: the model catalog
(models.py — MLP/CNN/GRU trunks by config, reference:
rllib/models/catalog.py:71) feeding every trainer, and the
multi-agent stack (multi_agent.py — MultiAgentVectorEnv + per-agent
policy mapping + MA-PPO, reference: rllib/env/multi_agent_env.py:9).
"""

from ray_tpu.rllib import execution  # noqa: F401

from ray_tpu.rllib.env import ENV_REGISTRY, CartPole, VectorEnv  # noqa: F401
from ray_tpu.rllib.policy import (  # noqa: F401
    compute_gae,
    init_policy_params,
    ppo_loss,
    sample_actions,
)
from ray_tpu.rllib.a2c import A2CTrainer, PGTrainer  # noqa: F401
from ray_tpu.rllib.dqn import DQNTrainer  # noqa: F401
from ray_tpu.rllib.models import (  # noqa: F401
    MODEL_DEFAULTS,
    freeze_model_config,
)
from ray_tpu.rllib.multi_agent import (  # noqa: F401
    MultiAgentPPOTrainer,
    MultiAgentRolloutWorker,
    MultiAgentVectorEnv,
)
from ray_tpu.rllib.sac import SACTrainer  # noqa: F401
from ray_tpu.rllib.sac_continuous import ContinuousSACTrainer  # noqa: F401
from ray_tpu.rllib.td3 import TD3Trainer  # noqa: F401
from ray_tpu.rllib.execution import Trainer, build_trainer  # noqa: F401
from ray_tpu.rllib.impala import ImpalaTrainer  # noqa: F401
from ray_tpu.rllib.offline import JsonReader, JsonWriter  # noqa: F401
from ray_tpu.rllib.ppo import DEFAULT_CONFIG, PPOTrainer  # noqa: F401
from ray_tpu.rllib.replay_buffer import (  # noqa: F401
    PrioritizedReplayBuffer,
    ReplayBuffer,
)
from ray_tpu.rllib.rollout_worker import (  # noqa: F401
    RolloutWorker,
    TransitionWorker,
    WorkerSet,
)
