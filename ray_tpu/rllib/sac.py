"""SAC-discrete: entropy-regularized off-policy learning with twin
critics.

Parity target: the reference's SAC family (reference:
rllib/agents/sac/sac.py — a trainer_template composition over the
replay execution ops, with twin Q networks and an entropy term; the
discrete-action variant follows the standard public formulation of
Christodoulou 2019, "Soft Actor-Critic for Discrete Action Settings").
TPU-first re-design: the whole optimization phase — K steps of policy
+ twin-critic Adam updates and the Polyak target blend — is ONE jitted
program via lax.scan over pre-gathered replay minibatches.  Alpha is a
fixed config entropy temperature (the reference's autotuned-alpha
variant is a config knob left out of scope).

Shares everything with the DQN family: env registry, stochastic
TransitionWorker sampling (softmax behavior policy), ReplayBuffer
actor, execution-plan ops, and the Tune trainable contract via
build_trainer.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp

import ray_tpu
from ray_tpu.rllib import execution
from ray_tpu.rllib.dqn import init_q_params, q_values
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.replay_buffer import ReplayBuffer
from ray_tpu.rllib.rollout_worker import TransitionWorker

DEFAULT_CONFIG: Dict[str, Any] = {
    "env": "Chain-v0",
    "num_workers": 1,
    "num_envs_per_worker": 8,
    "rollout_len": 32,
    "gamma": 0.99,
    "lr": 5e-3,
    "alpha": 0.05,                # entropy temperature (fixed)
    "tau": 0.01,                  # Polyak target blend per sgd step
    "buffer_size": 50_000,
    "learning_starts": 256,
    "train_batch_size": 128,
    "num_sgd_steps": 8,
    "hidden": 64,
    "seed": 0,
}


def _policy_logits(params, obs):
    return q_values(params, obs)  # same MLP shape, logits head


@functools.partial(jax.jit, static_argnames=("gamma", "alpha", "tau",
                                             "lr"))
def _sac_update(params, target_params, opt_state, batches, *,
                gamma, alpha, tau, lr):
    """K SAC-discrete steps as one compiled program.  ``params`` is the
    pytree {"pi": ..., "q1": ..., "q2": ...}; targets hold q1/q2."""
    import optax

    optimizer = optax.adam(lr)

    def losses(p, tp, mb):
        logits = _policy_logits(p["pi"], mb["obs"])
        logp = jax.nn.log_softmax(logits)
        probs = jnp.exp(logp)
        q1 = q_values(p["q1"], mb["obs"])
        q2 = q_values(p["q2"], mb["obs"])
        qmin = jnp.minimum(q1, q2)

        # critic target: soft state value of s' under the CURRENT policy
        logits_n = _policy_logits(p["pi"], mb["next_obs"])
        logp_n = jax.nn.log_softmax(logits_n)
        probs_n = jnp.exp(logp_n)
        q1_t = q_values(tp["q1"], mb["next_obs"])
        q2_t = q_values(tp["q2"], mb["next_obs"])
        v_next = (probs_n * (jnp.minimum(q1_t, q2_t)
                             - alpha * logp_n)).sum(-1)
        target = mb["rewards"] + gamma * (1.0 - mb["dones"]) * \
            jax.lax.stop_gradient(v_next)

        idx = jnp.arange(q1.shape[0])
        act = mb["actions"]
        critic = ((q1[idx, act] - target) ** 2).mean() + \
                 ((q2[idx, act] - target) ** 2).mean()
        # policy: minimize E_pi[alpha*logp - Qmin] (expectation exact
        # over the discrete action set)
        actor = (probs * (alpha * logp
                          - jax.lax.stop_gradient(qmin))).sum(-1).mean()
        entropy = -(probs * logp).sum(-1).mean()
        return critic + actor, entropy

    def step(carry, mb):
        p, tp, opt_state = carry
        (loss, entropy), grads = jax.value_and_grad(
            losses, has_aux=True)(p, tp, mb)
        updates, opt_state = optimizer.update(grads, opt_state, p)
        p = optax.apply_updates(p, updates)
        tp = jax.tree.map(lambda t, o: (1 - tau) * t + tau * o,
                          tp, {"q1": p["q1"], "q2": p["q2"]})
        return (p, tp, opt_state), (loss, entropy)

    (params, target_params, opt_state), (losses_k, entropies) = \
        jax.lax.scan(step, (params, target_params, opt_state), batches)
    return params, target_params, opt_state, jnp.mean(losses_k), \
        jnp.mean(entropies)


def _setup(self, cfg: Dict[str, Any]) -> None:
    import optax

    probe = make_env(cfg["env"], 1)
    keys = jax.random.split(jax.random.key(cfg["seed"]), 3)
    mk = functools.partial(init_q_params, obs_size=probe.observation_size,
                           num_actions=probe.num_actions,
                           hidden=cfg["hidden"])
    self.params = {"pi": mk(keys[0]), "q1": mk(keys[1]),
                   "q2": mk(keys[2])}
    self.target_params = {"q1": self.params["q1"],
                          "q2": self.params["q2"]}
    self._opt_state = optax.adam(cfg["lr"]).init(self.params)
    self.buffer = ray_tpu.remote(ReplayBuffer).options(
        num_cpus=0).remote(cfg["buffer_size"], seed=cfg["seed"])
    cls = ray_tpu.remote(TransitionWorker)
    self.workers = [
        cls.remote(cfg["env"], cfg["num_envs_per_worker"],
                   cfg["rollout_len"], _policy_logits, seed=i + 1,
                   stochastic=True)
        for i in range(cfg["num_workers"])]
    self._counters = {"timesteps_total": 0, "buffer_size": 0}


def _ingest(self, batch):
    self._counters["timesteps_total"] += len(batch["obs"])
    self._counters["buffer_size"] = int(
        ray_tpu.get(self.buffer.add.remote(batch)))
    return batch


def _learn(self, stacked) -> Dict[str, Any]:
    if stacked is None:
        return {"loss": float("nan")}
    cfg = self.config
    (self.params, self.target_params, self._opt_state, loss,
     entropy) = _sac_update(
        self.params, self.target_params, self._opt_state, stacked,
        gamma=cfg["gamma"], alpha=cfg["alpha"], tau=cfg["tau"],
        lr=cfg["lr"])
    return {"loss": float(loss), "entropy": float(entropy)}


def _execution_plan(self):
    cfg = self.config
    replay = execution.Replay(
        self.buffer, train_batch_size=cfg["train_batch_size"],
        num_steps=cfg["num_sgd_steps"],
        learning_starts=cfg["learning_starts"],
        size_fn=lambda: self._counters["buffer_size"])
    learn = execution.TrainOneStep(replay, lambda b: _learn(self, b))
    rollouts = execution.ParallelRollouts(
        self.workers, mode="bulk_sync",
        weights=lambda: self.params["pi"],
        sample_args=lambda: (0.0,))
    store = execution.ForEach(rollouts, lambda b: _ingest(self, b))
    plan = execution.Concurrently([store, learn], output=1)
    return execution.StandardMetricsReporting(
        plan, self.workers, self._counters)


def _get_state(self) -> dict:
    return {"params": self.params, "target_params": self.target_params,
            "opt_state": self._opt_state,
            "timesteps": self._counters["timesteps_total"]}


def _set_state(self, state: dict) -> None:
    self.params = state["params"]
    self.target_params = state["target_params"]
    self._opt_state = state["opt_state"]
    self._counters["timesteps_total"] = state["timesteps"]


SACTrainer = execution.build_trainer(
    name="SACTrainer", default_config=DEFAULT_CONFIG, setup=_setup,
    execution_plan=_execution_plan, get_state=_get_state,
    set_state=_set_state)
