"""PPOTrainer: distributed rollouts + a jitted minibatch-SGD learner.

Parity target: the reference's Trainer/PPO
(reference: rllib/agents/trainer.py:513 — train :645 — and
rllib/agents/ppo/ppo.py). TPU-first re-design: sampling fans out over
RolloutWorker actors (the task/actor runtime), the learner is ONE
jitted update (epoch x minibatch loop via lax.scan inside jit, Adam
from optax) so the whole optimization phase is a single device
program. ``PPOTrainer`` also satisfies the Tune trainable contract
(train() -> result dict, save/restore), like the reference's
Trainer-is-a-Trainable layering.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp

from ray_tpu.rllib import execution
from ray_tpu.rllib.common import (
    actor_critic_get_state,
    actor_critic_set_state,
    actor_critic_setup,
    onpolicy_execution_plan,
)
from ray_tpu.rllib.policy import ppo_loss

DEFAULT_CONFIG: Dict[str, Any] = {
    "env": "CartPole-v0",
    "num_workers": 2,
    "num_envs_per_worker": 8,
    "rollout_len": 128,
    "gamma": 0.99,
    "lambda": 0.95,
    "lr": 3e-4,
    "clip": 0.2,
    "vf_coeff": 0.5,
    "entropy_coeff": 0.01,
    "num_sgd_epochs": 4,
    "minibatch_size": 256,
    "model": None,                # model-catalog config (models.py)
    "seed": 0,
}


@functools.partial(
    jax.jit,
    static_argnames=("num_epochs", "num_minibatches", "clip",
                     "vf_coeff", "ent_coeff", "model"))
def _ppo_update(params, opt_state, batch, key, *, num_epochs,
                num_minibatches, clip, vf_coeff, ent_coeff, lr,
                model=None):
    """The whole PPO optimization phase as one compiled program:
    (epochs x minibatches) of Adam steps via nested lax.scan."""
    import optax

    optimizer = optax.adam(lr)
    n = batch["obs"].shape[0]
    mb = n // num_minibatches

    def minibatch_step(carry, idx):
        params, opt_state = carry
        sub = {k: v[idx] for k, v in batch.items()}
        # advantage normalization per minibatch (standard practice)
        adv = sub["advantages"]
        sub = dict(sub, advantages=(adv - adv.mean()) /
                   (adv.std() + 1e-8))
        (loss, aux), grads = jax.value_and_grad(
            ppo_loss, has_aux=True)(params, sub, clip=clip,
                                    vf_coeff=vf_coeff,
                                    ent_coeff=ent_coeff, model=model)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), (loss, aux["entropy"])

    def epoch_step(carry, key):
        perm = jax.random.permutation(key, n)[:num_minibatches * mb]
        idxs = perm.reshape(num_minibatches, mb)
        return jax.lax.scan(minibatch_step, carry, idxs)

    keys = jax.random.split(key, num_epochs)
    (params, opt_state), (losses, entropies) = jax.lax.scan(
        epoch_step, (params, opt_state), keys)
    return params, opt_state, jnp.mean(losses), jnp.mean(entropies)


class PPOTrainer(execution.Trainer):
    """Sync on-policy shape of the execution-plan substrate
    (reference: ppo.py's execution_plan = ParallelRollouts |>
    TrainOneStep |> StandardMetricsReporting). Also a Tune trainable
    via the template."""

    default_config = DEFAULT_CONFIG

    def setup(self, cfg: Dict[str, Any]) -> None:
        actor_critic_setup(self, cfg)
        self._key = jax.random.key(cfg["seed"] + 1)

    def execution_plan(self):
        return onpolicy_execution_plan(self, self._learn_on_batch)

    def _learn_on_batch(self, batch) -> Dict[str, Any]:
        cfg = self.config
        num_minibatches = max(
            1, len(batch["obs"]) // cfg["minibatch_size"])
        self._key, sub = jax.random.split(self._key)
        self.params, self._opt_state, loss, entropy = _ppo_update(
            self.params, self._opt_state,
            {k: jnp.asarray(v) for k, v in batch.items()}, sub,
            num_epochs=cfg["num_sgd_epochs"],
            num_minibatches=num_minibatches, clip=cfg["clip"],
            vf_coeff=cfg["vf_coeff"], ent_coeff=cfg["entropy_coeff"],
            lr=cfg["lr"], model=self.model)
        return {"loss": float(loss), "entropy": float(entropy)}

    get_state = actor_critic_get_state
    set_state = actor_critic_set_state
