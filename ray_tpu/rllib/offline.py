"""Offline experience IO: write sampled batches to disk, read them back.

Parity target: the reference's offline dataset plane
(reference: rllib/offline/json_writer.py JsonWriter,
rllib/offline/json_reader.py JsonReader — Trainer config
``output``/``input``). Batches are JSON-lines files, one sample batch
per line with base64 numpy payloads — portable, appendable, and
streamable back into a replay buffer for offline training.
"""

from __future__ import annotations

import base64
import glob
import io
import json
import os
import time
from typing import Dict, Iterator, List, Optional

import numpy as np


def _encode(arr: np.ndarray) -> dict:
    buf = io.BytesIO()
    np.save(buf, np.asarray(arr), allow_pickle=False)
    return {"__npy__": base64.b64encode(buf.getvalue()).decode()}


def _decode(obj: dict) -> np.ndarray:
    return np.load(io.BytesIO(base64.b64decode(obj["__npy__"])),
                   allow_pickle=False)


class JsonWriter:
    """Append sample batches to ``<dir>/batches-<ts>.jsonl``."""

    def __init__(self, output_dir: str, max_file_size: int = 64 << 20):
        self.output_dir = output_dir
        self.max_file_size = max_file_size
        os.makedirs(output_dir, exist_ok=True)
        self._file = None
        self._path = ""

    def _roll(self) -> None:
        if self._file is not None:
            self._file.close()
        self._path = os.path.join(
            self.output_dir,
            f"batches-{int(time.time() * 1000)}-{os.getpid()}.jsonl")
        self._file = open(self._path, "a")

    def write(self, batch: Dict[str, np.ndarray]) -> None:
        if self._file is None or (
                self._file.tell() > self.max_file_size):
            self._roll()
        record = {k: _encode(v) for k, v in batch.items()}
        self._file.write(json.dumps(record) + "\n")
        self._file.flush()

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None


class JsonReader:
    """Iterate sample batches from every ``*.jsonl`` under a dir."""

    def __init__(self, input_dir: str):
        self.paths: List[str] = sorted(
            glob.glob(os.path.join(input_dir, "*.jsonl")))
        if not self.paths:
            raise FileNotFoundError(
                f"no offline batch files under {input_dir!r}")

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        for path in self.paths:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    record = json.loads(line)
                    yield {k: _decode(v) for k, v in record.items()}

    def read_all(self) -> Optional[Dict[str, np.ndarray]]:
        """Concatenate every batch into one ({} keys must match)."""
        batches = list(self)
        if not batches:
            return None
        return {k: np.concatenate([b[k] for b in batches])
                for k in batches[0]}
