"""RolloutWorker: an actor that owns envs + a policy copy and samples.

Parity target: the reference's RolloutWorker + WorkerSet
(reference: rllib/evaluation/rollout_worker.py:105 — sample :726,
get_weights/set_weights — and rllib/evaluation/worker_set.py:31).

TPU-first: with a jax-native env the WHOLE rollout (policy sampling +
env stepping, T steps) is one jitted ``lax.scan`` — a single device
program per sample() call. Numpy ``VectorEnv``s fall back to per-step
stepping (the generic external-env path).
"""

from __future__ import annotations

import functools
from typing import Dict, List

import jax
import numpy as np

import ray_tpu
from ray_tpu.rllib.env import VectorEnv, make_env
from ray_tpu.rllib.policy import (
    compute_gae, init_policy_params, logits_and_value, sample_actions,
)


@functools.partial(jax.jit, static_argnames=("env", "T", "model"))
def _device_rollout(params, state, steps, key, *, env, T, model=None):
    """[T]-step rollout fully on device: scan(policy→env)."""
    def body(carry, _):
        state, steps, key = carry
        key, k_act, k_env = jax.random.split(key, 3)
        obs = env.obs(state)
        actions, logp, value = sample_actions(params, obs, k_act,
                                              model=model)
        state, steps, reward, done = env.step(state, steps, actions,
                                              k_env)
        return ((state, steps, key),
                (obs, actions, logp, value, reward, done))

    (state, steps, key), traj = jax.lax.scan(
        body, (state, steps, key), None, length=T)
    _, last_value = logits_and_value(params, env.obs(state), model)
    return state, steps, key, traj, last_value


class RolloutWorker:
    """Runs as an actor; one instance steps ``num_envs`` episodes."""

    def __init__(self, env_name, num_envs: int, rollout_len: int,
                 seed: int = 0, gamma: float = 0.99, lam: float = 0.95,
                 model=None):
        import jax

        self.env = make_env(env_name, num_envs)
        self.num_envs = num_envs
        self.rollout_len = rollout_len
        self.gamma, self.lam = gamma, lam
        self.model = model  # frozen catalog spec (models.py) or None
        self._key = jax.random.key(seed)
        self._jax_env = not isinstance(self.env, VectorEnv)
        if self._jax_env:
            self._key, sub = jax.random.split(self._key)
            self._state, self._steps = self.env.reset(sub, num_envs)
        else:
            self.obs = self.env.reset(seed)
        self.params = init_policy_params(
            jax.random.key(0), self.env.observation_size,
            self.env.num_actions, model=model)
        # episode-return bookkeeping for metrics
        self._ep_return = np.zeros(num_envs, dtype=np.float32)
        self._finished_returns: List[float] = []

    def set_weights(self, params) -> None:
        self.params = params

    def sample(self) -> Dict[str, np.ndarray]:
        """One rollout of [T, B] transitions with GAE advantages."""
        if self._jax_env:
            return self._sample_device()
        return self._sample_host()

    def _sample_device(self) -> Dict[str, np.ndarray]:
        self._state, self._steps, self._key, traj, last_value = \
            _device_rollout(self.params, self._state, self._steps,
                            self._key, env=self.env,
                            T=self.rollout_len, model=self.model)
        obs, actions, logp, value, reward, done = \
            (np.asarray(a) for a in traj)
        self._track_returns(reward, done)
        adv, ret = compute_gae(reward, value, done,
                               np.asarray(last_value),
                               gamma=self.gamma, lam=self.lam)
        flat = lambda a: a.reshape(-1, *a.shape[2:])  # noqa: E731
        return {
            "obs": flat(obs), "actions": flat(actions),
            "logp_old": flat(logp), "advantages": flat(adv),
            "returns": flat(ret),
        }

    def _sample_host(self) -> Dict[str, np.ndarray]:
        import jax

        T, B = self.rollout_len, self.num_envs
        obs_buf = np.zeros((T, B, self.env.observation_size), np.float32)
        act_buf = np.zeros((T, B), np.int32)
        logp_buf = np.zeros((T, B), np.float32)
        val_buf = np.zeros((T, B), np.float32)
        rew_buf = np.zeros((T, B), np.float32)
        done_buf = np.zeros((T, B), np.float32)

        for t in range(T):
            self._key, sub = jax.random.split(self._key)
            actions, logp, value = sample_actions(
                self.params, self.obs, sub, model=self.model)
            actions = np.asarray(actions)
            obs_buf[t] = self.obs
            act_buf[t] = actions
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(value)
            self.obs, reward, done = self.env.step(actions)
            rew_buf[t] = reward
            done_buf[t] = done
        self._track_returns(rew_buf, done_buf)

        _, _, last_value = sample_actions(self.params, self.obs,
                                          self._key, model=self.model)
        adv, ret = compute_gae(rew_buf, val_buf, done_buf,
                               np.asarray(last_value),
                               gamma=self.gamma, lam=self.lam)
        flat = lambda a: a.reshape(-1, *a.shape[2:])  # noqa: E731
        return {
            "obs": flat(obs_buf), "actions": flat(act_buf),
            "logp_old": flat(logp_buf), "advantages": flat(adv),
            "returns": flat(ret),
        }

    def _track_returns(self, rewards: np.ndarray,
                       dones: np.ndarray) -> None:
        for t in range(rewards.shape[0]):
            self._ep_return += rewards[t]
            done = dones[t].astype(bool)
            if done.any():
                self._finished_returns.extend(
                    self._ep_return[done].tolist())
                self._ep_return[done] = 0.0

    def episode_returns(self, clear: bool = True) -> List[float]:
        out = list(self._finished_returns)
        if clear:
            self._finished_returns.clear()
        return out


class TransitionWorker:
    """Value-based sampling twin of RolloutWorker: collects
    (obs, action, reward, next_obs, done) transitions with an
    epsilon-greedy policy over a Q-network — the sample source for
    DQN-family learners feeding a replay buffer (reference:
    rollout_worker.py sampling for rllib/agents/dqn). Shares the env
    registry and episode bookkeeping with RolloutWorker."""

    def __init__(self, env_name, num_envs: int, rollout_len: int,
                 q_fn, seed: int = 0, stochastic: bool = False):
        self.env = make_env(env_name, num_envs)
        if not isinstance(self.env, VectorEnv):
            raise ValueError(
                "TransitionWorker samples numpy VectorEnvs; jax-native "
                "envs belong to the fused on-device path")
        self.num_envs = num_envs
        self.rollout_len = rollout_len
        self._q_fn = jax.jit(q_fn)
        # stochastic=True: sample from softmax(q_fn output) — the
        # behavior policy for entropy-regularized learners (SAC);
        # False: epsilon-greedy over argmax (DQN family)
        self._stochastic = stochastic
        self._rng = np.random.default_rng(seed)
        self.obs = self.env.reset(seed)
        self.params = None
        self._ep_return = np.zeros(num_envs, dtype=np.float32)
        self._finished_returns: List[float] = []

    def set_weights(self, params) -> None:
        self.params = params

    def sample(self, epsilon: float) -> Dict[str, np.ndarray]:
        T, B = self.rollout_len, self.num_envs
        obs_dim = self.env.observation_size
        out = {
            "obs": np.zeros((T * B, obs_dim), np.float32),
            "actions": np.zeros((T * B,), np.int32),
            "rewards": np.zeros((T * B,), np.float32),
            "next_obs": np.zeros((T * B, obs_dim), np.float32),
            "dones": np.zeros((T * B,), np.float32),
        }
        for t in range(T):
            q = np.asarray(self._q_fn(self.params, self.obs))
            if self._stochastic:
                # categorical over softmax(logits): Gumbel-max trick
                # (vectorized, no per-row choice() loop)
                g = -np.log(-np.log(
                    self._rng.random(q.shape) + 1e-12) + 1e-12)
                actions = (q + g).argmax(axis=-1).astype(np.int32)
            else:
                greedy = q.argmax(axis=-1)
                explore = self._rng.random(B) < epsilon
                randa = self._rng.integers(0, self.env.num_actions,
                                           size=B)
                actions = np.where(explore, randa, greedy).astype(np.int32)
            nxt, reward, done = self.env.step(actions)
            sl = slice(t * B, (t + 1) * B)
            out["obs"][sl] = self.obs
            out["actions"][sl] = actions
            out["rewards"][sl] = reward
            # note: env auto-resets; next_obs for done steps is the
            # fresh episode's obs, masked out by (1 - done) in the
            # bootstrapped target, so this is correct.
            out["next_obs"][sl] = nxt
            out["dones"][sl] = done
            self._ep_return += reward
            if done.any():
                self._finished_returns.extend(
                    self._ep_return[done].tolist())
                self._ep_return[done] = 0.0
            self.obs = nxt
        return out

    def episode_returns(self, clear: bool = True) -> List[float]:
        out = list(self._finished_returns)
        if clear:
            self._finished_returns.clear()
        return out


class WorkerSet:
    """A set of RolloutWorker actors (reference: worker_set.py:31)."""

    def __init__(self, env_name, num_workers: int, num_envs: int,
                 rollout_len: int, gamma: float, lam: float,
                 model=None):
        cls = ray_tpu.remote(RolloutWorker)
        self.workers = [
            cls.remote(env_name, num_envs, rollout_len, seed=i + 1,
                       gamma=gamma, lam=lam, model=model)
            for i in range(num_workers)]

    def sample(self) -> Dict[str, np.ndarray]:
        """One synchronous gather-and-concat round (execution plans use
        execution.ParallelRollouts instead; this is the direct API)."""
        from ray_tpu.rllib.execution import concat_batches

        return concat_batches(
            ray_tpu.get([w.sample.remote() for w in self.workers]))

    def set_weights(self, params) -> None:
        ray_tpu.get([w.set_weights.remote(params)
                     for w in self.workers])

    def episode_returns(self) -> List[float]:
        out: List[float] = []
        for rs in ray_tpu.get(
                [w.episode_returns.remote() for w in self.workers]):
            out.extend(rs)
        return out
