"""Model catalog: network trunks chosen by config.

Parity target: the reference's ModelCatalog
(reference: rllib/models/catalog.py:71 get_model_v2 — the network is
picked from the model config, not hand-wired per algorithm; fcnet /
vision / recurrent variants live behind one seam). TPU-first
re-design: a model is (init(key, obs_size) -> (params, feat_size),
apply(params, obs) -> [B, feat]) of PURE functions over pytrees — the
policy/Q heads attach on top, and the whole thing stays inside the
caller's single jitted device program (the spec is a hashable frozen
tuple, safe as a jit static argument or a trace-time constant).

Trunks:
- ``mlp``: dense stack, ``hiddens``/``activation`` from the config.
- ``cnn``: conv stack over ``conv_input_shape`` (H, W, C) — flat obs
  are reshaped on device; MXU-friendly NHWC convs.
- ``gru``: recurrent encoder over a stacked observation window
  (``seq_len`` frames flattened into the obs vector, the functional
  analog of the reference's use_lstm wrapper) via one lax.scan.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

MODEL_DEFAULTS: Dict[str, Any] = {
    "type": "mlp",
    "hiddens": (64, 64),
    "activation": "tanh",
    # cnn
    "conv_input_shape": None,        # (H, W, C); required for type=cnn
    "conv_filters": ((16, 4, 2), (32, 3, 2)),  # (features, kernel, stride)
    # gru
    "seq_len": None,                 # frames per obs window (type=gru)
    "gru_hidden": 64,
}


def freeze_model_config(cfg: Optional[Dict[str, Any]]) -> tuple:
    """Model config -> canonical hashable spec (jit-static safe).
    Nested lists become tuples; key order is fixed."""
    merged = dict(MODEL_DEFAULTS)
    merged.update(cfg or {})
    unknown = set(merged) - set(MODEL_DEFAULTS)
    if unknown:
        raise ValueError(f"unknown model config keys: {sorted(unknown)}")

    def _freeze(v):
        if isinstance(v, (list, tuple)):
            return tuple(_freeze(x) for x in v)
        return v

    return tuple((k, _freeze(merged[k])) for k in sorted(merged))


def _get(spec: tuple, key: str):
    for k, v in spec:
        if k == key:
            return v
    raise KeyError(key)


def _act(name: str):
    return {"tanh": jnp.tanh, "relu": jax.nn.relu,
            "silu": jax.nn.silu}[name]


def _dense_init(key, fan_in: int, fan_out: int, scale=None):
    init = jax.nn.initializers.orthogonal(
        scale if scale is not None else np.sqrt(2))
    return {"w": init(key, (fan_in, fan_out), jnp.float32),
            "b": jnp.zeros((fan_out,))}


# ---------------------------------------------------------------- mlp

def _mlp_init(spec, key, obs_size):
    hiddens = _get(spec, "hiddens")
    layers, fan_in = [], obs_size
    for h in hiddens:
        key, sub = jax.random.split(key)
        layers.append(_dense_init(sub, fan_in, h))
        fan_in = h
    return {"layers": layers}, fan_in


def _mlp_apply(spec, params, obs):
    act = _act(_get(spec, "activation"))
    h = obs
    for layer in params["layers"]:
        h = act(h @ layer["w"] + layer["b"])
    return h


# ---------------------------------------------------------------- cnn

def _cnn_init(spec, key, obs_size):
    shape = _get(spec, "conv_input_shape")
    if shape is None:
        raise ValueError("type=cnn needs model config conv_input_shape")
    h, w, c = shape
    if h * w * c != obs_size:
        raise ValueError(
            f"conv_input_shape {shape} != obs_size {obs_size}")
    convs = []
    in_ch = c
    for feats, kernel, stride in _get(spec, "conv_filters"):
        key, sub = jax.random.split(key)
        convs.append({
            "w": jax.nn.initializers.orthogonal(np.sqrt(2))(
                sub, (kernel, kernel, in_ch, feats), jnp.float32),
            "b": jnp.zeros((feats,)),
        })
        h = math.ceil(h / stride)
        w = math.ceil(w / stride)
        in_ch = feats
    key, sub = jax.random.split(key)
    hiddens = _get(spec, "hiddens")
    feat = hiddens[-1] if hiddens else 64
    flat = h * w * in_ch
    return {"convs": convs, "out": _dense_init(sub, flat, feat)}, feat


def _cnn_apply(spec, params, obs):
    shape = _get(spec, "conv_input_shape")
    x = obs.reshape((obs.shape[0],) + tuple(shape))
    strides = [s for _, _, s in _get(spec, "conv_filters")]
    for conv, stride in zip(params["convs"], strides):
        x = jax.lax.conv_general_dilated(
            x, conv["w"], window_strides=(stride, stride),
            padding="SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
        x = jax.nn.relu(x + conv["b"])
    x = x.reshape(x.shape[0], -1)
    out = params["out"]
    return jax.nn.relu(x @ out["w"] + out["b"])


# ---------------------------------------------------------------- gru

def _gru_init(spec, key, obs_size):
    seq_len = _get(spec, "seq_len")
    if not seq_len:
        raise ValueError("type=gru needs model config seq_len")
    if obs_size % seq_len:
        raise ValueError(f"obs_size {obs_size} not divisible by "
                         f"seq_len {seq_len}")
    feat_in = obs_size // seq_len
    hidden = _get(spec, "gru_hidden")
    ks = jax.random.split(key, 3)
    glorot = jax.nn.initializers.glorot_uniform()
    # fused gate weights: [z | r | h~]
    return {
        "wx": glorot(ks[0], (feat_in, 3 * hidden), jnp.float32),
        "wh": glorot(ks[1], (hidden, 3 * hidden), jnp.float32),
        "b": jnp.zeros((3 * hidden,)),
    }, hidden


def _gru_apply(spec, params, obs):
    seq_len = _get(spec, "seq_len")
    hidden = _get(spec, "gru_hidden")
    b = obs.shape[0]
    xs = obs.reshape(b, seq_len, -1).swapaxes(0, 1)  # [L, B, F]

    def cell(h, x):
        gates_x = x @ params["wx"] + params["b"]
        gates_h = h @ params["wh"]
        zx, rx, nx = jnp.split(gates_x, 3, axis=-1)
        zh, rh, nh = jnp.split(gates_h, 3, axis=-1)
        z = jax.nn.sigmoid(zx + zh)
        r = jax.nn.sigmoid(rx + rh)
        n = jnp.tanh(nx + r * nh)
        h = (1 - z) * n + z * h
        return h, None

    h0 = jnp.zeros((b, hidden))
    h_last, _ = jax.lax.scan(cell, h0, xs)
    return h_last


_TRUNKS = {"mlp": (_mlp_init, _mlp_apply),
           "cnn": (_cnn_init, _cnn_apply),
           "gru": (_gru_init, _gru_apply)}


def init_trunk(spec: tuple, key, obs_size: int) -> Tuple[Dict, int]:
    """-> (trunk params, feature size). ``spec`` from
    freeze_model_config."""
    return _TRUNKS[_get(spec, "type")][0](spec, key, obs_size)


def apply_trunk(spec: tuple, params: Dict, obs) -> Any:
    """[B, obs_size] -> [B, feat]. Pure; safe inside any jit trace
    (``spec`` is a Python constant at trace time)."""
    return _TRUNKS[_get(spec, "type")][1](spec, params, obs)


# ------------------------------------------- catalog-backed policy/Q

def init_actor_critic(spec: tuple, key, obs_size: int,
                      num_actions: int) -> Dict:
    """Trunk + pi/vf heads (the catalog twin of
    policy.init_policy_params)."""
    k_t, k_pi, k_vf = jax.random.split(key, 3)
    trunk, feat = init_trunk(spec, k_t, obs_size)
    return {
        "trunk": trunk,
        "pi": _dense_init(k_pi, feat, num_actions, scale=0.01),
        "vf": _dense_init(k_vf, feat, 1),
    }


def actor_critic_forward(spec: tuple, params: Dict, obs):
    """-> (logits, value), catalog twin of policy.logits_and_value."""
    h = apply_trunk(spec, params["trunk"], obs)
    logits = h @ params["pi"]["w"] + params["pi"]["b"]
    value = (h @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
    return logits, value


def init_q_net(spec: tuple, key, obs_size: int, num_actions: int) -> Dict:
    k_t, k_q = jax.random.split(key)
    trunk, feat = init_trunk(spec, k_t, obs_size)
    return {"trunk": trunk,
            "q": _dense_init(k_q, feat, num_actions, scale=0.01)}


def q_net_forward(spec: tuple, params: Dict, obs):
    h = apply_trunk(spec, params["trunk"], obs)
    return h @ params["q"]["w"] + params["q"]["b"]
