"""Environments for the RL library.

Parity target: the reference's env abstractions (reference:
rllib/env/ — gym-style single envs wrapped into vectorized samplers,
rllib/env/vector_env.py). TPU-first re-design: the env protocol is
BATCHED and functional from the start — ``reset(key) -> state`` and
``step(state, actions) -> (state, obs, reward, done)`` over numpy
arrays — so a rollout worker steps a whole vector of episodes at once
and the data layout matches what the jitted learner consumes.

``CartPole`` is a dependency-free implementation of the classic
control task (dynamics per the public equations; no gym import).
"""

from __future__ import annotations

import numpy as np


class VectorEnv:
    """Batched env protocol."""

    num_envs: int
    observation_size: int
    num_actions: int

    def reset(self, seed: int = 0) -> np.ndarray:
        """→ obs [num_envs, observation_size]"""
        raise NotImplementedError

    def step(self, actions: np.ndarray):
        """→ (obs, reward, done) each [num_envs, ...]; done episodes
        auto-reset (their returned obs is the fresh episode's)."""
        raise NotImplementedError


class CartPole(VectorEnv):
    """Vectorized cartpole balance task (episode cap 200 steps)."""

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LEN = 0.5
    FORCE = 10.0
    DT = 0.02
    X_LIMIT = 2.4
    THETA_LIMIT = 12 * np.pi / 180
    MAX_STEPS = 200

    observation_size = 4
    num_actions = 2

    def __init__(self, num_envs: int = 16):
        self.num_envs = num_envs
        self._rng = np.random.default_rng(0)
        self._state = None
        self._steps = None

    def _fresh(self, n: int) -> np.ndarray:
        return self._rng.uniform(-0.05, 0.05, size=(n, 4))

    def reset(self, seed: int = 0) -> np.ndarray:
        self._rng = np.random.default_rng(seed)
        self._state = self._fresh(self.num_envs)
        self._steps = np.zeros(self.num_envs, dtype=np.int32)
        return self._state.astype(np.float32)

    def step(self, actions: np.ndarray):
        x, x_dot, theta, theta_dot = self._state.T
        force = np.where(actions == 1, self.FORCE, -self.FORCE)
        cos_t, sin_t = np.cos(theta), np.sin(theta)
        total_mass = self.CART_MASS + self.POLE_MASS
        pole_ml = self.POLE_MASS * self.POLE_HALF_LEN
        temp = (force + pole_ml * theta_dot ** 2 * sin_t) / total_mass
        theta_acc = (self.GRAVITY * sin_t - cos_t * temp) / (
            self.POLE_HALF_LEN *
            (4.0 / 3.0 - self.POLE_MASS * cos_t ** 2 / total_mass))
        x_acc = temp - pole_ml * theta_acc * cos_t / total_mass
        x = x + self.DT * x_dot
        x_dot = x_dot + self.DT * x_acc
        theta = theta + self.DT * theta_dot
        theta_dot = theta_dot + self.DT * theta_acc
        self._state = np.stack([x, x_dot, theta, theta_dot], axis=1)
        self._steps += 1

        done = ((np.abs(x) > self.X_LIMIT) |
                (np.abs(theta) > self.THETA_LIMIT) |
                (self._steps >= self.MAX_STEPS))
        reward = np.ones(self.num_envs, dtype=np.float32)
        if done.any():
            self._state[done] = self._fresh(int(done.sum()))
            self._steps[done] = 0
        return self._state.astype(np.float32), reward, done


class JaxCartPole:
    """Functional (jax-native) cartpole: the whole rollout runs inside
    ONE jitted ``lax.scan`` on device (the Anakin/Brax pattern — no
    per-step host↔device round trips, which dominate wall clock when
    the device sits behind a transfer boundary)."""

    observation_size = 4
    num_actions = 2
    MAX_STEPS = 200

    @staticmethod
    def reset(key, n):
        import jax

        state = jax.random.uniform(key, (n, 4), minval=-0.05,
                                   maxval=0.05)
        import jax.numpy as jnp

        return state, jnp.zeros((n,), jnp.int32)

    @staticmethod
    def obs(state):
        return state

    @staticmethod
    def step(state, steps, actions, key):
        """→ (state, steps, reward, done); done envs auto-reset."""
        import jax
        import jax.numpy as jnp

        c = CartPole  # physics constants
        x, x_dot, theta, theta_dot = state.T
        force = jnp.where(actions == 1, c.FORCE, -c.FORCE)
        cos_t, sin_t = jnp.cos(theta), jnp.sin(theta)
        total_mass = c.CART_MASS + c.POLE_MASS
        pole_ml = c.POLE_MASS * c.POLE_HALF_LEN
        temp = (force + pole_ml * theta_dot ** 2 * sin_t) / total_mass
        theta_acc = (c.GRAVITY * sin_t - cos_t * temp) / (
            c.POLE_HALF_LEN *
            (4.0 / 3.0 - c.POLE_MASS * cos_t ** 2 / total_mass))
        x_acc = temp - pole_ml * theta_acc * cos_t / total_mass
        x = x + c.DT * x_dot
        x_dot = x_dot + c.DT * x_acc
        theta = theta + c.DT * theta_dot
        theta_dot = theta_dot + c.DT * theta_acc
        new_state = jnp.stack([x, x_dot, theta, theta_dot], axis=1)
        steps = steps + 1
        done = ((jnp.abs(x) > c.X_LIMIT) |
                (jnp.abs(theta) > c.THETA_LIMIT) |
                (steps >= JaxCartPole.MAX_STEPS))
        reward = jnp.ones_like(x)
        fresh = jax.random.uniform(key, new_state.shape, minval=-0.05,
                                   maxval=0.05)
        new_state = jnp.where(done[:, None], fresh, new_state)
        steps = jnp.where(done, 0, steps)
        return new_state, steps, reward, done.astype(jnp.float32)


class Chain(VectorEnv):
    """Deterministic chain MDP (reference test-env role:
    rllib/examples/env/ deterministic debug envs): positions 0..N-1,
    actions {left, right}; +1 only for reaching the right end, then the
    episode ends. Optimal return is exactly 1.0 per episode with the
    shortest path — a crisp learnability oracle for value-based
    agents."""

    LENGTH = 6
    MAX_STEPS = 16
    num_actions = 2

    def __init__(self, num_envs: int = 8):
        self.num_envs = num_envs
        self.observation_size = self.LENGTH
        self._pos = None
        self._steps = None

    def _obs(self) -> np.ndarray:
        eye = np.eye(self.LENGTH, dtype=np.float32)
        return eye[self._pos]

    def reset(self, seed: int = 0) -> np.ndarray:
        self._pos = np.zeros(self.num_envs, dtype=np.int64)
        self._steps = np.zeros(self.num_envs, dtype=np.int32)
        return self._obs()

    def step(self, actions: np.ndarray):
        move = np.where(actions == 1, 1, -1)
        self._pos = np.clip(self._pos + move, 0, self.LENGTH - 1)
        self._steps += 1
        reached = self._pos == self.LENGTH - 1
        done = reached | (self._steps >= self.MAX_STEPS)
        reward = reached.astype(np.float32)
        if done.any():
            self._pos[done] = 0
            self._steps[done] = 0
        return self._obs(), reward, done


class Pendulum(VectorEnv):
    """Vectorized pendulum swing-up (the classic continuous-control
    task, dynamics per the public equations; no gym import): obs
    [cos θ, sin θ, θ̇], one torque action in [-2, 2], reward
    −(θ² + 0.1·θ̇² + 0.001·u²), 200-step episodes (time-limit only).
    The continuous-action oracle for the SAC family."""

    G = 10.0
    MASS = 1.0
    LENGTH = 1.0
    DT = 0.05
    MAX_TORQUE = 2.0
    MAX_SPEED = 8.0
    MAX_STEPS = 200

    observation_size = 3
    continuous = True
    action_dim = 1
    action_low = -2.0
    action_high = 2.0

    def __init__(self, num_envs: int = 16):
        self.num_envs = num_envs
        self._rng = np.random.default_rng(0)
        self._theta = None
        self._theta_dot = None
        self._steps = None

    def _fresh(self, n: int):
        return (self._rng.uniform(-np.pi, np.pi, size=n),
                self._rng.uniform(-1.0, 1.0, size=n))

    def _obs(self) -> np.ndarray:
        return np.stack([np.cos(self._theta), np.sin(self._theta),
                         self._theta_dot], axis=1).astype(np.float32)

    def reset(self, seed: int = 0) -> np.ndarray:
        self._rng = np.random.default_rng(seed)
        self._theta, self._theta_dot = self._fresh(self.num_envs)
        self._steps = np.zeros(self.num_envs, dtype=np.int32)
        return self._obs()

    def step(self, actions: np.ndarray):
        u = np.clip(np.asarray(actions, dtype=np.float64).reshape(-1),
                    -self.MAX_TORQUE, self.MAX_TORQUE)
        th = np.mod(self._theta + np.pi, 2 * np.pi) - np.pi  # normalize
        cost = th ** 2 + 0.1 * self._theta_dot ** 2 + 0.001 * u ** 2
        new_dot = self._theta_dot + (
            3 * self.G / (2 * self.LENGTH) * np.sin(self._theta)
            + 3.0 / (self.MASS * self.LENGTH ** 2) * u) * self.DT
        new_dot = np.clip(new_dot, -self.MAX_SPEED, self.MAX_SPEED)
        self._theta = self._theta + new_dot * self.DT
        self._theta_dot = new_dot
        self._steps += 1
        done = self._steps >= self.MAX_STEPS
        if done.any():
            n = int(done.sum())
            fresh_th, fresh_dot = self._fresh(n)
            self._theta[done] = fresh_th
            self._theta_dot[done] = fresh_dot
            self._steps[done] = 0
        return self._obs(), (-cost).astype(np.float32), done


ENV_REGISTRY = {"CartPole-v0": JaxCartPole, "CartPole-np": CartPole,
                "Chain-v0": Chain, "Pendulum-v0": Pendulum}


def make_env(name_or_cls, num_envs: int):
    """Numpy VectorEnvs are instantiated; jax functional envs are
    returned as-is (they are stateless namespaces)."""
    if isinstance(name_or_cls, str):
        name_or_cls = ENV_REGISTRY[name_or_cls]
    if isinstance(name_or_cls, type) and issubclass(name_or_cls,
                                                    VectorEnv):
        return name_or_cls(num_envs=num_envs)
    return name_or_cls
