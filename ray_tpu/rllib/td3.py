"""TD3: twin-delayed deterministic policy gradient (continuous).

Parity target: the reference's DDPG/TD3 family
(reference: rllib/agents/ddpg/ddpg.py + td3.py — deterministic actor
with exploration noise, twin critics, target policy smoothing, delayed
actor updates; standard public formulation of Fujimoto et al. 2018).
Shares everything with SAC-continuous (sac_continuous.py): the critic
networks, ReplayBuffer actor, execution-plan ops, Pendulum env, and
the one-compiled-program learner shape — the delta is the
deterministic policy, the smoothed targets, and the update delay,
which is exactly the reference's layering (TD3 as a config patch over
DDPG's trainer).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rllib import execution
from ray_tpu.rllib.env import VectorEnv, make_env
from ray_tpu.rllib.replay_buffer import ReplayBuffer
from ray_tpu.rllib.sac_continuous import init_critic_params, critic_forward

DEFAULT_CONFIG: Dict[str, Any] = {
    "env": "Pendulum-v0",
    "num_workers": 1,
    "num_envs_per_worker": 16,
    "rollout_len": 8,
    "gamma": 0.99,
    "lr": 1e-3,
    "explore_noise": 0.1,         # behavior-policy Gaussian std (scaled)
    "target_noise": 0.2,          # target policy smoothing std
    "target_noise_clip": 0.5,
    "policy_delay": 2,            # critic updates per actor update
    # Polyak runs only on delayed (every policy_delay-th) steps, so tau
    # is doubled vs the per-step-update formulation to keep the same
    # average target tracking rate.
    "tau": 0.01,
    "buffer_size": 100_000,
    "learning_starts": 512,
    "train_batch_size": 256,
    "num_sgd_steps": 32,
    "hidden": 64,
    "seed": 0,
}


def init_det_actor_params(key, obs_size: int, action_dim: int,
                          hidden: int = 64) -> Dict:
    from ray_tpu.rllib.models import _dense_init

    k1, k2, k3 = jax.random.split(key, 3)
    return {"l1": _dense_init(k1, obs_size, hidden),
            "l2": _dense_init(k2, hidden, hidden),
            "mu": _dense_init(k3, hidden, action_dim, scale=0.01)}


def det_actor_forward(params, obs, scale: float):
    h = jnp.tanh(obs @ params["l1"]["w"] + params["l1"]["b"])
    h = jnp.tanh(h @ params["l2"]["w"] + params["l2"]["b"])
    return scale * jnp.tanh(h @ params["mu"]["w"] + params["mu"]["b"])


@functools.partial(jax.jit, static_argnames=(
    "gamma", "tau", "lr", "scale", "target_noise", "noise_clip",
    "policy_delay"))
def _td3_update(params, target_params, opt_state, batches, key, *,
                gamma, tau, lr, scale, target_noise, noise_clip,
                policy_delay):
    """K TD3 steps as one compiled program. ``params`` = {"pi", "q1",
    "q2"}; targets hold all three (TD3 targets the actor too)."""
    import optax

    optimizer = optax.adam(lr)

    def critic_loss(p, tp, mb, k):
        noise = jnp.clip(
            target_noise * jax.random.normal(
                k, mb["actions"].reshape(mb["rewards"].shape[0], -1).shape),
            -noise_clip, noise_clip) * scale
        a_next = jnp.clip(
            det_actor_forward(tp["pi"], mb["next_obs"], scale) + noise,
            -scale, scale)
        q_t = jnp.minimum(
            critic_forward(tp["q1"], mb["next_obs"], a_next),
            critic_forward(tp["q2"], mb["next_obs"], a_next))
        target = mb["rewards"] + gamma * (1.0 - mb["dones"]) * \
            jax.lax.stop_gradient(q_t)
        acts = mb["actions"].reshape(mb["rewards"].shape[0], -1)
        return ((critic_forward(p["q1"], mb["obs"], acts) - target) ** 2
                ).mean() + \
               ((critic_forward(p["q2"], mb["obs"], acts) - target) ** 2
                ).mean()

    def actor_loss(p, mb):
        a = det_actor_forward(p["pi"], mb["obs"], scale)
        return -critic_forward(jax.lax.stop_gradient(p["q1"]),
                               mb["obs"], a).mean()

    def step(carry, inp):
        p, tp, opt_state, i = carry
        mb, k = inp
        actor_step = i % policy_delay == 0

        def total_loss(p):
            c = critic_loss(p, tp, mb, k)
            # delayed policy updates: the actor term joins every
            # policy_delay-th step (lax.cond keeps one program)
            a = jax.lax.cond(actor_step,
                             lambda: actor_loss(p, mb),
                             lambda: 0.0)
            return c + a, c

        (loss, c), grads = jax.value_and_grad(
            total_loss, has_aux=True)(p)
        updates, new_opt_state = optimizer.update(grads, opt_state, p)
        new_p = optax.apply_updates(p, updates)

        # Critic-only steps must leave the actor ALONE: zero actor
        # grads still produce nonzero adam updates (the first/second
        # moments from past actor steps keep emitting deltas), so the
        # actor params — and the actor's moment state — are held
        # frozen between delayed updates.
        def keep(new, old):
            return jax.tree.map(
                lambda n, o: jnp.where(actor_step, n, o), new, old)

        new_p = dict(new_p, pi=keep(new_p["pi"], p["pi"]))
        masked_state = []
        for ns, os_ in zip(new_opt_state, opt_state):
            if hasattr(ns, "mu") and hasattr(ns, "nu"):
                ns = ns._replace(
                    mu=dict(ns.mu, pi=keep(ns.mu["pi"], os_.mu["pi"])),
                    nu=dict(ns.nu, pi=keep(ns.nu["pi"], os_.nu["pi"])))
            masked_state.append(ns)
        new_opt_state = tuple(masked_state)  # optax chain state
        # Polyak target updates are delayed with the policy (Fujimoto
        # et al. 2018: targets move every d-th step, not every step).
        new_tp = jax.tree.map(
            lambda t, o: (1 - tau) * t + tau * o, tp, new_p)
        tp = keep(new_tp, tp)
        return (new_p, tp, new_opt_state, i + 1), c

    n_steps = jax.tree.leaves(batches)[0].shape[0]
    keys = jax.random.split(key, n_steps)
    (params, target_params, opt_state, _), critic_losses = jax.lax.scan(
        step, (params, target_params, opt_state, 0), (batches, keys))
    return params, target_params, opt_state, jnp.mean(critic_losses)


class DetTransitionWorker:
    """Deterministic-policy sampler with exploration noise (reference:
    DDPG/TD3 exploration — OrnsteinUhlenbeck/Gaussian noise on the
    deterministic action; plain Gaussian here, TD3's default)."""

    def __init__(self, env_name, num_envs: int, rollout_len: int,
                 noise: float, seed: int = 0):
        self.env = make_env(env_name, num_envs)
        if not isinstance(self.env, VectorEnv) or \
                not getattr(self.env, "continuous", False):
            raise ValueError("needs a continuous-action VectorEnv")
        self.num_envs = num_envs
        self.rollout_len = rollout_len
        self._scale = float(self.env.action_high)
        self._noise = noise * self._scale
        self._fwd = jax.jit(functools.partial(det_actor_forward,
                                              scale=self._scale))
        self._rng = np.random.default_rng(seed)
        self.obs = self.env.reset(seed)
        self.params = None
        self._ep_return = np.zeros(num_envs, dtype=np.float32)
        self._finished_returns: List[float] = []

    def set_weights(self, params) -> None:
        self.params = params

    def sample(self) -> Dict[str, np.ndarray]:
        T, B = self.rollout_len, self.num_envs
        obs_dim = self.env.observation_size
        adim = self.env.action_dim
        out = {
            "obs": np.zeros((T * B, obs_dim), np.float32),
            "actions": np.zeros((T * B, adim), np.float32),
            "rewards": np.zeros((T * B,), np.float32),
            "next_obs": np.zeros((T * B, obs_dim), np.float32),
            "dones": np.zeros((T * B,), np.float32),
        }
        for t in range(T):
            a = np.asarray(self._fwd(self.params, self.obs))
            a = np.clip(a + self._rng.normal(0.0, self._noise, a.shape),
                        -self._scale, self._scale).astype(np.float32)
            nxt, reward, done = self.env.step(a)
            sl = slice(t * B, (t + 1) * B)
            out["obs"][sl] = self.obs
            out["actions"][sl] = a.reshape(B, adim)
            out["rewards"][sl] = reward
            out["next_obs"][sl] = nxt
            out["dones"][sl] = done
            self._ep_return += reward
            if done.any():
                self._finished_returns.extend(
                    self._ep_return[done].tolist())
                self._ep_return[done] = 0.0
            self.obs = nxt
        return out

    def episode_returns(self, clear: bool = True) -> List[float]:
        out = list(self._finished_returns)
        if clear:
            self._finished_returns.clear()
        return out


def _setup(self, cfg: Dict[str, Any]) -> None:
    import optax

    probe = make_env(cfg["env"], 1)
    keys = jax.random.split(jax.random.key(cfg["seed"]), 3)
    self.params = {
        "pi": init_det_actor_params(keys[0], probe.observation_size,
                                    probe.action_dim, cfg["hidden"]),
        "q1": init_critic_params(keys[1], probe.observation_size,
                                 probe.action_dim, cfg["hidden"]),
        "q2": init_critic_params(keys[2], probe.observation_size,
                                 probe.action_dim, cfg["hidden"]),
    }
    self.target_params = jax.tree.map(lambda x: x, self.params)
    self._opt_state = optax.adam(cfg["lr"]).init(self.params)
    self._scale = float(probe.action_high)
    self._key = jax.random.key(cfg["seed"] + 11)
    self.buffer = ray_tpu.remote(ReplayBuffer).options(
        num_cpus=0).remote(cfg["buffer_size"], seed=cfg["seed"])
    cls = ray_tpu.remote(DetTransitionWorker)
    self.workers = [
        cls.remote(cfg["env"], cfg["num_envs_per_worker"],
                   cfg["rollout_len"], cfg["explore_noise"], seed=i + 1)
        for i in range(cfg["num_workers"])]
    self._counters = {"timesteps_total": 0, "buffer_size": 0}


def _ingest(self, batch):
    self._counters["timesteps_total"] += len(batch["obs"])
    self._counters["buffer_size"] = int(
        ray_tpu.get(self.buffer.add.remote(batch)))
    return batch


def _learn(self, stacked) -> Dict[str, Any]:
    if stacked is None:
        return {"loss": float("nan")}
    cfg = self.config
    self._key, sub = jax.random.split(self._key)
    (self.params, self.target_params, self._opt_state,
     loss) = _td3_update(
        self.params, self.target_params, self._opt_state, stacked, sub,
        gamma=cfg["gamma"], tau=cfg["tau"], lr=cfg["lr"],
        scale=self._scale, target_noise=cfg["target_noise"],
        noise_clip=cfg["target_noise_clip"],
        policy_delay=cfg["policy_delay"])
    return {"loss": float(loss)}


def _execution_plan(self):
    cfg = self.config
    replay = execution.Replay(
        self.buffer, train_batch_size=cfg["train_batch_size"],
        num_steps=cfg["num_sgd_steps"],
        learning_starts=cfg["learning_starts"],
        size_fn=lambda: self._counters["buffer_size"])
    learn = execution.TrainOneStep(replay, lambda b: _learn(self, b))
    rollouts = execution.ParallelRollouts(
        self.workers, mode="bulk_sync",
        weights=lambda: self.params["pi"])
    store = execution.ForEach(rollouts, lambda b: _ingest(self, b))
    plan = execution.Concurrently([store, learn], output=1)
    return execution.StandardMetricsReporting(
        plan, self.workers, self._counters)


def _get_state(self) -> dict:
    return {"params": self.params, "target_params": self.target_params,
            "opt_state": self._opt_state,
            "timesteps": self._counters["timesteps_total"]}


def _set_state(self, state: dict) -> None:
    self.params = state["params"]
    self.target_params = state["target_params"]
    self._opt_state = state["opt_state"]
    self._counters["timesteps_total"] = state["timesteps"]


TD3Trainer = execution.build_trainer(
    name="TD3Trainer", default_config=DEFAULT_CONFIG, setup=_setup,
    execution_plan=_execution_plan, get_state=_get_state,
    set_state=_set_state)
