"""DQNTrainer: distributed epsilon-greedy sampling + replay + a jitted
double-DQN learner.

Parity target: the reference's DQN family
(reference: rllib/agents/dqn/dqn.py built on trainer_template.py:53,
with replay via rllib/execution/replay_buffer.py and offline IO via
rllib/offline/). TPU-first re-design: the optimization phase is ONE
jitted program — K minibatch Adam steps via lax.scan over batches
pre-gathered from the replay actor — and the Q-network matmuls run in
the MXU-friendly [batch, features] layout the buffer already stores.

Proves the second algorithm family shares the abstractions: env
registry + TransitionWorker (rollout_worker.py), ReplayBuffer actor,
JsonWriter/JsonReader offline IO, and the Tune trainable contract.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rllib import execution
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.offline import JsonReader, JsonWriter
from ray_tpu.rllib.replay_buffer import (PrioritizedReplayBuffer,
                                         ReplayBuffer)
from ray_tpu.rllib.rollout_worker import TransitionWorker

DEFAULT_CONFIG: Dict[str, Any] = {
    "env": "Chain-v0",
    "num_workers": 1,
    "num_envs_per_worker": 8,
    "rollout_len": 32,
    "gamma": 0.99,
    "lr": 5e-3,
    "buffer_size": 50_000,
    "learning_starts": 256,
    "train_batch_size": 128,
    "num_sgd_steps": 8,
    "target_update_freq": 4,      # in train() iterations
    "epsilon_initial": 1.0,
    "epsilon_final": 0.05,
    "epsilon_decay_iters": 20,
    "double_q": True,
    # prioritized replay (reference: DQN's default replay is
    # prioritized - execution/replay_buffer.py PrioritizedReplayBuffer)
    "prioritized_replay": False,
    "pr_alpha": 0.6,
    "pr_beta": 0.4,
    "hidden": 64,
    "model": None,                # model-catalog config (models.py)
    "seed": 0,
    "output": None,               # dir → JsonWriter episode logging
    "input": None,                # dir → offline training, no env sampling
}


def init_q_params(key, obs_size: int, num_actions: int,
                  hidden: int = 64, model=None) -> Dict:
    """``model``: frozen catalog spec (models.freeze_model_config)
    switches to the catalog q-net; None keeps the classic tanh MLP."""
    if model is not None:
        from ray_tpu.rllib.models import init_q_net

        return init_q_net(model, key, obs_size, num_actions)
    k1, k2, k3 = jax.random.split(key, 3)
    init = jax.nn.initializers.orthogonal(np.sqrt(2))
    return {
        "w1": init(k1, (obs_size, hidden), jnp.float32),
        "b1": jnp.zeros((hidden,)),
        "w2": init(k2, (hidden, hidden), jnp.float32),
        "b2": jnp.zeros((hidden,)),
        "q": init(k3, (hidden, num_actions), jnp.float32),
        "q_b": jnp.zeros((num_actions,)),
    }


def q_values(params, obs, model=None):
    if model is not None:
        from ray_tpu.rllib.models import q_net_forward

        return q_net_forward(model, params, obs)
    h = jnp.tanh(obs @ params["w1"] + params["b1"])
    h = jnp.tanh(h @ params["w2"] + params["b2"])
    return h @ params["q"] + params["q_b"]


@functools.partial(jax.jit, static_argnames=("gamma", "double_q", "lr",
                                             "model"))
def _dqn_update(params, target_params, opt_state, batches, *,
                gamma, double_q, lr, model=None):
    """K Adam steps as one compiled program: lax.scan over the [K,
    batch, ...] stack of replay minibatches (Huber TD loss, double-DQN
    action selection by the online net)."""
    import optax

    optimizer = optax.adam(lr)

    def td_loss(p, mb):
        q = q_values(p, mb["obs"], model)
        qa = q[jnp.arange(q.shape[0]), mb["actions"]]
        q_next_target = q_values(target_params, mb["next_obs"], model)
        if double_q:
            sel = jnp.argmax(q_values(p, mb["next_obs"], model), axis=-1)
            bootstrap = q_next_target[
                jnp.arange(q_next_target.shape[0]), sel]
        else:
            bootstrap = q_next_target.max(axis=-1)
        target = mb["rewards"] + gamma * (1.0 - mb["dones"]) * \
            jax.lax.stop_gradient(bootstrap)
        td = qa - target
        # importance-sampling weights correct the prioritized-sampling
        # bias; uniform replay sends no "weights" key
        w = mb.get("weights")
        loss = optax.huber_loss(qa, target)
        loss = (loss * w).mean() if w is not None else loss.mean()
        return loss, jnp.abs(td)

    def step(carry, mb):
        p, opt_state = carry
        (loss, td_abs), grads = jax.value_and_grad(
            td_loss, has_aux=True)(p, mb)
        updates, opt_state = optimizer.update(grads, opt_state, p)
        p = optax.apply_updates(p, updates)
        return (p, opt_state), (loss, td_abs)

    (params, opt_state), (losses, td_abs) = jax.lax.scan(
        step, (params, opt_state), batches)
    return params, opt_state, jnp.mean(losses), td_abs


class DQNTrainer(execution.Trainer):
    """Replay off-policy shape of the execution-plan substrate
    (reference: dqn.py's plan = Concurrently([rollouts -> store,
    replay -> train -> target-update]) per trainer_template.py). Also a
    Tune trainable via the template."""

    default_config = DEFAULT_CONFIG

    def setup(self, cfg: Dict[str, Any]) -> None:
        import optax

        from ray_tpu.rllib.models import freeze_model_config

        probe = make_env(cfg["env"], 1)
        self.model = freeze_model_config(cfg["model"]) \
            if cfg.get("model") else None
        self.params = init_q_params(
            jax.random.key(cfg["seed"]), probe.observation_size,
            probe.num_actions, hidden=cfg["hidden"], model=self.model)
        self.target_params = self.params
        self._opt_state = optax.adam(cfg["lr"]).init(self.params)
        self._offline = cfg["input"] is not None
        # Replay lives in its own actor so many workers can feed it and
        # its memory is isolated from the learner (reference:
        # LocalReplayBuffer actor, rllib/execution/replay_buffer.py:302).
        if cfg["prioritized_replay"]:
            self.buffer = ray_tpu.remote(PrioritizedReplayBuffer).options(
                num_cpus=0).remote(cfg["buffer_size"], seed=cfg["seed"],
                                   alpha=cfg["pr_alpha"],
                                   beta=cfg["pr_beta"])
        else:
            self.buffer = ray_tpu.remote(ReplayBuffer).options(
                num_cpus=0).remote(cfg["buffer_size"], seed=cfg["seed"])
        self._counters = {"timesteps_total": 0, "buffer_size": 0,
                          "epsilon": cfg["epsilon_initial"]}
        if self._offline:
            batch = JsonReader(cfg["input"]).read_all()
            if batch is None:
                raise ValueError(f"no offline data in {cfg['input']!r}")
            self._counters["buffer_size"] = int(
                ray_tpu.get(self.buffer.add.remote(batch)))
            self.workers = []
        else:
            cls = ray_tpu.remote(TransitionWorker)
            q_fn = q_values if self.model is None else \
                functools.partial(q_values, model=self.model)
            self.workers = [
                cls.remote(cfg["env"], cfg["num_envs_per_worker"],
                           cfg["rollout_len"], q_fn, seed=i + 1)
                for i in range(cfg["num_workers"])]
        self._writer = JsonWriter(cfg["output"]) if cfg["output"] else None

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._iteration / max(1, cfg["epsilon_decay_iters"]))
        return cfg["epsilon_initial"] + frac * (
            cfg["epsilon_final"] - cfg["epsilon_initial"])

    def execution_plan(self):
        cfg = self.config
        replay = execution.Replay(
            self.buffer, train_batch_size=cfg["train_batch_size"],
            num_steps=cfg["num_sgd_steps"],
            learning_starts=cfg["learning_starts"],
            size_fn=lambda: self._counters["buffer_size"])
        learn = execution.TrainOneStep(replay, self._learn_on_batches)
        learn = execution.UpdateTargetNetwork(
            learn, self._update_target, cfg["target_update_freq"])
        if self._offline:
            return execution.StandardMetricsReporting(
                learn, [], self._counters)

        rollouts = execution.ParallelRollouts(
            self.workers, mode="bulk_sync",
            weights=lambda: self.params,
            sample_args=lambda: (self._epsilon(),))
        store = execution.ForEach(rollouts, self._ingest)
        plan = execution.Concurrently([store, learn], output=1)
        return execution.StandardMetricsReporting(
            plan, self.workers, self._counters)

    def _ingest(self, batch):
        """Count, tee to offline output, and store SYNCHRONOUSLY so the
        replay op (advanced next in the same Concurrently round) sees
        this round's transitions, like the reference's local-mode
        store-then-replay ordering."""
        self._counters["timesteps_total"] += len(batch["obs"])
        self._counters["epsilon"] = self._epsilon()
        if self._writer is not None:
            self._writer.write(batch)
        self._counters["buffer_size"] = int(
            ray_tpu.get(self.buffer.add.remote(batch)))
        return batch

    def _learn_on_batches(self, stacked) -> Dict[str, Any]:
        if stacked is None:
            return {"loss": float("nan")}
        cfg = self.config
        # "indices" are host-side bookkeeping for priority updates —
        # the jitted update must not trace them
        indices = stacked.pop("indices", None)
        self.params, self._opt_state, loss, td_abs = _dqn_update(
            self.params, self.target_params, self._opt_state,
            stacked, gamma=cfg["gamma"], double_q=cfg["double_q"],
            lr=cfg["lr"], model=self.model)
        if indices is not None:
            self.buffer.update_priorities.remote(
                np.asarray(indices).reshape(-1),
                np.asarray(td_abs).reshape(-1))
        return {"loss": float(loss)}

    def _update_target(self) -> None:
        self.target_params = self.params

    def get_state(self) -> dict:
        return {"params": self.params,
                "target_params": self.target_params,
                "opt_state": self._opt_state,
                "timesteps": self._counters["timesteps_total"]}

    def set_state(self, state: dict) -> None:
        self.params = state["params"]
        self.target_params = state["target_params"]
        self._opt_state = state["opt_state"]
        self._counters["timesteps_total"] = state["timesteps"]

    def stop(self) -> None:
        if self._writer is not None:
            self._writer.close()
