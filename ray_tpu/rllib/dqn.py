"""DQNTrainer: distributed epsilon-greedy sampling + replay + a jitted
double-DQN learner.

Parity target: the reference's DQN family
(reference: rllib/agents/dqn/dqn.py built on trainer_template.py:53,
with replay via rllib/execution/replay_buffer.py and offline IO via
rllib/offline/). TPU-first re-design: the optimization phase is ONE
jitted program — K minibatch Adam steps via lax.scan over batches
pre-gathered from the replay actor — and the Q-network matmuls run in
the MXU-friendly [batch, features] layout the buffer already stores.

Proves the second algorithm family shares the abstractions: env
registry + TransitionWorker (rollout_worker.py), ReplayBuffer actor,
JsonWriter/JsonReader offline IO, and the Tune trainable contract.
"""

from __future__ import annotations

import functools
import pickle
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.offline import JsonReader, JsonWriter
from ray_tpu.rllib.replay_buffer import ReplayBuffer
from ray_tpu.rllib.rollout_worker import TransitionWorker

DEFAULT_CONFIG: Dict[str, Any] = {
    "env": "Chain-v0",
    "num_workers": 1,
    "num_envs_per_worker": 8,
    "rollout_len": 32,
    "gamma": 0.99,
    "lr": 5e-3,
    "buffer_size": 50_000,
    "learning_starts": 256,
    "train_batch_size": 128,
    "num_sgd_steps": 8,
    "target_update_freq": 4,      # in train() iterations
    "epsilon_initial": 1.0,
    "epsilon_final": 0.05,
    "epsilon_decay_iters": 20,
    "double_q": True,
    "hidden": 64,
    "seed": 0,
    "output": None,               # dir → JsonWriter episode logging
    "input": None,                # dir → offline training, no env sampling
}


def init_q_params(key, obs_size: int, num_actions: int,
                  hidden: int = 64) -> Dict:
    k1, k2, k3 = jax.random.split(key, 3)
    init = jax.nn.initializers.orthogonal(np.sqrt(2))
    return {
        "w1": init(k1, (obs_size, hidden), jnp.float32),
        "b1": jnp.zeros((hidden,)),
        "w2": init(k2, (hidden, hidden), jnp.float32),
        "b2": jnp.zeros((hidden,)),
        "q": init(k3, (hidden, num_actions), jnp.float32),
        "q_b": jnp.zeros((num_actions,)),
    }


def q_values(params, obs):
    h = jnp.tanh(obs @ params["w1"] + params["b1"])
    h = jnp.tanh(h @ params["w2"] + params["b2"])
    return h @ params["q"] + params["q_b"]


@functools.partial(jax.jit, static_argnames=("gamma", "double_q", "lr"))
def _dqn_update(params, target_params, opt_state, batches, *,
                gamma, double_q, lr):
    """K Adam steps as one compiled program: lax.scan over the [K,
    batch, ...] stack of replay minibatches (Huber TD loss, double-DQN
    action selection by the online net)."""
    import optax

    optimizer = optax.adam(lr)

    def td_loss(p, mb):
        q = q_values(p, mb["obs"])
        qa = q[jnp.arange(q.shape[0]), mb["actions"]]
        q_next_target = q_values(target_params, mb["next_obs"])
        if double_q:
            sel = jnp.argmax(q_values(p, mb["next_obs"]), axis=-1)
            bootstrap = q_next_target[
                jnp.arange(q_next_target.shape[0]), sel]
        else:
            bootstrap = q_next_target.max(axis=-1)
        target = mb["rewards"] + gamma * (1.0 - mb["dones"]) * \
            jax.lax.stop_gradient(bootstrap)
        return optax.huber_loss(qa, target).mean()

    def step(carry, mb):
        p, opt_state = carry
        loss, grads = jax.value_and_grad(td_loss)(p, mb)
        updates, opt_state = optimizer.update(grads, opt_state, p)
        p = optax.apply_updates(p, updates)
        return (p, opt_state), loss

    (params, opt_state), losses = jax.lax.scan(
        step, (params, opt_state), batches)
    return params, opt_state, jnp.mean(losses)


class DQNTrainer:
    """Also a Tune trainable: train()/save()/restore()."""

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        import optax

        self.config = {**DEFAULT_CONFIG, **(config or {})}
        cfg = self.config
        probe = make_env(cfg["env"], 1)
        self.params = init_q_params(
            jax.random.key(cfg["seed"]), probe.observation_size,
            probe.num_actions, hidden=cfg["hidden"])
        self.target_params = self.params
        self._opt_state = optax.adam(cfg["lr"]).init(self.params)
        self._offline = cfg["input"] is not None
        # Replay lives in its own actor so many workers can feed it and
        # its memory is isolated from the learner (reference:
        # LocalReplayBuffer actor, rllib/execution/replay_buffer.py:302).
        self.buffer = ray_tpu.remote(ReplayBuffer).options(
            num_cpus=0).remote(cfg["buffer_size"], seed=cfg["seed"])
        if self._offline:
            batch = JsonReader(cfg["input"]).read_all()
            if batch is None:
                raise ValueError(f"no offline data in {cfg['input']!r}")
            ray_tpu.get(self.buffer.add.remote(batch))
            self.workers = []
        else:
            cls = ray_tpu.remote(TransitionWorker)
            self.workers = [
                cls.remote(cfg["env"], cfg["num_envs_per_worker"],
                           cfg["rollout_len"], q_values, seed=i + 1)
                for i in range(cfg["num_workers"])]
        self._writer = JsonWriter(cfg["output"]) if cfg["output"] else None
        self._iteration = 0
        self._timesteps = 0

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._iteration / max(1, cfg["epsilon_decay_iters"]))
        return cfg["epsilon_initial"] + frac * (
            cfg["epsilon_final"] - cfg["epsilon_initial"])

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        eps = self._epsilon()
        if not self._offline:
            ray_tpu.get([w.set_weights.remote(self.params)
                         for w in self.workers])
            batches = ray_tpu.get(
                [w.sample.remote(eps) for w in self.workers])
            for b in batches:
                self._timesteps += len(b["obs"])
                if self._writer is not None:
                    self._writer.write(b)
            adds = [self.buffer.add.remote(b) for b in batches]
            buffer_size = ray_tpu.get(adds)[-1]
        else:
            buffer_size = ray_tpu.get(self.buffer.size.remote())

        loss = float("nan")
        if buffer_size >= cfg["learning_starts"]:
            k = cfg["num_sgd_steps"]
            minibatches = ray_tpu.get(
                [self.buffer.sample.remote(cfg["train_batch_size"])
                 for _ in range(k)])
            stacked = {key: jnp.stack([m[key] for m in minibatches])
                       for key in minibatches[0]}
            self.params, self._opt_state, loss = _dqn_update(
                self.params, self.target_params, self._opt_state,
                stacked, gamma=cfg["gamma"], double_q=cfg["double_q"],
                lr=cfg["lr"])
            loss = float(loss)
        self._iteration += 1
        if self._iteration % cfg["target_update_freq"] == 0:
            self.target_params = self.params

        returns: list = []
        if not self._offline:
            for rs in ray_tpu.get([w.episode_returns.remote()
                                   for w in self.workers]):
                returns.extend(rs)
        return {
            "training_iteration": self._iteration,
            "timesteps_total": self._timesteps,
            "buffer_size": int(buffer_size),
            "epsilon": eps,
            "episode_reward_mean":
                float(np.mean(returns)) if returns else float("nan"),
            "episodes_this_iter": len(returns),
            "loss": loss,
        }

    # ---- Tune trainable contract ----

    def save(self, path: str) -> str:
        with open(path, "wb") as f:
            pickle.dump({"params": self.params,
                         "target_params": self.target_params,
                         "opt_state": self._opt_state,
                         "iteration": self._iteration,
                         "timesteps": self._timesteps}, f)
        return path

    def restore(self, path: str) -> None:
        with open(path, "rb") as f:
            state = pickle.load(f)
        self.params = state["params"]
        self.target_params = state["target_params"]
        self._opt_state = state["opt_state"]
        self._iteration = state["iteration"]
        self._timesteps = state["timesteps"]

    def stop(self) -> None:
        if self._writer is not None:
            self._writer.close()
