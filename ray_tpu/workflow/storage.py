"""Durable workflow storage (filesystem backend).

Parity target: the reference's WorkflowStorage
(reference: python/ray/workflow/workflow_storage.py:89 —
save_step_output :124, inspect paths — and workflow/storage/filesystem.py).
Layout::

    <base>/<workflow_id>/
        dag.pkl                  # the whole step DAG (for resume)
        status                   # RUNNING | SUCCESSFUL | FAILED
        steps/<step_id>/output.pkl

Writes are atomic (tmp + rename) so a driver killed mid-checkpoint
never leaves a half-written output that resume would trust. The base
dir must be on a filesystem reachable by every node that executes
steps (the same contract as the reference's filesystem backend).
"""

from __future__ import annotations

import os
import pickle
import tempfile
from typing import Any, List, Optional

try:
    import cloudpickle
except ImportError:  # pragma: no cover
    cloudpickle = pickle


def _atomic_write(path: str, data: bytes) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class WorkflowStorage:
    def __init__(self, base_dir: str):
        self.base_dir = base_dir
        os.makedirs(base_dir, exist_ok=True)

    # ---- per-workflow ----

    def _wf_dir(self, workflow_id: str) -> str:
        return os.path.join(self.base_dir, workflow_id)

    def save_dag(self, workflow_id: str, dag: Any) -> None:
        _atomic_write(os.path.join(self._wf_dir(workflow_id), "dag.pkl"),
                      cloudpickle.dumps(dag))

    def load_dag(self, workflow_id: str) -> Any:
        with open(os.path.join(self._wf_dir(workflow_id), "dag.pkl"),
                  "rb") as f:
            return pickle.loads(f.read())

    def set_status(self, workflow_id: str, status: str) -> None:
        _atomic_write(os.path.join(self._wf_dir(workflow_id), "status"),
                      status.encode())

    def get_status(self, workflow_id: str) -> Optional[str]:
        try:
            with open(os.path.join(self._wf_dir(workflow_id),
                                   "status"), "rb") as f:
                return f.read().decode()
        except FileNotFoundError:
            return None

    def list_workflows(self) -> List[str]:
        try:
            return sorted(
                d for d in os.listdir(self.base_dir)
                if os.path.isdir(os.path.join(self.base_dir, d)))
        except FileNotFoundError:
            return []

    # ---- per-step ----

    def _step_output_path(self, workflow_id: str, step_id: str) -> str:
        return os.path.join(self._wf_dir(workflow_id), "steps", step_id,
                            "output.pkl")

    def has_step_output(self, workflow_id: str, step_id: str) -> bool:
        return os.path.exists(self._step_output_path(workflow_id, step_id))

    def save_step_output(self, workflow_id: str, step_id: str,
                         value: Any) -> None:
        _atomic_write(self._step_output_path(workflow_id, step_id),
                      cloudpickle.dumps(value))

    def load_step_output(self, workflow_id: str, step_id: str) -> Any:
        with open(self._step_output_path(workflow_id, step_id), "rb") as f:
            return pickle.loads(f.read())
