"""Durable workflow storage: a pluggable backend seam.

Parity target: the reference's WorkflowStorage over swappable backends
(reference: python/ray/workflow/workflow_storage.py:89,
workflow/storage/base.py, storage/filesystem.py, storage/s3.py).
Backends implement a small key-value contract; ``WorkflowStorage``
layers the workflow layout on top::

    <workflow_id>/dag.pkl                  # the whole step DAG (resume)
    <workflow_id>/status                   # RUNNING | SUCCESSFUL | FAILED
    <workflow_id>/steps/<step_id>/output.pkl
    actors/<actor_id>/state.pkl            # virtual actor state

Selection is by URL (``storage_from_url``):

* ``file:///path`` (or a bare path) — filesystem; writes are atomic
  (tmp + rename) so a driver killed mid-checkpoint never leaves a
  half-written output that resume would trust. The base dir must be
  reachable by every node that executes steps.
* ``kv://prefix`` — the cluster's internal GCS KV (journal-persisted,
  survives GCS restarts; reachable from every worker by construction).
* ``s3://bucket/prefix`` — reference-parity cloud backend; requires
  boto3 (not bundled — the class raises a clear error without it).
"""

from __future__ import annotations

import os
import pickle
import tempfile
from typing import Any, List, Optional

try:
    import cloudpickle
except ImportError:  # pragma: no cover
    cloudpickle = pickle


def _atomic_write(path: str, data: bytes) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class Storage:
    """Backend contract (reference: workflow/storage/base.py Storage)."""

    url: str = ""

    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        return self.get(key) is not None

    def list_prefix(self, prefix: str) -> List[str]:
        """Immediate child names under a '/'-delimited prefix."""
        raise NotImplementedError

    def delete(self, key: str) -> None:
        """Best-effort removal (spill cleanup etc.); missing keys are
        not an error."""
        raise NotImplementedError


class FilesystemStorage(Storage):
    def __init__(self, base_dir: str):
        self.base_dir = base_dir
        self.url = f"file://{base_dir}"
        os.makedirs(base_dir, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.base_dir, *key.split("/"))

    def put(self, key: str, data: bytes) -> None:
        _atomic_write(self._path(key), data)

    def get(self, key: str) -> Optional[bytes]:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except (FileNotFoundError, IsADirectoryError):
            return None

    def exists(self, key: str) -> bool:
        return os.path.exists(self._path(key))

    def list_prefix(self, prefix: str) -> List[str]:
        try:
            return sorted(os.listdir(self._path(prefix)))
        except FileNotFoundError:
            return []

    def delete(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except OSError:
            pass


class KVStorage(Storage):
    """Workflow storage inside the cluster's internal GCS KV — the KV
    journal makes it durable across GCS restarts, and every worker can
    reach it without a shared filesystem."""

    def __init__(self, prefix: str = "workflow"):
        self.prefix = prefix.strip("/")
        self.url = f"kv://{self.prefix}"

    def _key(self, key: str) -> bytes:
        return f"__wf__/{self.prefix}/{key}".encode()

    def put(self, key: str, data: bytes) -> None:
        import ray_tpu

        ray_tpu.experimental_internal_kv_put(self._key(key), data,
                                             overwrite=True)

    def get(self, key: str) -> Optional[bytes]:
        import ray_tpu

        return ray_tpu.experimental_internal_kv_get(self._key(key))

    def exists(self, key: str) -> bool:
        # keys-only RPC: existence must not transfer the (possibly
        # large) checkpoint value
        import ray_tpu

        return bool(ray_tpu.experimental_internal_kv_list(self._key(key)))

    def list_prefix(self, prefix: str) -> List[str]:
        import ray_tpu

        base = self._key(prefix).rstrip(b"/") + b"/"
        out = set()
        for k in ray_tpu.experimental_internal_kv_list(base):
            rest = k[len(base):].decode()
            out.add(rest.split("/", 1)[0])
        return sorted(out)

    def delete(self, key: str) -> None:
        import ray_tpu

        ray_tpu.experimental_internal_kv_del(self._key(key))


class S3Storage(Storage):
    """Reference-parity S3 backend (reference: workflow/storage/s3.py).
    boto3 is not bundled in this environment; the class is importable
    (URL routing + tests can see it) but raises on construction
    without it."""

    def __init__(self, bucket: str, prefix: str = ""):
        try:
            import boto3
        except ImportError as e:  # pragma: no cover - env has no boto3
            raise RuntimeError(
                "s3:// workflow storage requires boto3, which is not "
                "installed in this environment") from e

        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self.url = f"s3://{bucket}/{self.prefix}"
        self._s3 = boto3.client("s3")  # pragma: no cover

    def _key(self, key: str) -> str:  # pragma: no cover
        return f"{self.prefix}/{key}" if self.prefix else key

    def put(self, key: str, data: bytes) -> None:  # pragma: no cover
        self._s3.put_object(Bucket=self.bucket, Key=self._key(key),
                            Body=data)

    def get(self, key: str) -> Optional[bytes]:  # pragma: no cover
        try:
            r = self._s3.get_object(Bucket=self.bucket, Key=self._key(key))
            return r["Body"].read()
        except self._s3.exceptions.NoSuchKey:
            return None

    def delete(self, key: str) -> None:  # pragma: no cover
        import logging

        try:
            self._s3.delete_object(Bucket=self.bucket,
                                   Key=self._key(key))
        except Exception:  # noqa: BLE001 — leak must be visible
            logging.getLogger(__name__).warning(
                "s3 delete of %s failed (spill blob may leak)",
                self._key(key), exc_info=True)

    def list_prefix(self, prefix: str) -> List[str]:  # pragma: no cover
        base = self._key(prefix).rstrip("/") + "/"
        out = set()
        pages = self._s3.get_paginator("list_objects_v2").paginate(
            Bucket=self.bucket, Prefix=base, Delimiter="/")
        for page in pages:
            for cp in page.get("CommonPrefixes", []):
                out.add(cp["Prefix"][len(base):].rstrip("/"))
            for obj in page.get("Contents", []):
                out.add(obj["Key"][len(base):].split("/", 1)[0])
        return sorted(x for x in out if x)


def storage_from_url(url: str) -> Storage:
    """file:///path | kv://prefix | s3://bucket/prefix | bare path."""
    if url.startswith("kv://"):
        return KVStorage(url[len("kv://"):] or "workflow")
    if url.startswith("s3://"):
        rest = url[len("s3://"):]
        bucket, _, prefix = rest.partition("/")
        return S3Storage(bucket, prefix)
    if url.startswith("file://"):
        url = url[len("file://"):]
    return FilesystemStorage(url)


class WorkflowStorage:
    """Workflow layout over a Storage backend. Constructible either
    from a backend or from a URL/path (what remote steps receive)."""

    def __init__(self, base: "str | Storage"):
        self.backend = (base if isinstance(base, Storage)
                        else storage_from_url(base))
        self.url = self.backend.url

    # ---- per-workflow ----

    def save_dag(self, workflow_id: str, dag: Any) -> None:
        self.backend.put(f"{workflow_id}/dag.pkl", cloudpickle.dumps(dag))

    def load_dag(self, workflow_id: str) -> Any:
        data = self.backend.get(f"{workflow_id}/dag.pkl")
        if data is None:
            raise FileNotFoundError(f"no dag for workflow {workflow_id}")
        return pickle.loads(data)

    def set_status(self, workflow_id: str, status: str) -> None:
        self.backend.put(f"{workflow_id}/status", status.encode())

    def get_status(self, workflow_id: str) -> Optional[str]:
        data = self.backend.get(f"{workflow_id}/status")
        return data.decode() if data is not None else None

    def list_workflows(self) -> List[str]:
        return [w for w in self.backend.list_prefix("")
                if w != "actors"]

    # ---- per-step ----

    def has_step_output(self, workflow_id: str, step_id: str) -> bool:
        return self.backend.exists(
            f"{workflow_id}/steps/{step_id}/output.pkl")

    def save_step_output(self, workflow_id: str, step_id: str,
                         value: Any) -> None:
        self.backend.put(f"{workflow_id}/steps/{step_id}/output.pkl",
                         cloudpickle.dumps(value))

    def load_step_output(self, workflow_id: str, step_id: str) -> Any:
        data = self.backend.get(
            f"{workflow_id}/steps/{step_id}/output.pkl")
        if data is None:
            raise FileNotFoundError(
                f"no output for {workflow_id}/{step_id}")
        return pickle.loads(data)

    def try_load_step_output(self, workflow_id: str, step_id: str):
        """(found, value) in ONE backend fetch — the resume hot path
        would otherwise transfer every checkpoint twice (exists + load)
        over remote backends."""
        data = self.backend.get(
            f"{workflow_id}/steps/{step_id}/output.pkl")
        if data is None:
            return False, None
        return True, pickle.loads(data)

    # ---- virtual actors ----

    def save_actor_state(self, actor_id: str, seq: int,
                         state: Any) -> None:
        self.backend.put(f"actors/{actor_id}/state.pkl",
                         cloudpickle.dumps((seq, state)))

    def load_actor_state(self, actor_id: str):
        """Returns (seq, state) or None if the actor was never created."""
        data = self.backend.get(f"actors/{actor_id}/state.pkl")
        return pickle.loads(data) if data is not None else None

    def list_actors(self) -> List[str]:
        return self.backend.list_prefix("actors")
