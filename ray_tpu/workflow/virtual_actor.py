"""Virtual actors: durable actors addressed by id, state in storage.

Parity target: the reference's virtual actor layer
(reference: python/ray/workflow/virtual_actor_class.py — VirtualActor,
``get_or_create`` :86, readonly methods). A virtual actor holds no
process: each method call runs as a task that loads the persisted
instance, applies the method, and checkpoints the new state before the
result is returned. The actor therefore survives cluster restarts and
driver crashes, and is resumable from any driver that shares the
storage.

Usage::

    from ray_tpu import workflow

    @workflow.virtual_actor
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

        @workflow.virtual_actor.readonly
        def peek(self):
            return self.n

    workflow.init(storage="/tmp/wf")
    c = Counter.get_or_create("my_counter")
    assert c.incr.run() == 1
    # ... crash, new driver ...
    c = workflow.get_actor("my_counter")
    assert c.incr.run() == 2

Consistency model: calls made through ONE handle are totally ordered
(each call chains on the previous call's ref). Concurrent handles are
last-write-wins, as in the reference's non-locking storage backends.
"""

from __future__ import annotations

import pickle
from typing import Any, Optional

try:
    import cloudpickle
except ImportError:  # pragma: no cover
    cloudpickle = pickle

import ray_tpu
from ray_tpu.workflow.storage import WorkflowStorage


class _Failed:
    """Resolved value of a failed call: the task returns this marker
    instead of raising, so the handle's order chain (``_tail``) stays
    usable — a raised ref would poison every later chained call with
    the stored error. ``run()`` re-raises it for the caller."""

    def __init__(self, error: BaseException):
        self.error = error


@ray_tpu.remote
def _run_actor_method(storage_url: str, actor_id: str, method: str,
                      readonly: bool, args: tuple, kwargs: dict,
                      _after):
    """One virtual-actor method call as a task. ``_after`` is the
    previous call's ref (or None): a top-level arg the runtime resolves
    first, giving per-handle total ordering."""
    store = WorkflowStorage(storage_url)
    rec = store.load_actor_state(actor_id)
    if rec is None:
        return _Failed(
            ValueError(f"virtual actor {actor_id!r} does not exist"))
    seq, inst = rec
    try:
        result = getattr(inst, method)(*args, **kwargs)
    except BaseException as e:  # noqa: BLE001 — surfaced via run()
        return _Failed(e)  # state NOT persisted: the call never happened
    if not readonly:
        store.save_actor_state(actor_id, seq + 1, inst)
    return result


class _VirtualMethod:
    def __init__(self, handle: "VirtualActorHandle", name: str,
                 readonly: bool):
        self._handle = handle
        self._name = name
        self._readonly = readonly

    def run_async(self, *args, **kwargs):
        """Returns the call's ObjectRef. A failed call resolves to a
        ``_Failed`` marker (it would poison the order chain if it
        raised); ``run()`` translates it back into the exception."""
        h = self._handle
        ref = _run_actor_method.remote(
            h._storage_url, h._actor_id, self._name, self._readonly,
            args, kwargs, None if self._readonly else h._tail)
        if not self._readonly:
            h._tail = ref
        return ref

    def run(self, *args, **kwargs):
        out = ray_tpu.get(self.run_async(*args, **kwargs))
        if isinstance(out, _Failed):
            raise out.error
        return out


class VirtualActorHandle:
    """Client-side handle; ``_tail`` chains mutating calls in order."""

    def __init__(self, cls, actor_id: str, storage_url: str):
        self._cls = cls
        self._actor_id = actor_id
        self._storage_url = storage_url
        self._tail = None

    def __getattr__(self, name: str):
        method = getattr(self._cls, name, None)
        if method is None or not callable(method):
            raise AttributeError(
                f"virtual actor {self._cls.__name__} has no method "
                f"{name!r}")
        return _VirtualMethod(
            self, name, getattr(method, "__workflow_readonly__", False))


class VirtualActorClass:
    """What ``@workflow.virtual_actor`` returns: a factory for durable
    instances addressed by id."""

    def __init__(self, cls):
        self._cls = cls
        self.__name__ = cls.__name__

    def get_or_create(self, actor_id: str, *init_args,
                      **init_kwargs) -> VirtualActorHandle:
        from ray_tpu import workflow

        store = workflow._get_storage()
        if store.load_actor_state(actor_id) is None:
            inst = self._cls(*init_args, **init_kwargs)
            store.save_actor_state(actor_id, 0, inst)
            # class ships to storage so get_actor() works class-free
            store.backend.put(f"actors/{actor_id}/class.pkl",
                              cloudpickle.dumps(self._cls))
        return VirtualActorHandle(self._cls, actor_id, store.url)

    def __call__(self, *a, **kw):
        raise RuntimeError(
            "virtual actors are created with .get_or_create(actor_id), "
            "not instantiated directly")


def virtual_actor(cls):
    """``@workflow.virtual_actor`` class decorator."""
    return VirtualActorClass(cls)


def _readonly(fn):
    """``@workflow.virtual_actor.readonly``: the method reads state but
    never persists it (and doesn't order against mutating calls)."""
    fn.__workflow_readonly__ = True
    return fn


virtual_actor.readonly = _readonly


def get_actor(actor_id: str) -> VirtualActorHandle:
    """Look up an existing virtual actor by id (class comes from
    storage — no local class definition needed)."""
    from ray_tpu import workflow

    store = workflow._get_storage()
    data = store.backend.get(f"actors/{actor_id}/class.pkl")
    if data is None or store.load_actor_state(actor_id) is None:
        raise ValueError(f"no virtual actor with id {actor_id!r}")
    return VirtualActorHandle(pickle.loads(data), actor_id, store.url)
