"""Durable workflows: a checkpointed step DAG with crash recovery.

Parity target: the reference's Workflow library
(reference: python/ray/workflow/ — step_executor.py, WorkflowStorage
workflow_storage.py:89, recovery.py). Steps are remote tasks whose
outputs checkpoint to durable storage before the value is used
downstream; ``resume`` reloads the persisted DAG and re-executes only
the steps without a checkpoint. Step continuations (a step returning
another workflow) are supported — that's the recursion/loop primitive.

Usage::

    from ray_tpu import workflow

    workflow.init(storage="/tmp/wf")

    @workflow.step
    def add(a, b):
        return a + b

    out = add.step(add.step(1, 2), 3).run(workflow_id="sum3")  # 6
    workflow.resume("sum3")  # replays from checkpoints -> 6
"""

from __future__ import annotations

import functools
import os
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.workflow.storage import WorkflowStorage
from ray_tpu.workflow.virtual_actor import (  # noqa: F401
    VirtualActorHandle, get_actor, virtual_actor)

__all__ = ["init", "step", "Workflow", "resume", "get_output",
           "get_status", "list_all", "virtual_actor", "get_actor"]

_storage: Optional[WorkflowStorage] = None


def init(storage: Optional[str] = None) -> None:
    """Set the durable storage root: a path, ``file://``, ``kv://``
    (cluster-internal GCS KV) or ``s3://`` URL (defaults to
    ``~/.ray_tpu_workflows`` or ``$RAY_TPU_WORKFLOW_STORAGE``)."""
    global _storage
    base = (storage or os.environ.get("RAY_TPU_WORKFLOW_STORAGE")
            or os.path.expanduser("~/.ray_tpu_workflows"))
    _storage = WorkflowStorage(base)


def _get_storage() -> WorkflowStorage:
    if _storage is None:
        init()
    return _storage


class Workflow:
    """A step DAG node: function + args (args may be Workflows)."""

    def __init__(self, fn, args: tuple, kwargs: dict,
                 name: Optional[str] = None, max_retries: int = 0):
        self._fn = fn
        self._args = args
        self._kwargs = kwargs
        self._name = name or fn.__name__
        self._max_retries = max_retries

    def run(self, workflow_id: Optional[str] = None) -> Any:
        """Execute to completion (blocking) with checkpointing."""
        workflow_id = workflow_id or uuid.uuid4().hex[:12]
        try:
            return ray_tpu.get(self.run_async(workflow_id))
        except Exception:
            _get_storage().set_status(workflow_id, "FAILED")
            raise

    def run_async(self, workflow_id: Optional[str] = None):
        """Start execution; returns an ObjectRef of the final output."""
        workflow_id = workflow_id or uuid.uuid4().hex[:12]
        store = _get_storage()
        store.save_dag(workflow_id, self)
        store.set_status(workflow_id, "RUNNING")
        return _execute_dag(store, workflow_id, self)


def step(_fn=None, *, name: Optional[str] = None, max_retries: int = 0):
    """``@workflow.step`` decorator (bare or with options)."""
    def wrap(fn):
        return StepFunction(fn, name=name, max_retries=max_retries)

    if _fn is not None:
        return wrap(_fn)
    return wrap


class StepFunction:
    def __init__(self, fn, name: Optional[str] = None,
                 max_retries: int = 0):
        self._fn = fn
        self._name = name
        self._max_retries = max_retries
        functools.update_wrapper(self, fn)

    def step(self, *args, **kwargs) -> Workflow:
        return Workflow(self._fn, args, kwargs, name=self._name,
                        max_retries=self._max_retries)

    def options(self, name: Optional[str] = None,
                max_retries: Optional[int] = None) -> "StepFunction":
        return StepFunction(
            self._fn, name=name or self._name,
            max_retries=self._max_retries if max_retries is None
            else max_retries)

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)  # plain local call


# --------------------------------------------------------------- execution

class _Continuation:
    """Wire marker: a step returned another workflow."""

    def __init__(self, dag: Workflow):
        self.dag = dag


@ray_tpu.remote
def _run_step(storage_url: str, workflow_id: str, step_id: str, fn,
              nargs: int, kwarg_keys, *values):
    """One step as a remote task. Upstream values arrive as TOP-LEVEL
    ObjectRef arguments in ``values`` (the runtime resolves top-level
    refs only — reference parity — so args/kwargs are flattened and
    rebuilt here). Execution order AND sibling parallelism come from
    normal task scheduling. Idempotent: a checkpointed output
    short-circuits re-execution on resume."""
    args = values[:nargs]
    kwargs = dict(zip(kwarg_keys, values[nargs:]))
    store = WorkflowStorage(storage_url)
    found, cached = store.try_load_step_output(workflow_id, step_id)
    if found:
        return cached
    result = fn(*args, **kwargs)
    if isinstance(result, Workflow):
        # Continuation: checkpoint the DAG, not the (unknown) value;
        # the driver-side executor picks it up.
        result = _Continuation(result)
    store.save_step_output(workflow_id, step_id, result)
    return result


def _assign_step_ids(node: Workflow, prefix: str,
                     counter: Dict[str, int]) -> Dict[int, str]:
    """Deterministic step ids: name + DFS ordinal (stable across the
    identical DAG pickle loaded by resume)."""
    ids: Dict[int, str] = {}

    def visit(n: Workflow):
        if id(n) in ids:
            return
        for a in list(n._args) + list(n._kwargs.values()):
            if isinstance(a, Workflow):
                visit(a)
        k = n._name
        counter[k] = counter.get(k, 0) + 1
        ids[id(n)] = f"{prefix}{k}_{counter[k]}"

    visit(node)
    return ids


def _submit_steps(store: WorkflowStorage, workflow_id: str,
                  root: Workflow, prefix: str = ""):
    """Submit every step as a task wired by (top-level) ObjectRef args.
    Returns (root_step_id, root_ref)."""
    ids = _assign_step_ids(root, prefix, {})
    refs: Dict[int, Any] = {}

    def submit(n: Workflow):
        if id(n) in refs:
            return refs[id(n)]
        args = tuple(submit(a) if isinstance(a, Workflow) else a
                     for a in n._args)
        kwargs = {k: (submit(v) if isinstance(v, Workflow) else v)
                  for k, v in n._kwargs.items()}
        opts = _run_step.options(max_retries=n._max_retries) \
            if n._max_retries else _run_step
        refs[id(n)] = opts.remote(
            store.url, workflow_id, ids[id(n)], n._fn,
            len(args), list(kwargs), *args, *kwargs.values())
        return refs[id(n)]

    return ids[id(root)], submit(root)


def _execute_dag(store: WorkflowStorage, workflow_id: str,
                 root: Workflow):
    root_id, root_ref = _submit_steps(store, workflow_id, root)
    return _finalize.remote(store.url, workflow_id, root_id,
                            root_ref)


@ray_tpu.remote
def _finalize(storage_url: str, workflow_id: str, root_step_id: str,
              result):
    """Resolve continuations, then mark the workflow SUCCESSFUL.

    ONE finalize task per workflow run: the continuation loop lives
    here (submitting step tasks and blocking on their refs) instead of
    chaining nested finalize tasks, which would hold one worker per
    continuation depth and deadlock the pool on deep tail recursion."""
    store = WorkflowStorage(storage_url)
    depth = 0
    while isinstance(result, _Continuation):
        depth += 1
        _, ref = _submit_steps(store, workflow_id, result.dag,
                               prefix=f"{root_step_id}/c{depth}/")
        result = ray_tpu.get(ref)
    store.save_step_output(workflow_id, "__output__", result)
    store.set_status(workflow_id, "SUCCESSFUL")
    return result


# --------------------------------------------------------------- management

def resume(workflow_id: str) -> Any:
    """Re-execute a workflow from its last checkpoints (blocking)."""
    # Lookup errors (unknown id) must raise cleanly, NOT stamp a
    # phantom FAILED record — only an actual re-execution may fail.
    ref = resume_async(workflow_id)
    try:
        return ray_tpu.get(ref)
    except Exception:
        _get_storage().set_status(workflow_id, "FAILED")
        raise


def resume_async(workflow_id: str):
    store = _get_storage()
    if store.get_status(workflow_id) is None:
        raise ValueError(f"no workflow with id {workflow_id!r}")
    dag = store.load_dag(workflow_id)
    store.set_status(workflow_id, "RUNNING")
    return _execute_dag(store, workflow_id, dag)


def get_output(workflow_id: str) -> Any:
    """Fetch the checkpointed final output of a finished workflow."""
    store = _get_storage()
    status = store.get_status(workflow_id)
    if status != "SUCCESSFUL":
        hint = ("it failed — fix the step and resume()"
                if status == "FAILED" else "resume() it first")
        raise ValueError(
            f"workflow {workflow_id!r} is {status or 'unknown'}; {hint}")
    return store.load_step_output(workflow_id, "__output__")


def get_status(workflow_id: str) -> Optional[str]:
    return _get_storage().get_status(workflow_id)


def list_all() -> List[str]:
    return _get_storage().list_workflows()
