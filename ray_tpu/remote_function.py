"""@remote functions.

Role parity: reference python/ray/remote_function.py RemoteFunction —
decoration captures the function plus default task options; ``.remote()``
exports once via the function manager and submits through the core worker;
``.options()`` creates a shallow override.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu import worker as worker_mod


class RemoteFunction:
    def __init__(self, fn, num_returns=1, num_cpus=None, num_tpus=None,
                 resources=None, max_retries=None, retry_exceptions=False,
                 runtime_env=None, scheduling_strategy="DEFAULT",
                 placement_group=None, placement_group_bundle_index=-1,
                 name=None):
        self._function = fn
        self._name = (name or getattr(fn, "__qualname__", None)
                      or getattr(fn, "__name__", None)
                      or getattr(getattr(fn, "func", None), "__qualname__",
                                 None)  # functools.partial
                      or type(fn).__name__)
        self._num_returns = num_returns
        self._num_cpus = num_cpus
        self._num_tpus = num_tpus
        self._resources = resources or {}
        self._max_retries = max_retries
        self._retry_exceptions = retry_exceptions
        self._runtime_env = runtime_env
        self._scheduling_strategy = scheduling_strategy
        self._placement_group = placement_group
        self._placement_group_bundle_index = placement_group_bundle_index
        self._fn_key: Optional[str] = None
        self._pickled: Optional[bytes] = None
        self._demand: Optional[Dict[str, float]] = None
        # (core, job_id, prototype TaskSpec) — see CoreWorker
        # .make_task_template; invalidated on reconnect / job adoption
        self._template = None
        # (core, job_id, zero-arg submit closure) for the dominant
        # no-arg single-return driver-side call — one closure call
        # instead of re-validating the template chain per .remote()
        self._fastcall = None
        functools.update_wrapper(self, fn)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._name} cannot be called directly; use "
            f"{self._name}.remote()")

    def _resource_demand(self) -> Dict[str, float]:
        # Cached: the demand is fixed per RemoteFunction and read once per
        # .remote() call (the TaskSpec treats it as immutable).
        if self._demand is None:
            demand = dict(self._resources)
            demand["CPU"] = float(
                self._num_cpus if self._num_cpus is not None else 1)
            if self._num_tpus:
                demand["TPU"] = float(self._num_tpus)
            self._demand = demand
        return self._demand

    def remote(self, *args, **kwargs):
        if not args and not kwargs:
            fc = self._fastcall
            if fc is not None:
                w = worker_mod.global_worker
                if w is not None and fc[0] is w.core and \
                        fc[1] == fc[0].job_id:
                    return fc[2]()
        w = worker_mod._require_connected()
        core = w.core
        if self._fn_key is None:
            self._fn_key, self._pickled = \
                core.function_manager.prepare(self._function)
        if self._template is None or self._template[0] is not core:
            # once per (core, fn): the template cache below implies the
            # export happened for this core already
            core.function_manager.export_prepickled(
                self._fn_key, self._pickled, self._function)
        if not hasattr(core, "make_task_template"):
            # ray:// client core: no template fast path — submit per call
            call_args = list(args)
            if kwargs:
                call_args.append({"__rtpu_kwargs__": True, "kwargs": kwargs})
            pg = self._placement_group
            refs = core.submit_task(
                fn_key=self._fn_key, name=self._name, args=call_args,
                num_returns=self._num_returns,
                resources=self._resource_demand(),
                max_retries=self._max_retries,
                retry_exceptions=self._retry_exceptions,
                placement_group_id=pg.id.binary() if pg is not None else b"",
                placement_group_bundle_index=self._placement_group_bundle_index,
                scheduling_strategy=self._scheduling_strategy,
                runtime_env=self._runtime_env)
            if self._num_returns == 0:
                return None
            return refs[0] if self._num_returns == 1 else refs
        tmpl = self._template
        if tmpl is not None and self._runtime_env:
            # working_dir / local-wheel envs re-resolve per call: the
            # content hash must track edits made between submissions
            # (prepare_runtime_env's _dir_signature cache makes the
            # unchanged case cheap). Envs without local content resolve
            # to themselves, so this never rebuilds for plain env_vars.
            if core._resolve_runtime_env(self._runtime_env) != \
                    tmpl[2].runtime_env:
                tmpl = None
        if tmpl is None or tmpl[0] is not core or tmpl[1] != core.job_id:
            pg = self._placement_group
            proto = core.make_task_template(
                fn_key=self._fn_key, name=self._name,
                num_returns=self._num_returns,
                resources=self._resource_demand(),
                max_retries=self._max_retries,
                retry_exceptions=self._retry_exceptions,
                placement_group_id=pg.id.binary() if pg is not None else b"",
                placement_group_bundle_index=self._placement_group_bundle_index,
                scheduling_strategy=self._scheduling_strategy,
                runtime_env=self._runtime_env)
            tmpl = self._template = (core, core.job_id, proto)
        if kwargs:
            args = list(args) + \
                [{"__rtpu_kwargs__": True, "kwargs": kwargs}]
        refs = core.submit_task_from_template(tmpl[2], args)
        if self._num_returns == 1 and not self._runtime_env and \
                core.mode == "driver" and core._fast_ctx is not None:
            fc = self._fastcall
            if fc is None or fc[0] is not core or fc[1] != core.job_id:
                # (re)bind after connect/reconnect/job adoption
                self._fastcall = (core, core.job_id,
                                  self._make_fastcall(core, tmpl[2]))
        if self._num_returns == 0:
            return None
        if self._num_returns == 1:
            return refs[0]
        return refs

    @staticmethod
    def _make_fastcall(core, proto):
        """Zero-arg driver-side submit closure over the native ctx
        (everything template-validated once, here)."""
        from ray_tpu._private.core_worker import _trace_ctx

        submit = core._fast_ctx.submit

        def _call0():
            return submit(proto, core._task_lineage_prefix,
                          _trace_ctx())[0]

        return _call0

    def options(self, **overrides):
        """Return a copy with per-call option overrides (reference:
        RemoteFunction.options)."""
        allowed = {"num_returns", "num_cpus", "num_tpus", "resources",
                   "max_retries", "retry_exceptions", "runtime_env",
                   "scheduling_strategy", "placement_group",
                   "placement_group_bundle_index", "name"}
        bad = set(overrides) - allowed
        if bad:
            raise ValueError(f"unknown options: {sorted(bad)}")
        base = {
            "num_returns": self._num_returns, "num_cpus": self._num_cpus,
            "num_tpus": self._num_tpus, "resources": self._resources,
            "max_retries": self._max_retries,
            "retry_exceptions": self._retry_exceptions,
            "runtime_env": self._runtime_env,
            "scheduling_strategy": self._scheduling_strategy,
            "placement_group": self._placement_group,
            "placement_group_bundle_index": self._placement_group_bundle_index,
            "name": self._name,
        }
        base.update(overrides)
        clone = RemoteFunction(self._function, **base)
        clone._fn_key = self._fn_key
        clone._pickled = self._pickled
        return clone


def make_remote(fn_or_class=None, **options):
    """Implementation of the @remote decorator (functions and classes)."""
    import inspect

    from ray_tpu.actor import ActorClass

    def decorate(target):
        if inspect.isclass(target):
            return ActorClass(target, **options)
        return RemoteFunction(target, **options)

    if fn_or_class is not None:
        return decorate(fn_or_class)
    return decorate
