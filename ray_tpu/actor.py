"""Actor classes, handles and methods.

Role parity: reference python/ray/actor.py — ``@remote`` on a class yields
an ``ActorClass`` whose ``.remote(...)`` registers the actor with the GCS
and returns an ``ActorHandle``; method calls go through ``ActorMethod`` to
the core worker's ordered per-actor submission queue. Handles serialize
into tasks/objects and reconstruct on any process (borrowed handles).
"""

from __future__ import annotations

import functools
import inspect
from typing import Any, Dict, Optional

from ray_tpu import worker as worker_mod


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: int = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns
        # (core, prototype TaskSpec) cache for the dominant arg-less
        # single-return call — per-call work drops to the fused native
        # submit (see CoreWorker.submit_actor_from_template)
        self._template = None

    def remote(self, *args, **kwargs):
        if not args and not kwargs and self._num_returns == 1:
            h = self._handle
            core = h._core
            tmpl = self._template
            if tmpl is None or tmpl[0] is not core:
                if hasattr(core, "make_actor_template"):
                    proto = core.make_actor_template(
                        h._actor_id, h._fn_key,
                        f"{h._class_name}.{self._method_name}",
                        num_returns=1,
                        max_task_retries=h._max_task_retries)
                    tmpl = self._template = (core, proto)
                else:
                    # core without templates (ray:// client): drop any
                    # stale tuple so we fall through to _submit
                    tmpl = self._template = None
            if tmpl is not None:
                return core.submit_actor_from_template(tmpl[1])[0]
        return self._handle._submit(self._method_name, args, kwargs,
                                    num_returns=self._num_returns)

    def options(self, num_returns: int = 1):
        return ActorMethod(self._handle, self._method_name, num_returns)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor method {self._method_name} cannot be called directly; "
            f"use .remote()")


class ActorHandle:
    def __init__(self, core, actor_id: bytes, class_name: str, fn_key: str,
                 max_task_retries: int = 0, method_num_returns=None):
        self._core = core
        self._actor_id = actor_id
        self._class_name = class_name
        self._fn_key = fn_key
        self._max_task_retries = max_task_retries
        self._method_num_returns = method_num_returns or {}

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        m = ActorMethod(self, item,
                        self._method_num_returns.get(item, 1))
        # cache: subsequent handle.method reads skip __getattr__ AND
        # keep the method's template cache alive across calls (the
        # per-access ActorMethod construction was ~1us/call on the
        # actor microbenchmarks). Serialization is unaffected:
        # handles serialize via _serialization_state, not __dict__.
        self.__dict__[item] = m
        return m

    def _submit(self, method_name: str, args, kwargs, num_returns: int = 1):
        call_args = list(args)
        if kwargs:
            call_args.append({"__rtpu_kwargs__": True, "kwargs": kwargs})
        refs = self._core.submit_actor_task(
            self._actor_id, self._fn_key,
            f"{self._class_name}.{method_name}", call_args,
            num_returns=num_returns,
            max_task_retries=self._max_task_retries)
        if num_returns == 0:
            return None
        if num_returns == 1:
            return refs[0]
        return refs

    def _serialization_state(self):
        return {"actor_id": self._actor_id, "class_name": self._class_name,
                "fn_key": self._fn_key,
                "max_task_retries": self._max_task_retries,
                "method_num_returns": self._method_num_returns}

    def __repr__(self):
        from ray_tpu._private.ids import ActorID
        return f"ActorHandle({self._class_name}, {ActorID(self._actor_id).hex()[:12]})"

    def __reduce__(self):
        raise RuntimeError(
            "ActorHandle can only be serialized through the runtime "
            "(pass it to a task or put it in an object)")


def _handle_factory(core, state) -> ActorHandle:
    return ActorHandle(core, state["actor_id"], state["class_name"],
                       state["fn_key"],
                       max_task_retries=state.get("max_task_retries", 0),
                       method_num_returns=state.get("method_num_returns"))


def register_with_core_worker(core):
    core.register_actor_handle_factory(_handle_factory)


class ActorClass:
    def __init__(self, cls, num_cpus=None, num_tpus=None, resources=None,
                 max_restarts=0, max_task_retries=0, max_concurrency=None,
                 num_returns=1, runtime_env=None, name=None, namespace=None,
                 lifetime=None, placement_group=None,
                 placement_group_bundle_index=-1, max_pending_calls=-1,
                 scheduling_strategy="DEFAULT", max_retries=None,
                 retry_exceptions=False, get_if_exists=False):
        self._cls = cls
        self._class_name = cls.__name__
        self._num_cpus = num_cpus
        self._num_tpus = num_tpus
        self._resources = resources or {}
        self._max_restarts = max_restarts
        self._max_task_retries = max_task_retries
        self._is_asyncio = any(
            inspect.iscoroutinefunction(m)
            for _, m in inspect.getmembers(cls, inspect.isfunction))
        self._max_concurrency = max_concurrency if max_concurrency is not None \
            else (1000 if self._is_asyncio else 1)
        self._runtime_env = runtime_env
        self._name = name
        self._namespace = namespace
        self._lifetime = lifetime
        self._placement_group = placement_group
        self._placement_group_bundle_index = placement_group_bundle_index
        self._max_pending_calls = max_pending_calls
        self._get_if_exists = get_if_exists
        self._fn_key: Optional[str] = None
        self._pickled: Optional[bytes] = None
        # @ray_tpu.method(num_returns=N) annotations on the class's methods.
        self._method_num_returns = {
            mname: getattr(m, "__rtpu_num_returns__")
            for mname, m in inspect.getmembers(cls, callable)
            if hasattr(m, "__rtpu_num_returns__")}
        functools.update_wrapper(self, cls, updated=[])

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self._class_name} cannot be instantiated directly;"
            f" use {self._class_name}.remote()")

    def _resource_demand(self) -> Dict[str, float]:
        demand = dict(self._resources)
        demand["CPU"] = float(self._num_cpus if self._num_cpus is not None else 1)
        if self._num_tpus:
            demand["TPU"] = float(self._num_tpus)
        return demand

    def _lifetime_resources(self) -> Dict[str, float]:
        """Resources held while the actor is alive. Reference parity
        (python/ray/actor.py): an unspecified num_cpus means 1 CPU to
        schedule the creation task but 0 held for the actor's lifetime —
        default actors pack onto a node without consuming CPU slots."""
        lifetime = dict(self._resources)
        if self._num_cpus is not None:
            lifetime["CPU"] = float(self._num_cpus)
        if self._num_tpus:
            lifetime["TPU"] = float(self._num_tpus)
        return lifetime

    def remote(self, *args, **kwargs):
        if self._get_if_exists and self._name:
            # race-free named-actor rendezvous (reference parity:
            # ray 1.x used bare name= + try/except; modern get_if_exists)
            try:
                return get_actor(self._name, self._namespace)
            except ValueError:
                pass
            try:
                return self._do_create(args, kwargs)
            except Exception as e:  # noqa: BLE001 - name race only
                if "already taken" in str(e):
                    return get_actor(self._name, self._namespace)
                raise
        return self._do_create(args, kwargs)

    def _do_create(self, args, kwargs):
        w = worker_mod._require_connected()
        if self._fn_key is None:
            self._fn_key, self._pickled = \
                w.core.function_manager.prepare(self._cls)
        w.core.function_manager.export_prepickled(
            self._fn_key, self._pickled, self._cls)
        call_args = list(args)
        if kwargs:
            call_args.append({"__rtpu_kwargs__": True, "kwargs": kwargs})
        pg = self._placement_group
        actor_id = w.core.create_actor(
            fn_key=self._fn_key, name=self._class_name, args=call_args,
            actor_name=self._name or "",
            namespace=self._namespace or worker_mod.global_worker.namespace,
            max_restarts=self._max_restarts,
            max_concurrency=self._max_concurrency,
            resources=self._resource_demand(),
            lifetime_resources=self._lifetime_resources(),
            is_asyncio=self._is_asyncio,
            runtime_env=self._runtime_env,
            placement_group_id=pg.id.binary() if pg is not None else b"",
            placement_group_bundle_index=self._placement_group_bundle_index,
            max_pending_calls=self._max_pending_calls)
        return ActorHandle(w.core, actor_id, self._class_name, self._fn_key,
                           max_task_retries=self._max_task_retries,
                           method_num_returns=self._method_num_returns)

    def options(self, **overrides):
        allowed = {"num_cpus", "num_tpus", "resources", "max_restarts",
                   "max_task_retries", "max_concurrency", "runtime_env",
                   "name", "namespace", "lifetime", "placement_group",
                   "placement_group_bundle_index", "max_pending_calls",
                   "scheduling_strategy", "num_returns", "get_if_exists"}
        bad = set(overrides) - allowed
        if bad:
            raise ValueError(f"unknown actor options: {sorted(bad)}")
        base = {
            "num_cpus": self._num_cpus, "num_tpus": self._num_tpus,
            "resources": self._resources, "max_restarts": self._max_restarts,
            "max_task_retries": self._max_task_retries,
            "max_concurrency": self._max_concurrency,
            "runtime_env": self._runtime_env, "name": self._name,
            "namespace": self._namespace, "lifetime": self._lifetime,
            "placement_group": self._placement_group,
            "placement_group_bundle_index": self._placement_group_bundle_index,
            "max_pending_calls": self._max_pending_calls,
            "get_if_exists": self._get_if_exists,
        }
        base.update(overrides)
        clone = ActorClass(self._cls, **base)
        clone._fn_key = self._fn_key
        clone._pickled = self._pickled
        return clone


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    """Look up a named actor (reference: ray.get_actor)."""
    w = worker_mod._require_connected()
    reply, _ = w.core._run(w.core._gcs_call("GetNamedActor", {
        "name": name,
        "namespace": namespace if namespace is not None
        else worker_mod.global_worker.namespace}))
    if not reply.get("found"):
        raise ValueError(f"no actor named {name!r}")
    spec = reply["spec"]
    return ActorHandle(w.core, reply["actor_id"], spec["name"], spec["fn_key"])


def list_named_actors(namespace: Optional[str] = None):
    w = worker_mod._require_connected()
    reply, _ = w.core._run(w.core._gcs_call(
        "ListNamedActors", {"namespace": namespace}))
    return [a["name"] for a in reply["actors"]]
