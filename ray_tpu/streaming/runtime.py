"""Streaming runtime: operator actors linked by credit-controlled channels.

Parity target: the reference's streaming engine (reference:
streaming/src/ — DataWriter/DataReader data_writer.h, data_reader.h,
credit-based flow_control.h, barrier/checkpoint reliability
reliability/barrier_helper.h, transport over direct actor calls in
streaming/src/queue/). Re-design: each operator is an async actor;
records flow downstream as batched actor calls; the receiver admits at
most ``capacity`` in-flight records per input channel and withholds
the push REPLY while full — the sender awaits it, so the blocked reply
is the credit window. Barriers flow in-band: an operator aligns barriers from
all inputs, snapshots its state, and forwards the barrier downstream
(Chandy-Lamport style, the public pattern the reference implements).
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable, Dict, List, Optional

class Barrier:
    """In-band checkpoint marker (typed: user records can never be
    mistaken for control messages)."""

    def __init__(self, barrier_id: int):
        self.barrier_id = barrier_id


class Eos:
    """In-band end-of-stream marker."""


class StreamOperator:
    """Async actor hosting one pipeline stage.

    fn(record) → list of output records (map=1, filter=0/1, flat_map=n)
    For keyed reduce, the operator keeps per-key state and emits
    updated (key, value) pairs.
    """

    def __init__(self, op_kind: str, fn: Optional[Callable],
                 capacity: int = 256, num_inputs: int = 1):
        self.op_kind = op_kind
        self.fn = fn
        self.capacity = capacity
        self.num_inputs = num_inputs
        self.downstream = None           # ActorHandle or None (sink)
        self._inflight = 0
        self._space = asyncio.Condition()
        self._queue: Optional[asyncio.Queue] = None
        self._consumer: Optional[asyncio.Task] = None
        self._barrier_waiting: Dict[int, int] = {}  # barrier_id → count
        self._eos_seen = 0
        self._state: Dict[Any, Any] = {}  # keyed-reduce state
        self._sink_out: List[Any] = []
        self._snapshots: Dict[int, dict] = {}
        self._error: Optional[str] = None

    def set_downstream(self, handle) -> None:
        self.downstream = handle

    # ---- data plane ----

    async def push(self, records: List[Any]) -> None:
        """Receive a batch from upstream. The reply is DELAYED while
        the operator is over capacity — that blocked reply IS the
        backpressure (the sender awaits it before sending more). A
        single consumer task processes admitted batches strictly in
        arrival order (records and barriers must not reorder)."""
        if self._consumer is None:
            self._queue = asyncio.Queue()
            self._consumer = asyncio.get_running_loop().create_task(
                self._consume_loop())
        async with self._space:
            await self._space.wait_for(
                lambda: self._inflight < self.capacity)
            self._inflight += len(records)
        self._queue.put_nowait(records)

    async def _consume_loop(self) -> None:
        while True:
            records = await self._queue.get()
            try:
                await self._process(records)
            except Exception as e:  # noqa: BLE001 — driver polls error()
                import traceback

                if self._error is None:
                    self._error = (f"{type(e).__name__}: {e}\n"
                                   f"{traceback.format_exc()}")
            finally:
                # credit MUST return even when user code raised, or the
                # channel wedges at capacity
                async with self._space:
                    self._inflight -= len(records)
                    self._space.notify_all()

    async def _process(self, records: List[Any]) -> None:
        out: List[Any] = []
        control: List[Any] = []
        for rec in records:
            if isinstance(rec, (Barrier, Eos)):
                control.append(rec)
                continue
            out.extend(self._apply(rec))
        if out:
            if self.downstream is not None:
                await self._send(out)
            else:
                self._sink_out.extend(out)
        for rec in control:
            await self._handle_control(rec)

    def _apply(self, rec: Any) -> List[Any]:
        if self.op_kind == "map":
            return [self.fn(rec)]
        if self.op_kind == "filter":
            return [rec] if self.fn(rec) else []
        if self.op_kind == "flat_map":
            return list(self.fn(rec))
        if self.op_kind == "reduce":
            key, value = rec
            if key in self._state:
                self._state[key] = self.fn(self._state[key], value)
            else:
                self._state[key] = value
            return [(key, self._state[key])]
        if self.op_kind == "sink":
            return [self.fn(rec) if self.fn else rec]
        raise ValueError(f"unknown op kind {self.op_kind!r}")

    async def _send(self, records: List[Any]) -> None:
        # the await paces this operator to the receiver's admission
        # rate (the reply is withheld while the receiver is full)
        await self.downstream.push.remote(records)

    async def _handle_control(self, rec) -> None:
        if isinstance(rec, Eos):
            self._eos_seen += 1
            if self._eos_seen >= self.num_inputs:
                if self.downstream is not None:
                    await self.downstream.push.remote([Eos()])
            return
        barrier_id = rec.barrier_id
        n = self._barrier_waiting.get(barrier_id, 0) + 1
        self._barrier_waiting[barrier_id] = n
        if n >= self.num_inputs:  # aligned: snapshot + forward
            del self._barrier_waiting[barrier_id]
            self._snapshots[barrier_id] = {
                "state": dict(self._state),
                "sink_len": len(self._sink_out),
            }
            if self.downstream is not None:
                await self.downstream.push.remote([Barrier(barrier_id)])

    # ---- introspection (driver-side) ----

    async def drain(self) -> None:
        """Wait until everything admitted has been processed."""
        async with self._space:
            await self._space.wait_for(lambda: self._inflight == 0)

    async def sink_output(self) -> List[Any]:
        return list(self._sink_out)

    async def snapshot(self, barrier_id: int) -> Optional[dict]:
        return self._snapshots.get(barrier_id)

    async def eos_done(self) -> bool:
        return self._eos_seen >= self.num_inputs

    async def error(self) -> Optional[str]:
        return self._error

    async def stats(self) -> dict:
        return {"inflight": self._inflight,
                "snapshots": sorted(self._snapshots)}
