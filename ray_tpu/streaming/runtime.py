"""Streaming runtime: operator actors linked by credit-controlled channels.

Parity target: the reference's streaming engine (reference:
streaming/src/ — DataWriter/DataReader data_writer.h, data_reader.h,
credit-based flow_control.h, bounded ring_buffer/, barrier/checkpoint
reliability reliability/barrier_helper.h, transport over direct actor
calls in streaming/src/queue/). Re-design for the actor runtime:

- **Per-edge credits.** Every input edge has its own bounded window
  (``capacity`` records). ``push(channel, records)`` withholds its
  reply while the edge is over capacity; credits replenish when the
  records are *consumed*, not merely enqueued. The blocked reply is the
  credit grant — the wire protocol needs no separate credit messages
  (the reference's flow_control.h exchanges explicit credit counts
  because its channels are shared-memory rings; an actor call's reply
  slot already carries exactly one bit of "you may send again").
- **Windowed senders.** An operator keeps up to ``SEND_WINDOW``
  un-replied pushes in flight per downstream edge — pipelining without
  unbounded queues (actor-call ordering keeps batches in order).
- **Aligned barriers.** Chandy-Lamport alignment: when a barrier
  arrives on one edge, that edge STALLS (its post-barrier records are
  stashed, not processed) until the same barrier has arrived on every
  edge; then the operator snapshots its state, forwards the barrier
  once, and unstalls (reference: barrier_helper.h alignment).
- EOS: an edge at end-of-stream auto-aligns for any later barrier.
"""

from __future__ import annotations

import asyncio
from collections import deque
from typing import Any, Callable, Dict, List, Optional

SEND_WINDOW = 4


class Barrier:
    """In-band checkpoint marker (typed: user records can never be
    mistaken for control messages)."""

    def __init__(self, barrier_id: int):
        self.barrier_id = barrier_id


class Eos:
    """In-band end-of-stream marker."""


class _Edge:
    """Receiver-side state of one input channel."""

    __slots__ = ("inflight", "peak_inflight", "ready", "stalled_on",
                 "stash", "eos")

    def __init__(self):
        self.inflight = 0          # admitted, not yet consumed
        self.peak_inflight = 0     # high-water mark (tests/monitoring)
        self.ready: deque = deque()  # admitted batches awaiting the consumer
        self.stalled_on: Optional[int] = None  # barrier awaiting alignment
        self.stash: List[Any] = []  # records held while stalled
        self.eos = False


class StreamOperator:
    """Async actor hosting one pipeline stage.

    fn(record) → list of output records (map=1, filter=0/1, flat_map=n)
    For keyed reduce, the operator keeps per-key state and emits
    updated (key, value) pairs.
    """

    def __init__(self, op_kind: str, fn: Optional[Callable],
                 capacity: int = 256, num_inputs: int = 1):
        self.op_kind = op_kind
        self.fn = fn
        self.capacity = capacity
        self.num_inputs = num_inputs
        self.downstream = None           # ActorHandle or None (sink)
        self._out_channel = 0
        self._edges: Dict[int, _Edge] = {
            i: _Edge() for i in range(num_inputs)}
        self._work = asyncio.Condition()
        self._consumer: Optional[asyncio.Task] = None
        self._outstanding: deque = deque()  # windowed downstream pushes
        self._eos_forwarded = False
        self._state: Dict[Any, Any] = {}  # keyed-reduce state
        self._sink_out: List[Any] = []
        self._snapshots: Dict[int, dict] = {}
        self._error: Optional[str] = None

    def set_downstream(self, handle, channel: int = 0) -> None:
        self.downstream = handle
        self._out_channel = channel

    # ---- data plane ----

    async def push(self, records: List[Any], channel: int = 0) -> None:
        """Receive a batch on one input edge. The reply is DELAYED
        while the edge is over capacity — that blocked reply IS the
        credit window; it replenishes when the consumer processes the
        records, not when they are queued."""
        edge = self._edges[channel]
        if self._consumer is None:
            self._consumer = asyncio.get_running_loop().create_task(
                self._consume_loop())
        async with self._work:
            await self._work.wait_for(
                lambda: edge.inflight < self.capacity)
            edge.inflight += len(records)
            edge.peak_inflight = max(edge.peak_inflight, edge.inflight)
            edge.ready.append(records)
            self._work.notify_all()

    def _runnable_edge(self) -> Optional[int]:
        for cid, edge in self._edges.items():
            if edge.ready and edge.stalled_on is None:
                return cid
        return None

    async def _consume_loop(self) -> None:
        while True:
            async with self._work:
                await self._work.wait_for(
                    lambda: self._runnable_edge() is not None)
                cid = self._runnable_edge()
                edge = self._edges[cid]
                records = edge.ready.popleft()
            # mutable so the stash point can fix the credit BEFORE any
            # downstream await that might raise (else the stashed
            # records' credit would be returned twice)
            consumed_box = [len(records)]
            try:
                await self._process_edge(cid, records, consumed_box)
            except Exception as e:  # noqa: BLE001 — driver polls error()
                import traceback

                if self._error is None:
                    self._error = (f"{type(e).__name__}: {e}\n"
                                   f"{traceback.format_exc()}")
            finally:
                # credit MUST return even when user code raised, or the
                # channel wedges at capacity — but only for records
                # actually consumed: post-barrier records stashed during
                # a stall stay counted against the window until
                # alignment re-queues them, so a sender cannot push past
                # capacity while the barrier is pending.
                async with self._work:
                    edge.inflight -= consumed_box[0]
                    self._work.notify_all()

    async def _process_edge(self, cid: int, records: List[Any],
                            consumed_box: List[int]) -> None:
        """Sets ``consumed_box[0]`` to the number of records CONSUMED
        (credit to return); stashed post-barrier records are not
        consumed yet. Written at the stash point so the count is right
        even if a later await raises."""
        edge = self._edges[cid]
        out: List[Any] = []
        i = 0
        while i < len(records):
            rec = records[i]
            if isinstance(rec, Barrier):
                # stall this edge; records after the barrier wait for
                # alignment (they belong to the next epoch). Credit for
                # the stash is withheld NOW, before flush/align awaits.
                edge.stalled_on = rec.barrier_id
                edge.stash.extend(records[i + 1:])
                consumed_box[0] = i + 1
                await self._flush(out)
                out = []
                await self._maybe_align(rec.barrier_id)
                return
            if isinstance(rec, Eos):
                edge.eos = True
                await self._flush(out)
                out = []
                await self._maybe_forward_eos()
                # an ended edge can no longer block any barrier
                for bid in list(self._pending_barriers()):
                    await self._maybe_align(bid)
                i += 1
                continue
            out.extend(self._apply(rec))
            i += 1
        await self._flush(out)

    def _pending_barriers(self) -> List[int]:
        return sorted({e.stalled_on for e in self._edges.values()
                       if e.stalled_on is not None})

    async def _maybe_align(self, barrier_id: int) -> None:
        """Snapshot + forward once EVERY live edge has stalled on this
        barrier (edges at EOS auto-align)."""
        for edge in self._edges.values():
            if edge.eos:
                continue
            if edge.stalled_on != barrier_id:
                return  # still waiting on this edge
        self._snapshots[barrier_id] = {
            "state": dict(self._state),
            # the sink records themselves: recovery restores a FRESH
            # actor to this prefix for exactly-once output (reference:
            # barrier-checkpointed channel state,
            # streaming/src/reliability/barrier_helper.h)
            "sink": list(self._sink_out),
        }
        # the driver collects barrier N-1 when injecting N: anything
        # older is an unusable recovery point — holding it would grow
        # O(barriers x sink) memory
        for old in [b for b in self._snapshots if b < barrier_id - 1]:
            del self._snapshots[old]
        if self.downstream is not None:
            await self._send([Barrier(barrier_id)])
        # unstall: stashed (post-barrier) records become ready batches
        async with self._work:
            for edge in self._edges.values():
                if edge.stalled_on == barrier_id:
                    edge.stalled_on = None
                    if edge.stash:
                        # re-queue at the FRONT: stashed records precede
                        # anything admitted later on this edge. They
                        # never left the credit window (the consumer
                        # withheld their credit at the barrier), so no
                        # inflight adjustment here.
                        edge.ready.appendleft(list(edge.stash))
                        edge.stash.clear()
            self._work.notify_all()

    async def _maybe_forward_eos(self) -> None:
        if self._eos_forwarded:
            return
        if all(e.eos for e in self._edges.values()):
            self._eos_forwarded = True
            if self.downstream is not None:
                await self._send([Eos()])
            await self._drain_sends()

    def _apply(self, rec: Any) -> List[Any]:
        if self.op_kind in ("map", "union"):
            return [self.fn(rec)] if self.fn else [rec]
        if self.op_kind == "filter":
            return [rec] if self.fn(rec) else []
        if self.op_kind == "flat_map":
            return list(self.fn(rec))
        if self.op_kind == "reduce":
            key, value = rec
            if key in self._state:
                self._state[key] = self.fn(self._state[key], value)
            else:
                self._state[key] = value
            return [(key, self._state[key])]
        if self.op_kind == "sink":
            return [self.fn(rec) if self.fn else rec]
        raise ValueError(f"unknown op kind {self.op_kind!r}")

    async def _flush(self, out: List[Any]) -> None:
        if not out:
            return
        if self.downstream is not None:
            await self._send(out)
        else:
            self._sink_out.extend(out)

    async def _send(self, records: List[Any]) -> None:
        """Windowed pipelined push: up to SEND_WINDOW un-replied batches
        in flight (replies are the receiver's credit grants; actor-call
        ordering keeps the batches in order on the wire)."""
        while len(self._outstanding) >= SEND_WINDOW:
            await self._outstanding.popleft()
        ref = self.downstream.push.remote(records, self._out_channel)
        self._outstanding.append(asyncio.ensure_future(ref.as_future()))

    async def _drain_sends(self) -> None:
        while self._outstanding:
            await self._outstanding.popleft()

    # ---- introspection (driver-side) ----

    async def drain(self) -> None:
        """Wait until everything admitted has been processed."""
        async with self._work:
            await self._work.wait_for(
                lambda: all(e.inflight == 0
                            for e in self._edges.values()))
        await self._drain_sends()

    async def sink_output(self) -> List[Any]:
        return list(self._sink_out)

    async def snapshot(self, barrier_id: int) -> Optional[dict]:
        return self._snapshots.get(barrier_id)

    async def restore(self, snap: dict) -> None:
        """Load a barrier snapshot into this (fresh) operator: reduce
        state and the exactly-once sink prefix (reference: per-node
        rollback from barrier checkpoints, reliability/barrier_helper.h)."""
        self._state = dict(snap.get("state") or {})
        self._sink_out = list(snap.get("sink") or ())

    async def eos_done(self) -> bool:
        return self._eos_forwarded or \
            all(e.eos for e in self._edges.values())

    async def error(self) -> Optional[str]:
        return self._error

    async def stats(self) -> dict:
        return {
            "inflight": {c: e.inflight for c, e in self._edges.items()},
            "peak_inflight": {c: e.peak_inflight
                              for c, e in self._edges.items()},
            "stalled": {c: e.stalled_on for c, e in self._edges.items()},
            "snapshots": sorted(self._snapshots),
        }
