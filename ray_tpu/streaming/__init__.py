"""Streaming: a fluent DataStream API over actor operator pipelines.

Parity target: the reference's streaming library (reference:
streaming/python/ — StreamingContext, DataStream with
map/filter/flat_map/key_by/reduce/sink — over the C++ engine
streaming/src/; see runtime.py for the engine re-design). Usage::

    from ray_tpu import streaming

    ctx = streaming.StreamingContext()
    out = (ctx.from_collection(words)
              .flat_map(str.split)
              .key_by(lambda w: w)
              .reduce(lambda a, b: a + b)
              .execute())
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

import ray_tpu
from ray_tpu.streaming.runtime import Barrier, Eos, StreamOperator

__all__ = ["StreamingContext", "DataStream"]

_BATCH = 64


class _Stage:
    def __init__(self, kind: str, fn: Optional[Callable]):
        self.kind = kind
        self.fn = fn


class DataStream:
    def __init__(self, ctx: "StreamingContext", stages: List[_Stage]):
        self._ctx = ctx
        self._stages = stages

    def _with(self, stage: _Stage) -> "DataStream":
        # preserve KeyedStream-ness across chained transforms
        return type(self)(self._ctx, self._stages + [stage])

    def map(self, fn: Callable) -> "DataStream":
        return self._with(_Stage("map", fn))

    def filter(self, fn: Callable) -> "DataStream":
        return self._with(_Stage("filter", fn))

    def flat_map(self, fn: Callable) -> "DataStream":
        return self._with(_Stage("flat_map", fn))

    def key_by(self, key_fn: Callable) -> "KeyedStream":
        keyed = self._with(_Stage("map", _KeyBy(key_fn)))
        return KeyedStream(keyed._ctx, keyed._stages)

    def sink(self, fn: Optional[Callable] = None) -> "DataStream":
        return self._with(_Stage("sink", fn))

    def execute(self, checkpoint_every: Optional[int] = None
                ) -> List[Any]:
        """Build the operator actors, stream the source through, and
        return the terminal stage's output (the last stage becomes a
        sink when none was declared)."""
        stages = list(self._stages)
        if not stages or stages[-1].kind != "sink":
            stages.append(_Stage("sink", None))
        return self._ctx._run(stages, checkpoint_every)


class KeyedStream(DataStream):
    def reduce(self, fn: Callable) -> DataStream:
        return self._with(_Stage("reduce", fn))


class _KeyBy:
    """Picklable key extractor → (key, record) pairs."""

    def __init__(self, key_fn: Callable):
        self.key_fn = key_fn

    def __call__(self, rec):
        return (self.key_fn(rec), rec)


class StreamingContext:
    def __init__(self, capacity: int = 256):
        self.capacity = capacity
        self._source: Iterable[Any] = ()
        self.operators: List[Any] = []  # live handles of the last run

    def from_collection(self, items: Iterable[Any]) -> DataStream:
        self._source = items
        return DataStream(self, [])

    def _run(self, stages: List[_Stage],
             checkpoint_every: Optional[int]) -> List[Any]:
        op_cls = ray_tpu.remote(StreamOperator)
        ops = [op_cls.remote(s.kind, s.fn, self.capacity)
               for s in stages]
        self.operators = ops
        # wire the chain back-to-front
        for up, down in zip(ops, ops[1:]):
            ray_tpu.get(up.set_downstream.remote(down))

        head = ops[0]
        batch: List[Any] = []
        sent = 0
        barrier_id = 0
        for item in self._source:
            batch.append(item)
            sent += 1
            if len(batch) >= _BATCH:
                ray_tpu.get(head.push.remote(batch))
                batch = []
            if checkpoint_every and sent % checkpoint_every == 0:
                if batch:
                    ray_tpu.get(head.push.remote(batch))
                    batch = []
                barrier_id += 1
                ray_tpu.get(head.push.remote([Barrier(barrier_id)]))
        if batch:
            ray_tpu.get(head.push.remote(batch))
        ray_tpu.get(head.push.remote([Eos()]))

        # wait for EOS to reach the sink, surfacing operator failures
        sink = ops[-1]
        import time

        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            errors = ray_tpu.get([op.error.remote() for op in ops])
            bad = next((e for e in errors if e), None)
            if bad:
                raise RuntimeError(f"stream operator failed:\n{bad}")
            if ray_tpu.get(sink.eos_done.remote()):
                break
            time.sleep(0.02)
        else:
            raise TimeoutError("stream did not reach EOS")
        ray_tpu.get(sink.drain.remote())
        errors = ray_tpu.get([op.error.remote() for op in ops])
        bad = next((e for e in errors if e), None)
        if bad:  # an error that raced the EOS poll
            raise RuntimeError(f"stream operator failed:\n{bad}")
        return ray_tpu.get(sink.sink_output.remote())
