"""Streaming: a fluent DataStream API over actor operator pipelines.

Parity target: the reference's streaming library (reference:
streaming/python/ — StreamingContext, DataStream with
map/filter/flat_map/key_by/reduce/sink — over the C++ engine
streaming/src/; see runtime.py for the engine re-design). Usage::

    from ray_tpu import streaming

    ctx = streaming.StreamingContext()
    out = (ctx.from_collection(words)
              .flat_map(str.split)
              .key_by(lambda w: w)
              .reduce(lambda a, b: a + b)
              .execute())
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu
from ray_tpu import exceptions as exc_mod
from ray_tpu.streaming.runtime import Barrier, Eos, StreamOperator

logger = logging.getLogger(__name__)

__all__ = ["StreamingContext", "DataStream"]

_BATCH = 64


class _Stage:
    def __init__(self, kind: str, fn: Optional[Callable]):
        self.kind = kind
        self.fn = fn


class DataStream:
    def __init__(self, ctx: "StreamingContext", stages: List[_Stage],
                 branches: Optional[List["DataStream"]] = None):
        self._ctx = ctx
        self._stages = stages
        self._source: Optional[Iterable[Any]] = None
        # fan-in: upstream branch pipelines merging into this stream
        # (reference: streaming python DataStream.union)
        self._branches = branches or []

    def _with(self, stage: _Stage) -> "DataStream":
        # preserve KeyedStream-ness across chained transforms
        stream = type(self)(self._ctx, self._stages + [stage],
                            self._branches)
        stream._source = self._source
        return stream

    def union(self, *others: "DataStream") -> "DataStream":
        """Merge this stream with others into one multi-input stage;
        downstream transforms see records from every branch. Barrier
        alignment across the branches is the runtime's job
        (runtime.py _maybe_align)."""
        branches = [self] + list(others)
        return DataStream(self._ctx, [], branches=branches)

    def map(self, fn: Callable) -> "DataStream":
        return self._with(_Stage("map", fn))

    def filter(self, fn: Callable) -> "DataStream":
        return self._with(_Stage("filter", fn))

    def flat_map(self, fn: Callable) -> "DataStream":
        return self._with(_Stage("flat_map", fn))

    def key_by(self, key_fn: Callable) -> "KeyedStream":
        keyed = self._with(_Stage("map", _KeyBy(key_fn)))
        stream = KeyedStream(keyed._ctx, keyed._stages,
                             keyed._branches)
        stream._source = keyed._source
        return stream

    def sink(self, fn: Optional[Callable] = None) -> "DataStream":
        return self._with(_Stage("sink", fn))

    def execute(self, checkpoint_every: Optional[int] = None
                ) -> List[Any]:
        """Build the operator actors, stream the source through, and
        return the terminal stage's output (the last stage becomes a
        sink when none was declared)."""
        return self._ctx._run(self, checkpoint_every)


class KeyedStream(DataStream):
    def reduce(self, fn: Callable) -> DataStream:
        return self._with(_Stage("reduce", fn))


class _KeyBy:
    """Picklable key extractor → (key, record) pairs."""

    def __init__(self, key_fn: Callable):
        self.key_fn = key_fn

    def __call__(self, rec):
        return (self.key_fn(rec), rec)


class StreamingContext:
    def __init__(self, capacity: int = 256):
        self.capacity = capacity
        self._source: Iterable[Any] = ()
        self.operators: List[Any] = []  # live handles of the last run

    def from_collection(self, items: Iterable[Any]) -> DataStream:
        stream = DataStream(self, [])
        stream._source = items
        self._source = items  # kept for backwards compatibility
        return stream

    def _build_chain(self, op_cls, stages: List[_Stage]) -> List[Any]:
        ops = [op_cls.remote(s.kind, s.fn, self.capacity)
               for s in stages]
        for up, down in zip(ops, ops[1:]):
            ray_tpu.get(up.set_downstream.remote(down))
        return ops

    def _build_topology(self, stream: DataStream):
        """Instantiate the operator actors for ``stream``; returns
        (all_ops, heads, sources). Re-invoked wholesale by failure
        recovery — a fresh actor set replaces the broken pipeline."""
        op_cls = ray_tpu.remote(StreamOperator)
        suffix = list(stream._stages)
        if not suffix or suffix[-1].kind != "sink":
            suffix.append(_Stage("sink", None))

        if stream._branches:
            # Fan-in topology: branch chains → union op → suffix chain.
            branches = stream._branches
            for b in branches:
                if b._branches:
                    raise ValueError("nested union is not supported")
            union_op = op_cls.remote(
                "union", None, self.capacity, len(branches))
            suffix_ops = self._build_chain(op_cls, suffix)
            ray_tpu.get(union_op.set_downstream.remote(suffix_ops[0]))
            heads = []
            all_ops = [union_op] + suffix_ops
            for i, b in enumerate(branches):
                if b._stages:
                    chain = self._build_chain(op_cls, b._stages)
                    ray_tpu.get(
                        chain[-1].set_downstream.remote(union_op, i))
                    heads.append(chain[0])
                    all_ops = chain + all_ops
                else:
                    heads.append((union_op, i))
            sources = [b._source if b._source is not None else ()
                       for b in branches]
        else:
            all_ops = self._build_chain(op_cls, suffix)
            heads = [all_ops[0]]
            sources = [stream._source if stream._source is not None
                       else self._source]
        return all_ops, heads, sources

    def _collect_snapshot(self, all_ops, barrier_id: int,
                          timeout: float = 30.0) -> Optional[list]:
        """Poll until EVERY operator has aligned ``barrier_id`` and
        return their snapshots (driver-side copies: an operator's own
        snapshot dies with its actor — holding them here is what makes
        them a recovery point, the role of the reference's checkpoint
        store in reliability/barrier_helper.h)."""
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            snaps = ray_tpu.get(
                [op.snapshot.remote(barrier_id) for op in all_ops])
            if all(s is not None for s in snaps):
                return snaps
            # Driver-thread backoff between actor-state polls: each iteration
            # submits .remote() via the sync API, which must stay OFF the IO loop
            # (core._run rejects loop-thread callers) — never runs on the loop.
            # raylint: disable=async-blocking — sync-API driver-thread poll (see above)
            time.sleep(0.02)
        return None

    def _run(self, stream: DataStream,
             checkpoint_every: Optional[int]) -> List[Any]:
        """Drive the pipeline; with ``checkpoint_every`` set, operator
        failure mid-stream triggers recovery: rebuild the actor
        pipeline, restore every operator from the last fully-aligned
        barrier snapshot, replay the sources from that barrier's
        offsets — output is exactly-once (reference:
        streaming/src/reliability/barrier_helper.h rollback)."""
        if checkpoint_every:
            # recovery replays sources from saved offsets: a one-shot
            # iterator cannot be replayed (silent data loss otherwise)
            srcs = [b._source for b in stream._branches] \
                if stream._branches else [stream._source or self._source]
            for s in srcs:
                if s is not None and iter(s) is s:
                    raise ValueError(
                        "checkpointed streams need RE-ITERABLE sources "
                        "(list/tuple/an __iter__ class), not a one-shot "
                        "generator — recovery replays from offsets")
        recovery: dict = {}  # {"barrier", "snaps", "offsets"}
        attempts = 0
        while True:
            try:
                return self._drive(stream, checkpoint_every, recovery)
            except (exc_mod.ActorDiedError, exc_mod.WorkerCrashedError,
                    exc_mod.RaySystemError, ConnectionError):
                attempts += 1
                if not checkpoint_every or "snaps" not in recovery \
                        or attempts > 3:
                    raise
                # the broken pipeline's survivors must not linger
                for op in self.operators:
                    try:
                        ray_tpu.kill(op)
                    except Exception:  # noqa: BLE001 — already dead
                        pass
                logger.warning(
                    "stream operator died; recovering from barrier %s "
                    "(attempt %d)", recovery.get("barrier"), attempts)

    def _drive(self, stream: DataStream,
               checkpoint_every: Optional[int],
               recovery: dict) -> List[Any]:
        all_ops, heads, sources = self._build_topology(stream)
        self.operators = all_ops
        sink = all_ops[-1]

        def _push(head, payload):
            if isinstance(head, tuple):  # (op, channel) direct fan-in
                ray_tpu.get(head[0].push.remote(payload, head[1]))
            else:
                ray_tpu.get(head.push.remote(payload))

        # Resume point: restore operator state, skip replayed records.
        offsets = [0] * len(sources)
        sent = 0
        barrier_id = 0
        if recovery:
            ray_tpu.get([op.restore.remote(snap) for op, snap in
                         zip(all_ops, recovery["snaps"])])
            offsets = list(recovery["offsets"])
            sent = sum(offsets)
            barrier_id = recovery["barrier"]

        # Drive every source round-robin so fan-in edges genuinely
        # interleave; barriers are injected into EVERY head at the same
        # logical point (the runtime aligns them downstream).
        iters = []
        for i, s in enumerate(sources):
            it = iter(s)
            for _ in range(offsets[i]):  # replay: skip consumed prefix
                next(it)
            iters.append(it)
        counts = list(offsets)
        batches: List[List[Any]] = [[] for _ in sources]
        live = set(range(len(sources)))
        pending_barrier: Optional[int] = None

        def _inject_barrier():
            nonlocal barrier_id, pending_barrier
            # collect the PREVIOUS barrier first: its alignment is done
            # or imminent, and holding its snapshots driver-side turns
            # it into the recovery point
            if pending_barrier is not None and checkpoint_every:
                snaps = self._collect_snapshot(all_ops, pending_barrier)
                if snaps is not None:
                    recovery.update(barrier=pending_barrier, snaps=snaps,
                                    offsets=recovery.pop("_offsets_at",
                                                         list(counts)))
            barrier_id += 1
            for j in range(len(sources)):
                if batches[j]:
                    _push(heads[j], batches[j])
                    batches[j] = []
                _push(heads[j], [Barrier(barrier_id)])
            pending_barrier = barrier_id
            recovery["_offsets_at"] = list(counts)

        while live:
            for i in list(live):
                try:
                    batches[i].append(next(iters[i]))
                except StopIteration:
                    if batches[i]:
                        _push(heads[i], batches[i])
                        batches[i] = []
                    live.discard(i)
                    continue
                counts[i] += 1
                sent += 1
                if len(batches[i]) >= _BATCH:
                    _push(heads[i], batches[i])
                    batches[i] = []
                # per-record cadence: a barrier lands exactly every
                # checkpoint_every records across all sources
                if checkpoint_every and sent % checkpoint_every == 0:
                    _inject_barrier()
        for i in range(len(sources)):
            _push(heads[i], [Eos()])

        # wait for EOS to reach the sink, surfacing operator failures
        import time

        def _raise_op_error(msg: str):
            # a mid-pipeline neighbor observing a dead actor reports it
            # as "<ExcType>: ..." (runtime.py _consume_loop) — map the
            # death types back to the recoverable class so the retry
            # loop can rebuild instead of failing the job. Matching the
            # TYPE PREFIX only: a user exception merely mentioning
            # 'connection' in its text must stay non-recoverable.
            if msg.startswith(("ActorDiedError", "WorkerCrashedError",
                               "ConnectionError", "ConnectionResetError",
                               "BrokenPipeError")):
                raise exc_mod.ActorDiedError(msg)
            raise RuntimeError(f"stream operator failed:\n{msg}")

        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            errors = ray_tpu.get([op.error.remote() for op in all_ops])
            bad = next((e for e in errors if e), None)
            if bad:
                _raise_op_error(bad)
            if ray_tpu.get(sink.eos_done.remote()):
                break
            # raylint: disable=async-blocking — same sync-API driver-thread poll as _collect_snapshot
            time.sleep(0.02)
        else:
            raise TimeoutError("stream did not reach EOS")
        ray_tpu.get(sink.drain.remote())
        errors = ray_tpu.get([op.error.remote() for op in all_ops])
        bad = next((e for e in errors if e), None)
        if bad:  # an error that raced the EOS poll
            _raise_op_error(bad)
        return ray_tpu.get(sink.sink_output.remote())
