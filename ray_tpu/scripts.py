"""The ``ray_tpu`` command line: cluster lifecycle + introspection.

Parity target: reference python/ray/scripts/scripts.py — ``ray start``
(:485), ``stop`` (:800), ``status`` (:1521), ``memory`` (:1497),
``timeline`` (:1433), ``microbenchmark`` (:1421).

Usage::

    python -m ray_tpu start --head [--num-cpus N]
    python -m ray_tpu start --address tcp://HOST:PORT
    python -m ray_tpu status | memory | timeline | microbenchmark
    python -m ray_tpu stop
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

_BASE = os.environ.get("RAY_TPU_TMPDIR", "/tmp/ray_tpu")
_CURRENT = os.path.join(_BASE, "ray_current_cluster")
_PIDS = os.path.join(_BASE, "cli_node_pids")


def _read_current_address() -> str:
    try:
        with open(_CURRENT) as f:
            return f.read().strip()
    except FileNotFoundError:
        return ""


def _resolve_address(args) -> str:
    addr = getattr(args, "address", "") or _read_current_address()
    if not addr:
        sys.exit("no running cluster found: pass --address or run "
                 "`python -m ray_tpu start --head` first")
    return addr


def _connect(args):
    import ray_tpu

    ray_tpu.init(address=_resolve_address(args), log_to_driver=False)
    return ray_tpu


def cmd_start(args) -> None:
    os.makedirs(_BASE, exist_ok=True)
    addr_file = os.path.join(
        _BASE, f"cli_addr_{os.getpid()}_{int(time.time())}")
    cmd = [sys.executable, "-m", "ray_tpu._private.node",
           "--num-cpus", str(args.num_cpus),
           "--address-file", addr_file]
    if args.head:
        cmd += ["--head"]
        if args.port:
            cmd += ["--gcs-listen", f"tcp://127.0.0.1:{args.port}"]
    else:
        if not args.address:
            sys.exit("worker nodes need --address of the head GCS")
        cmd += ["--gcs-address", args.address]
    if args.resources:
        cmd += ["--resources", args.resources]

    proc = subprocess.Popen(cmd, start_new_session=True,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and not os.path.exists(addr_file):
        if proc.poll() is not None:
            sys.exit(f"node process exited early (rc={proc.returncode})")
        # raylint: disable=async-blocking — CLI process waiting on a child daemon's address file; no loop here
        time.sleep(0.1)
    if not os.path.exists(addr_file):
        proc.terminate()
        sys.exit("timed out waiting for the node to come up")
    with open(addr_file) as f:
        gcs_address, raylet_address, session_dir = \
            f.read().strip().splitlines()
    os.unlink(addr_file)

    with open(_PIDS, "a") as f:
        f.write(f"{proc.pid}\n")
    if args.head:
        with open(_CURRENT, "w") as f:
            f.write(gcs_address)
        print(f"started head node (pid {proc.pid})")
        print(f"  GCS address: {gcs_address}")
        print("connect with:")
        print(f"  ray_tpu.init(address={gcs_address!r})")
        print("or from this shell:")
        print(f"  python -m ray_tpu status")
    else:
        print(f"started worker node (pid {proc.pid}) -> {args.address}")
    print(f"  session dir: {session_dir}")
    if args.block:
        try:
            proc.wait()
        except KeyboardInterrupt:
            proc.terminate()


def cmd_stop(args) -> None:
    try:
        with open(_PIDS) as f:
            pids = [int(line) for line in f.read().split()]
    except FileNotFoundError:
        print("no CLI-started nodes found")
        return
    stopped = 0
    for pid in pids:
        try:
            os.killpg(os.getpgid(pid), signal.SIGTERM)
            stopped += 1
        except (ProcessLookupError, PermissionError):
            pass
    for path in (_PIDS, _CURRENT):
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
    print(f"stopped {stopped} node process(es)")


def cmd_status(args) -> None:
    ray_tpu = _connect(args)
    from ray_tpu import state

    print(state.status())
    addr = state.metrics_address()
    if addr:
        print(f"Prometheus metrics: http://{addr}/metrics")
    ray_tpu.shutdown()


def cmd_memory(args) -> None:
    ray_tpu = _connect(args)
    from ray_tpu import state

    print(state.memory_summary())
    ray_tpu.shutdown()


def cmd_stack(args) -> None:
    """All-worker stack dump per node (reference: `ray stack`,
    scripts.py:1393 — py-spy over local worker pids; here every worker
    self-reports all its threads over RPC, so it works cluster-wide)."""
    ray_tpu = _connect(args)
    from ray_tpu._private import rpc as rpc_mod

    nodes = [n for n in ray_tpu.nodes() if n.get("Alive")]

    async def _dump(address):
        conn = await rpc_mod.connect(address, peer_name="stack-cli")
        try:
            reply, _ = await conn.call("DumpWorkerStacks", {}, timeout=15.0)
            return reply
        finally:
            await conn.close()

    core = ray_tpu.worker.global_worker.core
    for n in nodes:
        print(f"===== node {n['NodeID'][:12]} {n['Address']} =====")
        try:
            reply = core._run(_dump(n["Address"]))
        except Exception as e:  # noqa: BLE001
            print(f"  unreachable: {e}")
            continue
        for w in reply.get("workers", []):
            print(f"--- worker pid {w.get('pid')} "
                  f"{w.get('worker_id', '')[:12]} ---")
            print(w.get("stacks") or w.get("error", ""))
    ray_tpu.shutdown()


def cmd_logs(args) -> None:
    """List or tail a node's session log files over the raylet RPC."""
    ray_tpu = _connect(args)
    from ray_tpu._private import rpc as rpc_mod

    nodes = [n for n in ray_tpu.nodes() if n.get("Alive")]
    node = nodes[0] if nodes else None
    for n in nodes:
        if args.node and n["NodeID"].startswith(args.node):
            node = n
            break
    if node is None:
        print("no alive nodes")
        ray_tpu.shutdown()
        return

    async def _logs(address):
        conn = await rpc_mod.connect(address, peer_name="logs-cli")
        try:
            reply, _ = await conn.call(
                "GetLogs", {"name": args.name, "tail": args.tail},
                timeout=10.0)
            return reply
        finally:
            await conn.close()

    core = ray_tpu.worker.global_worker.core
    reply = core._run(_logs(node["Address"]))
    if "files" in reply and not args.name:
        for f in reply["files"]:
            print(f"{f.get('size', 0):>10}  {f['name']}")
    elif "lines" in reply:
        print(f"==> {reply['name']} <==")
        for line in reply["lines"]:
            print(line)
    else:
        print(reply.get("error", reply))
    ray_tpu.shutdown()


def cmd_timeline(args) -> None:
    ray_tpu = _connect(args)
    events = ray_tpu.timeline()
    out = args.output or os.path.join(
        _BASE, f"timeline_{int(time.time())}.json")
    with open(out, "w") as f:
        json.dump(events, f)
    print(f"wrote {len(events)} events to {out} "
          f"(open in chrome://tracing or Perfetto)")
    ray_tpu.shutdown()


def _microbenchmark_main() -> None:
    # In-process cluster, same harness shape as the reference's
    # `ray microbenchmark` (reference: _private/ray_perf.py).
    import ray_tpu

    ray_tpu.init(num_cpus=max(1, os.cpu_count() or 1))

    @ray_tpu.remote
    def small():
        return b"ok"

    @ray_tpu.remote
    class A:
        def ping(self):
            return b"ok"

    def timeit(name, fn, n):
        fn(min(n, 100))  # warmup
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            fn(n)
            best = max(best, n / (time.perf_counter() - t0))
        print(f"{name}: {best:,.1f}/s")

    timeit("single client tasks async",
           lambda n: ray_tpu.get([small.remote() for _ in range(n)]),
           5000)
    a = A.remote()
    timeit("1:1 actor calls async",
           lambda n: ray_tpu.get([a.ping.remote() for _ in range(n)]),
           5000)
    timeit("single client put",
           lambda n: [ray_tpu.put(b"x") for _ in range(n)] and None,
           5000)
    ray_tpu.shutdown()


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        prog="ray_tpu", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("start", help="start a head or worker node")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address", default="",
                   help="head GCS address (worker nodes)")
    p.add_argument("--port", type=int, default=0,
                   help="head: fixed GCS port")
    p.add_argument("--num-cpus", type=int,
                   default=max(1, os.cpu_count() or 1))
    p.add_argument("--resources", default="",
                   help="comma list k=v of custom resources")
    p.add_argument("--block", action="store_true",
                   help="stay attached to the node process")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop", help="stop CLI-started nodes")
    p.set_defaults(fn=cmd_stop)

    for name, fn in [("status", cmd_status), ("memory", cmd_memory),
                     ("timeline", cmd_timeline), ("stack", cmd_stack),
                     ("logs", cmd_logs)]:
        p = sub.add_parser(name)
        p.add_argument("--address", default="")
        if name == "timeline":
            p.add_argument("--output", default="")
        if name == "logs":
            p.add_argument("--node", default="",
                           help="node id hex prefix (default: first node)")
            p.add_argument("--name", default="",
                           help="log file substring; empty lists files")
            p.add_argument("--tail", type=int, default=200)
        p.set_defaults(fn=fn)

    p = sub.add_parser("microbenchmark",
                       help="task/actor/put throughput on this machine")
    p.set_defaults(fn=lambda a: _microbenchmark_main())

    args = parser.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
