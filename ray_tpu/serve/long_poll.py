"""Long-poll change notification between the controller and handles.

Parity target: the reference's LongPollHost/LongPollClient
(reference: python/ray/serve/long_poll.py:38,135). The host side lives
inside the ServeController (an async actor): listeners block on an
``asyncio.Condition`` until a watched key's version advances, so config
pushes reach every router in one actor-call round trip instead of each
router polling. The client side is a daemon thread issuing back-to-back
blocking listens (the core-worker API is thread-safe: calls hop onto
the IO loop via run_coroutine_threadsafe).
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Callable, Dict, Tuple

LISTEN_TIMEOUT_S = 30.0  # heartbeat: return empty so the client re-arms


class LongPollHost:
    """Versioned key/value store with blocking listeners (host side)."""

    def __init__(self):
        self._values: Dict[str, Any] = {}
        self._versions: Dict[str, int] = {}
        self._cond = asyncio.Condition()

    async def notify_changed(self, key: str, value: Any) -> None:
        async with self._cond:
            self._values[key] = value
            self._versions[key] = self._versions.get(key, 0) + 1
            self._cond.notify_all()

    async def listen_for_change(
            self, known: Dict[str, int]) -> Dict[str, Tuple[int, Any]]:
        """Block until some watched key's version != the known version.

        Returns {key: (version, value)} for every changed key; {} on
        timeout (client re-issues the listen — keeps slow clients from
        pinning the actor forever).
        """
        def changed():
            return {
                k: (self._versions[k], self._values[k])
                for k, v in known.items()
                if self._versions.get(k, 0) != v and k in self._values
            }

        async with self._cond:
            out = changed()
            if out:
                return out
            try:
                await asyncio.wait_for(
                    self._cond.wait_for(lambda: bool(changed())),
                    timeout=LISTEN_TIMEOUT_S)
            except asyncio.TimeoutError:
                return {}
            return changed()


class LongPollClient:
    """Daemon-thread listener pushing updates into callbacks."""

    def __init__(self, host_actor,
                 callbacks: Dict[str, Callable[[Any], None]]):
        self._host = host_actor
        self._callbacks = callbacks
        self._known = {k: 0 for k in callbacks}
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="serve-long-poll", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

    def add_callback(self, key: str,
                     callback: Callable[[Any], None]) -> None:
        """Watch another key on the live listener (the HTTP proxy learns
        deployments dynamically from the route table). Safe from any
        thread: dict item assignment is atomic and the loop copies
        ``_known`` per listen."""
        self._callbacks[key] = callback
        self._known.setdefault(key, 0)

    def _run(self) -> None:
        import ray_tpu

        failures = 0
        while not self._stopped.is_set():
            try:
                updates = ray_tpu.get(
                    self._host.listen_for_change.remote(dict(self._known)),
                    timeout=LISTEN_TIMEOUT_S * 2)
                failures = 0
            except Exception:  # noqa: BLE001 — controller died / shutdown
                failures += 1
                if failures >= 20 or self._stopped.wait(0.5):
                    return  # controller is gone; stop burning a thread
                continue
            for key, (version, value) in (updates or {}).items():
                self._known[key] = version
                try:
                    self._callbacks[key](value)
                except Exception:  # noqa: BLE001 — never kill the loop
                    pass
