"""Continuous batching for KV-cached decode: one in-flight batch,
slot admission at step boundaries.

The ``@serve.batch`` decorator forms batches with a static window —
requests wait up to ``batch_wait_timeout_s`` for peers, the batch runs
to completion, and a request arriving one tick after the flush waits a
FULL generation before its tokens start. Under ragged arrivals that
leaves most of the model's decode ceiling on the floor (the scheduling
gap PAPERS.md [1] measures: batch-formation policy, not kernel speed,
dominates accelerator goodput).

:class:`DecodeScheduler` replaces the window with ONE long-lived decode
batch over a per-slot KV cache (``models/decode.py``
``init_slot_cache`` / ``slot_prefill`` / ``slot_decode_step``):

* the loop runs one batched decode step per iteration for every
  ACTIVE slot;
* a newly arrived request is admitted into any open slot at the next
  step boundary — its prompt prefills into that cache row while the
  other rows' positions are untouched, and its first step joins the
  very next batch;
* a finished sequence (eos / max_tokens) frees its slot IMMEDIATELY
  and the head of the queue takes it — the batch never drains to empty
  just to let a waiter in;
* past ``max_queue_depth`` waiting requests, ``submit`` sheds with the
  typed :class:`~ray_tpu.exceptions.ServeOverloadedError` (the serving
  analog of the lease plane's ``retry_later``) instead of queueing
  work the decode loop can never catch up on.

The scheduler is ENGINE-AGNOSTIC: anything with ``slots``,
``prefill(slot, prompt) -> first_token`` and
``step({slot: last_token}) -> {slot: next_token}`` drives it, so the
admission policy is unit-testable without jax (tests/
test_decode_scheduler.py uses a fake engine); :class:`JaxSlotEngine`
adapts the real per-slot cache. Engine calls run in the default
executor — a jitted decode step must not block the replica's asyncio
loop, which keeps accepting/queueing requests mid-step.
"""

from __future__ import annotations

import asyncio
import logging
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu._private import rpc
from ray_tpu.exceptions import ServeOverloadedError

logger = logging.getLogger(__name__)


@dataclass
class _Request:
    prompt: Any
    max_tokens: int
    eos_token: Optional[int]
    future: asyncio.Future
    tokens: List[int] = field(default_factory=list)
    joined_mid_batch: bool = False


class DecodeScheduler:
    """One in-flight decode batch; admission at step boundaries.

    ``submit`` is awaited per request and resolves with the generated
    token list. The background loop starts lazily on the first submit
    and parks (zero cycles) whenever queue and batch are both empty.
    """

    def __init__(self, engine, *, max_queue_depth: int = 64,
                 retry_after_s: float = 1.0):
        if int(engine.slots) <= 0:
            raise ValueError("engine must expose at least one slot")
        self._engine = engine
        self._free: List[int] = list(range(engine.slots))
        self._queue: deque[_Request] = deque()
        self._active: Dict[int, _Request] = {}
        self._max_queue_depth = int(max_queue_depth)
        self._retry_after_s = float(retry_after_s)
        self._wakeup = asyncio.Event()
        self._loop_task: Optional[asyncio.Task] = None
        self._closed = False
        # counters surfaced by stats() (and the replica's stats() ->
        # autoscaler/admission view)
        self.steps = 0
        self.slot_steps = 0          # sum of batch occupancy per step
        self.completed = 0
        self.shed = 0
        self.admitted = 0
        self.admitted_mid_batch = 0
        self.tokens_generated = 0

    # ------------------------------------------------------------ public

    async def submit(self, prompt, *, max_tokens: int,
                     eos_token: Optional[int] = None) -> List[int]:
        """Queue one prompt; resolves with its generated tokens.

        Sheds (typed, never queues) once ``max_queue_depth`` requests
        are already waiting for a slot — the per-replica half of the
        SLO contract; the proxy's admission controller is the cluster
        half."""
        if self._closed:
            raise ServeOverloadedError("decode scheduler is closed",
                                       retry_after_s=self._retry_after_s)
        if len(self._queue) >= self._max_queue_depth:
            self.shed += 1
            raise ServeOverloadedError(
                f"decode queue full ({len(self._queue)} waiting, cap "
                f"{self._max_queue_depth})",
                retry_after_s=self._retry_after_s)
        req = _Request(prompt, int(max_tokens), eos_token,
                       asyncio.get_running_loop().create_future())
        self._queue.append(req)
        self._wakeup.set()
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = rpc.spawn_logged(self._run(),
                                               "serve-decode-loop")
        return await req.future

    def queue_depth(self) -> int:
        return len(self._queue)

    def stats(self) -> dict:
        return {
            "queue_depth": len(self._queue),
            "active_slots": len(self._active),
            "free_slots": len(self._free),
            "steps": self.steps,
            "slot_steps": self.slot_steps,
            "mean_occupancy": (self.slot_steps / self.steps
                               if self.steps else 0.0),
            "completed": self.completed,
            "shed": self.shed,
            "admitted": self.admitted,
            "admitted_mid_batch": self.admitted_mid_batch,
            "tokens_generated": self.tokens_generated,
        }

    async def aclose(self) -> None:
        """Stop the loop; fail queued and in-flight requests typed."""
        self._closed = True
        task, self._loop_task = self._loop_task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        err = ServeOverloadedError("decode scheduler closed",
                                   retry_after_s=self._retry_after_s)
        for req in list(self._queue) + list(self._active.values()):
            if not req.future.done():
                req.future.set_exception(err)
        self._queue.clear()
        self._active.clear()
        self._free = list(range(self._engine.slots))

    # ------------------------------------------------------------- loop

    async def _prefill(self, slot: int, req: _Request) -> None:
        loop = asyncio.get_running_loop()
        if asyncio.iscoroutinefunction(self._engine.prefill):
            first = await self._engine.prefill(slot, req.prompt)
        else:
            first = await loop.run_in_executor(
                None, self._engine.prefill, slot, req.prompt)
        req.tokens.append(int(first))
        self.tokens_generated += 1

    def _finish(self, slot: int, req: _Request) -> None:
        del self._active[slot]
        self._free.append(slot)
        self.completed += 1
        if not req.future.done():
            req.future.set_result(req.tokens)

    def _done(self, req: _Request) -> bool:
        return (len(req.tokens) >= req.max_tokens or
                (req.eos_token is not None and req.tokens and
                 req.tokens[-1] == req.eos_token))

    async def _admit(self) -> None:
        """Fill open slots from the queue head (step boundary only)."""
        while self._free and self._queue:
            req = self._queue.popleft()
            if req.future.done():   # caller gave up while queued
                continue
            slot = self._free.pop()
            req.joined_mid_batch = bool(self._active)
            self.admitted += 1
            if req.joined_mid_batch:
                self.admitted_mid_batch += 1
            try:
                await self._prefill(slot, req)
            except Exception as e:  # noqa: BLE001 — one bad prompt
                # must not kill the batch: fail ITS future, free the
                # slot, keep decoding everyone else
                self._free.append(slot)
                if not req.future.done():
                    req.future.set_exception(e)
                continue
            if self._done(req):
                self._free.append(slot)
                self.completed += 1
                if not req.future.done():
                    req.future.set_result(req.tokens)
            else:
                self._active[slot] = req

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while not self._closed:
            await self._admit()
            if not self._active:
                self._wakeup.clear()
                if not self._queue:
                    await self._wakeup.wait()
                continue
            tokens = {slot: req.tokens[-1]
                      for slot, req in self._active.items()}
            try:
                if asyncio.iscoroutinefunction(self._engine.step):
                    out = await self._engine.step(tokens)
                else:
                    out = await loop.run_in_executor(
                        None, self._engine.step, tokens)
            except Exception as e:  # noqa: BLE001 — a failed device
                # step fails the IN-FLIGHT requests typed; the loop and
                # the queue survive (shed at the door, never collapse)
                logger.error("decode step failed: %r", e, exc_info=e)
                for slot, req in list(self._active.items()):
                    del self._active[slot]
                    self._free.append(slot)
                    if not req.future.done():
                        req.future.set_exception(e)
                continue
            self.steps += 1
            self.slot_steps += len(tokens)
            for slot, tok in out.items():
                req = self._active.get(slot)
                if req is None:
                    continue
                req.tokens.append(int(tok))
                self.tokens_generated += 1
                if self._done(req):
                    self._finish(slot, req)


class JaxSlotEngine:
    """Adapts the per-slot KV cache (models/decode.py) to the
    scheduler's engine protocol. Greedy decoding; prompts are int
    token-id sequences. One compiled prefill program per distinct
    prompt length, one compiled step program total."""

    def __init__(self, params, cfg, *, slots: int, max_len: int):
        import jax.numpy as jnp  # deferred: scheduler users without a
        from ray_tpu.models import decode as decode_mod  # model never pay

        self._jnp = jnp
        self._decode = decode_mod
        self._params = params
        self._cfg = cfg
        self.slots = int(slots)
        self.max_len = int(max_len)
        self._cache = decode_mod.init_slot_cache(cfg, slots, max_len)

    def prefill(self, slot: int, prompt) -> int:
        jnp = self._jnp
        tokens = jnp.asarray(prompt, jnp.int32)[None, :]
        if tokens.shape[1] >= self.max_len:
            raise ValueError(
                f"prompt ({tokens.shape[1]}) >= slot max_len "
                f"({self.max_len})")
        logits, self._cache = self._decode.slot_prefill(
            self._params, tokens, self._cache, jnp.int32(slot),
            self._cfg)
        return int(jnp.argmax(logits[0]))

    def step(self, tokens: Dict[int, int]) -> Dict[int, int]:
        jnp = self._jnp
        tok = [0] * self.slots
        act = [False] * self.slots
        for slot, t in tokens.items():
            # a slot at capacity would silently clamp its cache write;
            # refuse loudly (the scheduler's max_tokens bound plus the
            # engine's prompt-length check make this unreachable)
            if int(self._cache["pos"][slot]) >= self.max_len:
                raise ValueError(f"slot {slot} KV cache full")
            tok[slot], act[slot] = int(t), True
        logits, self._cache = self._decode.slot_decode_step(
            self._params, self._cache, jnp.asarray(tok, jnp.int32),
            jnp.asarray(act), self._cfg)
        nxt = jnp.argmax(logits, axis=-1)
        return {slot: int(nxt[slot]) for slot in tokens}
