"""ServeController: the cluster-singleton control plane for serving.

Parity target: the reference's ServeController + BackendState
(reference: python/ray/serve/controller.py:38, backend_state.py). One
named async actor owns all deployment goal-state, reconciles replica
actors toward it (scale up/down, rolling version updates with drain),
and pushes membership snapshots to routers through the LongPollHost.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Dict, List, Optional

from ray_tpu.serve.long_poll import LongPollHost
from ray_tpu.serve.replica import Replica

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"
SNAPSHOT_KEY = "replicas:{name}"  # long-poll key per deployment
ROUTES_KEY = "routes"             # long-poll key for the HTTP route table
REPLICA_STARTUP_TIMEOUT_S = 60.0
# Cadence of the replica health loop (a crashed replica is detected,
# dropped from router membership, and replaced within ~one period).
HEALTH_CHECK_PERIOD_S = 0.5
# GCS internal-KV key the controller publishes its deployment/replica
# view under, so the dashboard's /api/serve renders without an RPC to
# this actor (the GCS process has no worker to call actors with).
SERVE_STATE_KEY = b"serve:state"


async def _as_coro(ref):
    """asyncio.wait_for needs a coroutine/task, not a bare awaitable."""
    return await ref


class ServeController:
    """Async actor. All methods run interleaved on one event loop, so
    state mutations need no locks (single-loop discipline, the same
    posture as the rest of the runtime)."""

    def __init__(self):
        self._host = LongPollHost()
        # goal state per deployment
        self._configs: Dict[str, dict] = {}
        # live replicas: name -> [{"id": str, "handle": ActorHandle,
        #                          "version": str}]
        self._replicas: Dict[str, List[dict]] = {}
        self._next_replica_id = 0
        self._reconciling: Dict[str, asyncio.Lock] = {}
        # autoscaling: per-deployment consecutive-decision counters
        # (reference: autoscaling_policy.py BasicAutoscalingPolicy)
        self._scale_counters: Dict[str, int] = {}
        self._autoscale_task: Optional[asyncio.Task] = None
        self._health_task: Optional[asyncio.Task] = None

    # ---- long-poll host passthrough (routers call this) ----

    async def listen_for_change(self, known: Dict[str, int]):
        return await self._host.listen_for_change(known)

    # ---- deployment API (called by serve.api) ----

    async def deploy(self, name: str, callable_def: Any,
                     init_args: tuple, init_kwargs: dict,
                     num_replicas: int = 1,
                     max_concurrent_queries: int = 100,
                     version: Optional[str] = None,
                     user_config: Any = None,
                     ray_actor_options: Optional[dict] = None,
                     route_prefix: Optional[str] = "__default__",
                     autoscaling_config: Optional[dict] = None) -> None:
        """Create or update a deployment and reconcile to the new goal."""
        version = version or "1"
        if route_prefix == "__default__":
            route_prefix = f"/{name}"
        if route_prefix:
            for other, cfg in self._configs.items():
                if other != name and cfg.get("route_prefix") == route_prefix:
                    raise ValueError(
                        f"route_prefix {route_prefix!r} is already used "
                        f"by deployment {other!r}")
        if callable_def is None:
            # Config-only redeploy (scale / reconfigure via
            # serve.get_deployment): keep the stored callable.
            existing = self._configs.get(name)
            if existing is None:
                raise ValueError(
                    f"deployment {name!r} has no stored callable")
            callable_def = existing["callable_def"]
        self._configs[name] = {
            "name": name,
            "callable_def": callable_def,
            "init_args": tuple(init_args or ()),
            "init_kwargs": dict(init_kwargs or {}),
            "num_replicas": int(num_replicas),
            "max_concurrent_queries": int(max_concurrent_queries),
            "version": version,
            "user_config": user_config,
            "ray_actor_options": dict(ray_actor_options or {}),
            "route_prefix": route_prefix,
            "autoscaling_config": dict(autoscaling_config)
            if autoscaling_config else None,
        }
        self._scale_counters.pop(name, None)  # fresh hysteresis per deploy
        if autoscaling_config:
            cfg = self._configs[name]
            lo, hi = self._bounds(autoscaling_config)
            cfg["num_replicas"] = max(lo, min(cfg["num_replicas"], hi))
            if self._autoscale_task is None or self._autoscale_task.done():
                self._autoscale_task = asyncio.get_running_loop().\
                    create_task(self._autoscale_loop())
        if self._health_task is None or self._health_task.done():
            self._health_task = asyncio.get_running_loop().create_task(
                self._health_loop())
        # Reconcile BEFORE announcing the route: when the proxy learns a
        # new route and bootstraps its replica snapshot, replicas must
        # already be serving (reference ordering: backend_state goal
        # completion precedes endpoint-table publication).
        await self._reconcile(name)
        await self._notify_routes()

    async def delete_deployment(self, name: str) -> None:
        self._configs.pop(name, None)
        self._scale_counters.pop(name, None)
        await self._notify_routes()
        await self._reconcile(name)

    async def get_routes(self) -> Dict[str, str]:
        """HTTP route table: {route_prefix: deployment_name} (reference:
        python/ray/serve/api.py route management + http_proxy routing)."""
        return {
            cfg["route_prefix"]: name
            for name, cfg in self._configs.items()
            if cfg.get("route_prefix")
        }

    async def _notify_routes(self) -> None:
        await self._host.notify_changed(ROUTES_KEY, await self.get_routes())
        self._publish_state()

    def _publish_state(self) -> None:
        """Mirror the deployment/replica view into the GCS internal KV
        (fire-and-forget). The dashboard's /api/serve reads it there and
        joins it with the serve metrics — same pattern as tracing's span
        export (util/tracing.py)."""
        import json

        try:
            import ray_tpu.worker as worker_mod
            core = worker_mod.global_worker.core
        except Exception:  # noqa: BLE001 — unit harness without a
            return         # worker: nothing to publish to
        state = {
            "routes": {cfg["route_prefix"]: name
                       for name, cfg in self._configs.items()
                       if cfg.get("route_prefix")},
            "deployments": {
                name: {
                    "num_replicas": cfg["num_replicas"],
                    "max_concurrent_queries":
                        cfg["max_concurrent_queries"],
                    "version": cfg["version"],
                    "route_prefix": cfg.get("route_prefix"),
                    "autoscaling": bool(cfg.get("autoscaling_config")),
                    "replicas": [r["id"] for r in
                                 self._replicas.get(name, [])],
                } for name, cfg in self._configs.items()
            },
        }
        try:
            core.kv_put_nowait(SERVE_STATE_KEY,
                               json.dumps(state).encode())
        except Exception:  # noqa: BLE001 — telemetry export must never
            pass           # fail a deploy/reconcile

    async def get_deployment_info(self, name: str) -> Optional[dict]:
        cfg = self._configs.get(name)
        if cfg is None:
            return None
        return {k: v for k, v in cfg.items() if k != "callable_def"}

    async def list_deployments(self) -> List[str]:
        return sorted(self._configs)

    async def get_replica_snapshot(self, name: str) -> dict:
        """One-shot snapshot (handles bootstrap before long-poll arms)."""
        return self._snapshot(name)

    async def shutdown(self) -> None:
        for name in list(self._configs):
            self._configs.pop(name, None)
            await self._reconcile(name)
        await self._notify_routes()

    # ---- reconciliation ----

    def _snapshot(self, name: str) -> dict:
        cfg = self._configs.get(name)
        return {
            "max_concurrent_queries":
                cfg["max_concurrent_queries"] if cfg else 1,
            "replicas": [
                {"id": r["id"], "handle": r["handle"]}
                for r in self._replicas.get(name, [])
            ],
        }

    async def _notify(self, name: str) -> None:
        await self._host.notify_changed(
            SNAPSHOT_KEY.format(name=name), self._snapshot(name))
        self._publish_state()

    async def _reconcile(self, name: str) -> None:
        # Serialize reconciles per deployment; concurrent deploy() calls
        # otherwise interleave replica starts and double-count.
        lock = self._reconciling.setdefault(name, asyncio.Lock())
        async with lock:
            await self._reconcile_locked(name)

    async def _reconcile_locked(self, name: str) -> None:
        import ray_tpu

        cfg = self._configs.get(name)
        live = self._replicas.setdefault(name, [])

        if cfg is None:  # deleted: drain everything, then kill
            victims = list(live)
            self._replicas[name] = []
            await self._notify(name)  # routers stop sending first
            await self._drain_and_kill(victims)
            self._replicas.pop(name, None)
            return

        version = cfg["version"]
        current = [r for r in live if r["version"] == version]
        outdated = [r for r in live if r["version"] != version]

        # Scale up to goal with new-version replicas.
        want = cfg["num_replicas"]
        starting = []
        for _ in range(want - len(current)):
            self._next_replica_id += 1
            rid = f"{name}#{version}#{self._next_replica_id}"
            opts = dict(cfg["ray_actor_options"])
            opts.setdefault("max_concurrency",
                            max(cfg["max_concurrent_queries"], 100))
            handle = ray_tpu.remote(Replica).options(**opts).remote(
                cfg["callable_def"], cfg["init_args"], cfg["init_kwargs"],
                max_concurrent_queries=cfg["max_concurrent_queries"])
            starting.append({"id": rid, "handle": handle,
                             "version": version})
        # Health-gate: route no traffic to a replica that can't init.
        # A failing/hanging constructor must not leak the batch or
        # wedge the reconcile lock forever.
        try:
            for r in starting:
                await asyncio.wait_for(
                    _as_coro(r["handle"].ready.remote()),
                    timeout=REPLICA_STARTUP_TIMEOUT_S)
                current.append(r)
        except BaseException:
            for r in starting:
                if r not in current:
                    try:
                        ray_tpu.kill(r["handle"])
                    except Exception:  # noqa: BLE001
                        pass
            # keep serving whatever came healthy; surface the failure
            self._replicas[name] = current
            await self._notify(name)
            raise

        # Scale down extra same-version replicas (newest first).
        extra = current[want:]
        current = current[:want]

        if cfg["user_config"] is not None:
            for r in current:
                await r["handle"].reconfigure.remote(cfg["user_config"])

        self._replicas[name] = current
        await self._notify(name)  # switch routers to the new set...
        await self._drain_and_kill(outdated + extra)  # ...then drain old

    # ---- replica health (a crashed replica — SIGKILL, OOM — must come
    # OUT of router membership and back UP to the replica goal without
    # waiting for the next deploy; reference: backend_state.py's
    # actor-death handling in the controller loop) ----

    async def _health_loop(self) -> None:
        from ray_tpu import exceptions as exc_mod

        while self._configs:
            await asyncio.sleep(HEALTH_CHECK_PERIOD_S)
            for name in list(self._configs):
                live = self._replicas.get(name, [])
                if not live:
                    continue
                checks = await asyncio.gather(
                    *[asyncio.wait_for(_as_coro(r["handle"].ready.remote()),
                                       timeout=10.0) for r in live],
                    return_exceptions=True)
                dead = [r for r, c in zip(live, checks)
                        if isinstance(c, exc_mod.ActorDiedError)]
                # only a DEAD actor counts: a slow/timed-out ready()
                # (replica busy under load) must not get it replaced
                if not dead:
                    continue
                dead_ids = {r["id"] for r in dead}
                logger.warning("replica(s) %s of %s died; replacing",
                               sorted(dead_ids), name)
                self._replicas[name] = [r for r in live
                                        if r["id"] not in dead_ids]
                await self._notify(name)  # routers stop picking it NOW
                try:
                    await self._reconcile(name)  # scale back to goal
                except Exception:  # noqa: BLE001 — node still sick;
                    # retry next period
                    logger.exception("replacing dead replicas of %s "
                                     "failed", name)
        self._health_task = None

    # ---- autoscaling (reference: serve/autoscaling_policy.py
    # BasicAutoscalingPolicy driven from the controller loop) ----

    async def _autoscale_loop(self) -> None:
        while any(cfg.get("autoscaling_config")
                  for cfg in self._configs.values()):
            await asyncio.sleep(0.25)
            for name in list(self._configs):
                try:
                    await self._autoscale_one(name)
                except Exception:  # noqa: BLE001 — loop must survive
                    logger.exception("autoscale of %s failed", name)
        self._autoscale_task = None

    @staticmethod
    def _bounds(ac: dict) -> tuple:
        """(min, max) replica bounds; max_replicas <= 0 = unbounded."""
        lo = int(ac.get("min_replicas", 1))
        hi = ac.get("max_replicas", -1)
        return lo, (int(hi) if hi and int(hi) > 0 else 10**9)

    async def _autoscale_one(self, name: str) -> None:
        cfg = self._configs.get(name)
        ac = cfg.get("autoscaling_config") if cfg else None
        if not ac:
            return
        replicas = self._replicas.get(name, [])
        if not replicas:
            return
        # concurrent polls: one slow replica must not serialize the
        # pass (and through the shared loop, every OTHER deployment)
        results = await asyncio.gather(
            *[asyncio.wait_for(_as_coro(r["handle"].stats.remote()),
                               timeout=5.0) for r in replicas],
            return_exceptions=True)
        inflight = 0
        responsive = 0
        for res in results:
            if isinstance(res, BaseException):
                continue  # unresponsive != idle: excluded entirely
            responsive += 1
            inflight += int(res.get("inflight", 0))
        if responsive == 0:
            return  # no signal this round: never scale blind
        avg = inflight / responsive
        # the router caps replica concurrency at max_concurrent_queries,
        # so a threshold above the cap could never fire — saturation
        # must always count as scale-up pressure
        up_thresh = min(float(ac.get("scale_up_threshold", 5)),
                        float(cfg["max_concurrent_queries"]))
        down_thresh = float(ac.get("scale_down_threshold", 1))
        counter = self._scale_counters.get(name, 0)
        if avg >= up_thresh:
            counter = max(1, counter + 1)
        elif avg <= down_thresh:
            counter = min(-1, counter - 1)
        else:
            counter = 0
        lo, hi = self._bounds(ac)
        want = cfg["num_replicas"]
        if counter >= int(ac.get("scale_up_consecutive_periods", 2)):
            want = min(hi, want + int(ac.get("scale_up_num_replicas", 1)))
            counter = 0
        elif -counter >= int(ac.get("scale_down_consecutive_periods", 5)):
            want = max(lo, want - int(ac.get("scale_down_num_replicas", 1)))
            counter = 0
        self._scale_counters[name] = counter
        if want != cfg["num_replicas"]:
            logger.info("autoscaling %s: %d -> %d replicas (avg load %.2f)",
                        name, cfg["num_replicas"], want, avg)
            cfg["num_replicas"] = want
            await self._reconcile(name)

    async def _drain_and_kill(self, replicas: List[dict]) -> None:
        import ray_tpu

        for r in replicas:
            try:
                await r["handle"].drain.remote()
            except Exception:  # noqa: BLE001 — already dead is fine
                pass
            try:
                ray_tpu.kill(r["handle"])
            except Exception:  # noqa: BLE001
                pass
