"""Request batching: coalesce concurrent calls into one invocation.

Parity target: the reference's ``@serve.batch``
(reference: python/ray/serve/batching.py:163 — a decorator that queues
individually-awaited calls and invokes the wrapped function once with
the LIST of pending requests, releasing each caller with its element
of the returned list). On TPU this is the serving pattern that
matters: N concurrent single requests become ONE batched device
program instead of N tiny dispatches.

Usage (inside an async deployment)::

    @serve.deployment
    class Model:
        @serve.batch(max_batch_size=16, batch_wait_timeout_s=0.01)
        async def __call__(self, requests):   # a list arrives
            return model_fn(jnp.stack(requests))  # list goes back

        # callers still send single requests and await single results

Implementation: pure asyncio on the replica's event loop — a pending
list per (function, bound instance), flushed when it reaches
``max_batch_size`` or when ``batch_wait_timeout_s`` elapses after the
first enqueue. Exceptions from the batched call propagate to every
caller in the batch.
"""

from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable, List, Optional

from ray_tpu._private import rpc
from ray_tpu.exceptions import ServeOverloadedError


class _BatchQueue:
    """Pending calls for one batched function (per bound instance)."""

    def __init__(self, fn: Callable, max_batch_size: int,
                 timeout_s: float, max_pending: int = 0):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout_s = timeout_s
        self.max_pending = max_pending
        self.outstanding = 0   # submitted, not yet resolved
        self.num_shed = 0
        self.pending: List[tuple] = []  # (request, future)
        self._timer: Optional[asyncio.TimerHandle] = None

    async def submit(self, request: Any):
        if self.max_pending and self.outstanding >= self.max_pending:
            # Shed, typed, instead of queueing a request behind more
            # batches than the SLO can absorb — the proxy renders this
            # as 503 + Retry-After like every other overload signal.
            self.num_shed += 1
            raise ServeOverloadedError(
                f"batch queue full ({self.outstanding} outstanding, cap "
                f"{self.max_pending})")
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self.pending.append((request, fut))
        self.outstanding += 1
        if len(self.pending) >= self.max_batch_size:
            self._flush()
        elif self._timer is None:
            self._timer = loop.call_later(self.timeout_s, self._flush)
        try:
            return await fut
        finally:
            self.outstanding -= 1

    def _flush(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if not self.pending:
            return
        batch, self.pending = self.pending, []
        # Tracked spawn: _run fans most errors out to caller futures,
        # but anything it RAISES (wrong-length result bookkeeping, a
        # BaseException re-raised after fan-out) died silently in a
        # dropped task handle before — now it's logged and counted.
        rpc.spawn_logged(self._run(batch), "serve-batch-run")

    async def _run(self, batch: List[tuple]) -> None:
        requests = [r for r, _ in batch]
        try:
            results = await self.fn(requests)
            if results is None or len(results) != len(requests):
                raise ValueError(
                    f"batched function must return one result per "
                    f"request ({len(requests)} in, "
                    f"{'none' if results is None else len(results)} out)")
        except BaseException as e:  # noqa: BLE001 — fan the error out.
            # BaseException on purpose: a CancelledError (replica loop
            # teardown) must still resolve every caller's future, or
            # handle_request awaiters hang and drain() wedges the
            # rolling update.
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)
            if not isinstance(e, Exception):
                raise  # propagate cancellation to the loop
            return
        for (_, fut), res in zip(batch, results):
            if not fut.done():
                fut.set_result(res)


def batch(_func: Optional[Callable] = None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01, max_pending: int = 0):
    """``@serve.batch`` / ``@serve.batch(max_batch_size=...,
    batch_wait_timeout_s=...)`` on an async function or method.

    ``max_pending`` (0 = unbounded, the default) caps submitted-but-
    unresolved calls per queue; past it ``submit`` sheds with the typed
    :class:`~ray_tpu.exceptions.ServeOverloadedError` instead of
    stacking batches the device can never drain inside the SLO."""
    if max_batch_size < 1:
        raise ValueError("max_batch_size must be >= 1")
    if batch_wait_timeout_s < 0:
        raise ValueError("batch_wait_timeout_s must be >= 0")
    if max_pending < 0:
        raise ValueError("max_pending must be >= 0")

    def decorate(fn: Callable) -> Callable:
        if not asyncio.iscoroutinefunction(fn):
            raise TypeError("@serve.batch requires an async function")
        # Queues are created lazily REPLICA-side and stored on the
        # bound instance (methods) or the wrapper itself (functions) —
        # no closure state, so the decorated deployment pickles to its
        # replica actor cleanly.
        qattr = f"_rtpu_batch_queue__{fn.__name__}"

        @functools.wraps(fn)
        async def wrapper(*args):
            # method call: (self, request); function call: (request,)
            if len(args) == 2:
                instance, request = args
            elif len(args) == 1:
                instance, request = None, args[0]
            else:
                raise TypeError(
                    "@serve.batch functions take exactly one request "
                    "argument")
            holder = wrapper if instance is None else instance
            q = getattr(holder, qattr, None)
            if q is None:
                bound = fn if instance is None \
                    else functools.partial(fn, instance)
                q = _BatchQueue(bound, max_batch_size,
                                batch_wait_timeout_s, max_pending)
                setattr(holder, qattr, q)
            return await q.submit(request)

        wrapper._rtpu_batched = True
        return wrapper

    return decorate(_func) if _func is not None else decorate
