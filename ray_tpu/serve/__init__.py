"""Model serving on the actor runtime.

Parity target: the reference's Serve control/data plane
(reference: python/ray/serve/ — ServeController controller.py:38,
Router/ReplicaSet router.py:45,177, RayServeHandle handle.py:44,
@serve.deployment api.py:610,865, LongPollClient/Host long_poll.py).
Handle-based calls are first-class (they compose with the task graph);
an HTTP ingress can be layered on top of handles.

Usage::

    from ray_tpu import serve

    serve.start()

    @serve.deployment(num_replicas=2, max_concurrent_queries=4)
    class Model:
        def __call__(self, x):
            return x * 2

    Model.deploy()
    handle = Model.get_handle()
    ray_tpu.get(handle.remote(21))  # 42
"""

from __future__ import annotations

import uuid
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.serve.controller import CONTROLLER_NAME, ServeController
from ray_tpu.serve.handle import DeploymentHandle

__all__ = [
    "start", "shutdown", "deployment", "get_deployment",
    "list_deployments", "DeploymentHandle",
]

_controller = None


def start(detached: bool = False):
    """Start (or connect to) the serve control plane.

    ``detached=True`` keeps the controller alive past this driver, like
    the reference's serve.start(detached=True).
    """
    global _controller
    if _controller is not None:
        return _controller
    opts = {"name": CONTROLLER_NAME, "get_if_exists": True,
            "max_concurrency": 1000}
    if detached:
        opts["lifetime"] = "detached"
    _controller = ray_tpu.remote(ServeController).options(**opts).remote()
    return _controller


def _get_controller():
    global _controller
    if _controller is None:
        try:
            _controller = ray_tpu.get_actor(CONTROLLER_NAME)
        except Exception:
            raise RuntimeError(
                "serve.start() must be called first") from None
    return _controller


def shutdown() -> None:
    """Tear down every deployment and the controller."""
    global _controller
    if _controller is None:
        try:
            _controller = ray_tpu.get_actor(CONTROLLER_NAME)
        except Exception:
            return
    ray_tpu.get(_controller.shutdown.remote())
    ray_tpu.kill(_controller)
    _controller = None


class Deployment:
    """Declarative deployment: callable + config, bound by deploy()."""

    def __init__(self, func_or_class: Callable, name: str,
                 num_replicas: int = 1,
                 max_concurrent_queries: int = 100,
                 version: Optional[str] = None,
                 user_config: Any = None,
                 ray_actor_options: Optional[Dict] = None,
                 init_args: tuple = (), init_kwargs: Optional[dict] = None):
        self._func_or_class = func_or_class
        self.name = name
        self.num_replicas = num_replicas
        self.max_concurrent_queries = max_concurrent_queries
        self.version = version
        self.user_config = user_config
        self.ray_actor_options = ray_actor_options or {}
        self.init_args = init_args
        self.init_kwargs = init_kwargs or {}

    def options(self, **overrides) -> "Deployment":
        cfg = {
            "name": self.name, "num_replicas": self.num_replicas,
            "max_concurrent_queries": self.max_concurrent_queries,
            "version": self.version, "user_config": self.user_config,
            "ray_actor_options": dict(self.ray_actor_options),
            "init_args": self.init_args,
            "init_kwargs": dict(self.init_kwargs),
        }
        cfg.update(overrides)
        return Deployment(self._func_or_class, **cfg)

    def deploy(self, *init_args, **init_kwargs) -> None:
        """Create or roll the deployment to this config (blocking)."""
        controller = _get_controller()
        ray_tpu.get(controller.deploy.remote(
            self.name, self._func_or_class,
            init_args or self.init_args,
            init_kwargs or self.init_kwargs,
            num_replicas=self.num_replicas,
            max_concurrent_queries=self.max_concurrent_queries,
            # an unversioned redeploy always rolls: fresh token
            version=self.version or uuid.uuid4().hex,
            user_config=self.user_config,
            ray_actor_options=self.ray_actor_options))

    def delete(self) -> None:
        controller = _get_controller()
        ray_tpu.get(controller.delete_deployment.remote(self.name))

    def get_handle(self) -> DeploymentHandle:
        return DeploymentHandle(_get_controller(), self.name)

    def __call__(self, *a, **kw):
        raise RuntimeError(
            "deployments are invoked via .get_handle().remote(), not "
            "called directly")


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_concurrent_queries: int = 100,
               version: Optional[str] = None, user_config: Any = None,
               ray_actor_options: Optional[Dict] = None):
    """``@serve.deployment`` decorator (bare or with options)."""
    def wrap(func_or_class):
        return Deployment(
            func_or_class,
            name or func_or_class.__name__,
            num_replicas=num_replicas,
            max_concurrent_queries=max_concurrent_queries,
            version=version, user_config=user_config,
            ray_actor_options=ray_actor_options)

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


def get_deployment(name: str) -> Deployment:
    """Fetch a live deployment's config as a re-deployable object."""
    controller = _get_controller()
    info = ray_tpu.get(controller.get_deployment_info.remote(name))
    if info is None:
        raise KeyError(f"no deployment named {name!r}")
    dep = Deployment(
        None, name,
        num_replicas=info["num_replicas"],
        max_concurrent_queries=info["max_concurrent_queries"],
        version=info["version"], user_config=info["user_config"],
        ray_actor_options=info["ray_actor_options"],
        init_args=info["init_args"], init_kwargs=info["init_kwargs"])
    return dep


def list_deployments() -> List[str]:
    return ray_tpu.get(_get_controller().list_deployments.remote())
