"""Model serving on the actor runtime.

Parity target: the reference's Serve control/data plane
(reference: python/ray/serve/ — ServeController controller.py:38,
Router/ReplicaSet router.py:45,177, RayServeHandle handle.py:44,
@serve.deployment api.py:610,865, LongPollClient/Host long_poll.py).
Handle-based calls are first-class (they compose with the task graph);
HTTP ingress is served by the HTTPProxy actor (http_proxy.py, parity
with python/ray/serve/http_proxy.py:162): every deployment gets a
route (default ``/<name>``, opt out with ``route_prefix=None``) and
receives an ``HTTPRequest`` when invoked over HTTP.

Usage::

    from ray_tpu import serve

    serve.start()

    @serve.deployment(num_replicas=2, max_concurrent_queries=4)
    class Model:
        def __call__(self, x):
            return x * 2

    Model.deploy()
    handle = Model.get_handle()
    ray_tpu.get(handle.remote(21))  # 42
"""

from __future__ import annotations

import uuid
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.serve.batching import batch  # noqa: F401
from ray_tpu.serve.controller import CONTROLLER_NAME, ServeController
from ray_tpu.serve.decode_scheduler import (DecodeScheduler,  # noqa: F401
                                            JaxSlotEngine)
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve.http_proxy import (HTTPProxy, HTTPRequest, HTTPResponse,
                                      PROXY_NAME)

__all__ = [
    "start", "shutdown", "deployment", "get_deployment",
    "list_deployments", "DeploymentHandle", "HTTPRequest", "HTTPResponse",
    "get_http_address", "batch", "DecodeScheduler", "JaxSlotEngine",
]

_controller = None
_http_address = None


def start(detached: bool = False, http: bool = True,
          http_host: str = "127.0.0.1", http_port: int = 0):
    """Start (or connect to) the serve control plane.

    ``detached=True`` keeps the controller alive past this driver, like
    the reference's serve.start(detached=True). ``http=True`` (default)
    also starts the HTTP ingress proxy (reference:
    python/ray/serve/http_proxy.py); ``http_port=0`` binds an ephemeral
    port — read it back with :func:`get_http_address`.
    """
    global _controller, _http_address
    if _controller is not None:
        return _controller
    opts = {"name": CONTROLLER_NAME, "get_if_exists": True,
            "max_concurrency": 1000}
    if detached:
        opts["lifetime"] = "detached"
    _controller = ray_tpu.remote(ServeController).options(**opts).remote()
    if http:
        popts = {"name": PROXY_NAME, "get_if_exists": True,
                 "max_concurrency": 10000, "num_cpus": 0}
        if detached:
            popts["lifetime"] = "detached"
        proxy = ray_tpu.remote(HTTPProxy).options(**popts).remote(
            _controller, http_host, http_port)
        _http_address = ray_tpu.get(proxy.ready.remote())
    return _controller


def get_http_address() -> Optional[str]:
    """'host:port' of the HTTP ingress, or None if HTTP is off."""
    global _http_address
    if _http_address is None:
        try:
            proxy = ray_tpu.get_actor(PROXY_NAME)
            _http_address = ray_tpu.get(proxy.ready.remote())
        except Exception:
            return None
    return _http_address


def _get_controller():
    global _controller
    if _controller is None:
        try:
            _controller = ray_tpu.get_actor(CONTROLLER_NAME)
        except Exception:
            raise RuntimeError(
                "serve.start() must be called first") from None
    return _controller


def shutdown() -> None:
    """Tear down every deployment, the HTTP proxy, and the controller."""
    global _controller, _http_address
    if _controller is None:
        try:
            _controller = ray_tpu.get_actor(CONTROLLER_NAME)
        except Exception:
            return
    try:
        proxy = ray_tpu.get_actor(PROXY_NAME)
        ray_tpu.get(proxy.drain.remote())
        ray_tpu.kill(proxy)
    except Exception:
        pass
    ray_tpu.get(_controller.shutdown.remote())
    ray_tpu.kill(_controller)
    _controller = None
    _http_address = None


class Deployment:
    """Declarative deployment: callable + config, bound by deploy()."""

    def __init__(self, func_or_class: Callable, name: str,
                 num_replicas: int = 1,
                 max_concurrent_queries: int = 100,
                 version: Optional[str] = None,
                 user_config: Any = None,
                 ray_actor_options: Optional[Dict] = None,
                 init_args: tuple = (), init_kwargs: Optional[dict] = None,
                 route_prefix: Optional[str] = "__default__",
                 autoscaling_config: Optional[Dict] = None):
        self._func_or_class = func_or_class
        self.name = name
        self.num_replicas = num_replicas
        self.max_concurrent_queries = max_concurrent_queries
        self.version = version
        self.user_config = user_config
        self.ray_actor_options = ray_actor_options or {}
        self.init_args = init_args
        self.init_kwargs = init_kwargs or {}
        # "__default__" → /<name>; None → not HTTP-routable (handle-only)
        self.route_prefix = route_prefix
        # reference: autoscaling_policy.py BasicAutoscalingPolicy keys
        # (min/max_replicas, scale_up/down_threshold, *_consecutive_
        # periods, scale_up/down_num_replicas); None = fixed replicas
        self.autoscaling_config = autoscaling_config

    def options(self, **overrides) -> "Deployment":
        cfg = {
            "name": self.name, "num_replicas": self.num_replicas,
            "max_concurrent_queries": self.max_concurrent_queries,
            "version": self.version, "user_config": self.user_config,
            "ray_actor_options": dict(self.ray_actor_options),
            "init_args": self.init_args,
            "init_kwargs": dict(self.init_kwargs),
            "route_prefix": self.route_prefix,
            "autoscaling_config": self.autoscaling_config,
        }
        cfg.update(overrides)
        return Deployment(self._func_or_class, **cfg)

    def deploy(self, *init_args, **init_kwargs) -> None:
        """Create or roll the deployment to this config (blocking)."""
        controller = _get_controller()
        ray_tpu.get(controller.deploy.remote(
            self.name, self._func_or_class,
            init_args or self.init_args,
            init_kwargs or self.init_kwargs,
            num_replicas=self.num_replicas,
            max_concurrent_queries=self.max_concurrent_queries,
            # an unversioned redeploy always rolls: fresh token
            version=self.version or uuid.uuid4().hex,
            user_config=self.user_config,
            ray_actor_options=self.ray_actor_options,
            route_prefix=self.route_prefix,
            autoscaling_config=self.autoscaling_config))
        _wait_http_route(self.name, self.route_prefix)

    def delete(self) -> None:
        controller = _get_controller()
        ray_tpu.get(controller.delete_deployment.remote(self.name))
        _wait_http_route(self.name, None)

    def get_handle(self) -> DeploymentHandle:
        return DeploymentHandle(_get_controller(), self.name)

    def __call__(self, *a, **kw):
        raise RuntimeError(
            "deployments are invoked via .get_handle().remote(), not "
            "called directly")


def _wait_http_route(name: str, route_prefix) -> None:
    """Best-effort: block until the HTTP proxy applied the new route
    table (the long-poll push is async; without this the first request
    after deploy() races the table update and can 404)."""
    try:
        proxy = ray_tpu.get_actor(PROXY_NAME)
    except Exception:  # noqa: BLE001 — http=False or detached teardown
        return
    try:
        ray_tpu.get(proxy.wait_for_route.remote(name, route_prefix),
                    timeout=15)
    except Exception:  # noqa: BLE001 — readiness is advisory
        pass


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_concurrent_queries: int = 100,
               version: Optional[str] = None, user_config: Any = None,
               ray_actor_options: Optional[Dict] = None,
               route_prefix: Optional[str] = "__default__",
               autoscaling_config: Optional[Dict] = None):
    """``@serve.deployment`` decorator (bare or with options)."""
    def wrap(func_or_class):
        return Deployment(
            func_or_class,
            name or func_or_class.__name__,
            num_replicas=num_replicas,
            max_concurrent_queries=max_concurrent_queries,
            version=version, user_config=user_config,
            ray_actor_options=ray_actor_options,
            route_prefix=route_prefix,
            autoscaling_config=autoscaling_config)

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


def get_deployment(name: str) -> Deployment:
    """Fetch a live deployment's config as a re-deployable object."""
    controller = _get_controller()
    info = ray_tpu.get(controller.get_deployment_info.remote(name))
    if info is None:
        raise KeyError(f"no deployment named {name!r}")
    dep = Deployment(
        None, name,
        num_replicas=info["num_replicas"],
        max_concurrent_queries=info["max_concurrent_queries"],
        version=info["version"], user_config=info["user_config"],
        ray_actor_options=info["ray_actor_options"],
        init_args=info["init_args"], init_kwargs=info["init_kwargs"],
        route_prefix=info.get("route_prefix"),
        autoscaling_config=info.get("autoscaling_config"))
    return dep


def list_deployments() -> List[str]:
    return ray_tpu.get(_get_controller().list_deployments.remote())
