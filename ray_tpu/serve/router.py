"""ReplicaSet: replica selection with max-concurrent-queries backpressure.

Parity target: the reference's Router/ReplicaSet
(reference: python/ray/serve/router.py:45,177). Membership comes from
the controller via long-poll; assignment is round-robin over replicas
with a free slot, and when every replica is saturated the caller BLOCKS
until an in-flight request completes — queries can't pile up
unboundedly on replica queues (the reference enforces the same cap via
its async flow-control loop).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

from ray_tpu._private import metrics as metrics_mod
from ray_tpu._private.object_ref import ObjectRef


class ReplicaSet:
    """Thread-safe (handles may be shared across driver threads)."""

    def __init__(self, deployment_name: str):
        self.deployment_name = deployment_name
        self._lock = threading.Lock()
        self._replicas: List[dict] = []       # {"id", "handle"}
        self._max_queries = 1
        self._inflight: Dict[str, List[ObjectRef]] = {}
        self._rr = 0
        self._have_members = threading.Event()
        # pulsed on every membership push so flap-waiters wake on the
        # long-poll delivery, not a fixed sleep (r3 verdict weak #5)
        self._membership_changed = threading.Event()
        # callers blocked in assign() backpressure; exported (with
        # in-flight) as ray_tpu_serve_{queue_depth,inflight} so the
        # dashboard sees handle-side routers next to the HTTP proxy.
        # Gauge merge is last-writer-wins per label set, hence the
        # per-router label (see metrics_mod.serve_metrics).
        self._num_waiting = 0
        self._metrics = metrics_mod.serve_metrics()
        self._labels = {"deployment": deployment_name,
                        "router": f"handle:{os.getpid()}"}

    def _export_gauges(self) -> None:
        """In-flight here counts bookkeeping refs, i.e. completed-but-
        unpruned queries inflate it until the next prune — an upper
        bound, matching what assign() backpressures on."""
        with self._lock:
            inflight = sum(len(v) for v in self._inflight.values())
            waiting = self._num_waiting
        self._metrics["inflight"].set(inflight, labels=self._labels)
        self._metrics["queue_depth"].set(waiting, labels=self._labels)

    # ---- membership (long-poll callback + bootstrap) ----

    def update_membership(self, snapshot: dict) -> None:
        with self._lock:
            self._replicas = list(snapshot.get("replicas", []))
            self._max_queries = max(
                1, int(snapshot.get("max_concurrent_queries", 1)))
            live = {r["id"] for r in self._replicas}
            for rid in list(self._inflight):
                if rid not in live:
                    del self._inflight[rid]
        if self._replicas:
            self._have_members.set()
        else:
            self._have_members.clear()
        self._membership_changed.set()

    # ---- assignment ----

    def assign(self, method: str, args: tuple, kwargs: dict,
               timeout_s: Optional[float] = None) -> ObjectRef:
        """Pick a replica with a free slot and submit; block when all
        replicas are at max_concurrent_queries."""
        import ray_tpu

        timeout_s = 30.0 if timeout_s is None else timeout_s
        deadline = time.monotonic() + timeout_s
        grace_pick_used = False
        while True:
            if not self._have_members.wait(
                    timeout=max(0.0, deadline - time.monotonic())):
                raise RuntimeError(
                    f"no replicas for deployment "
                    f"{self.deployment_name!r} (not deployed or deleted)")
            with self._lock:
                replica = self._try_pick()
                if replica is not None:
                    ref = replica["handle"].handle_request.remote(
                        method, args, kwargs)
                    self._inflight.setdefault(replica["id"], []).append(ref)
                    self._metrics["inflight"].set(
                        sum(len(v) for v in self._inflight.values()),
                        labels=self._labels)
                    return ref
                all_inflight = [r for refs in self._inflight.values()
                                for r in refs]
                # clear INSIDE the lock: membership applied before our
                # failed pick was already visible to it, and any update
                # applied after will set() after we cleared — no lost
                # wakeup window between release and clear
                self._membership_changed.clear()
                self._num_waiting += 1
            self._export_gauges()
            # Backpressure: every slot is busy. Wait for ANY in-flight
            # query to finish, then retry the pick. Only an actual
            # completion resets the timeout (progress); a wedged
            # replica must not block a caller that asked for a bound.
            try:
                if all_inflight:
                    done, _ = ray_tpu.wait(all_inflight, num_returns=1,
                                           timeout=1.0)
                    if done:
                        deadline = time.monotonic() + timeout_s
                    elif time.monotonic() >= deadline:
                        raise RuntimeError(
                            f"timed out after {timeout_s}s waiting for a "
                            f"free slot on deployment "
                            f"{self.deployment_name!r} (all "
                            f"{len(self._replicas)} replicas at "
                            f"max_concurrent_queries={self._max_queries})")
                else:
                    # No pickable slot and nothing in flight: membership
                    # flapped mid-roll. Sleep until the next long-poll
                    # push (bounded so the deadline still applies). A
                    # push landing at the wire earns exactly ONE
                    # post-deadline re-pick — so a replica restored at
                    # the buzzer is served, but continuous flapping (or
                    # another caller consuming the shared event) can't
                    # starve the timeout.
                    signaled = self._membership_changed.wait(
                        timeout=min(1.0, max(0.01,
                                             deadline - time.monotonic())))
                    if time.monotonic() >= deadline:
                        if not signaled or grace_pick_used:
                            raise RuntimeError(
                                f"timed out after {timeout_s}s waiting "
                                f"for a usable replica on deployment "
                                f"{self.deployment_name!r}")
                        grace_pick_used = True
            finally:
                with self._lock:
                    self._num_waiting -= 1
                self._export_gauges()

    def _prune_locked(self, rid: str) -> List[ObjectRef]:
        """Drop completed refs from one replica's book (holds lock)."""
        import ray_tpu

        refs = self._inflight.get(rid, [])
        if refs:
            _, refs = ray_tpu.wait(refs, num_returns=len(refs),
                                   timeout=0)
            self._inflight[rid] = refs
        return refs

    def _try_pick(self) -> Optional[dict]:
        """Round-robin over replicas with spare capacity. Caller holds
        the lock. Books are pruned only when they look full — the
        unsaturated fast path costs zero IO-loop round trips; the
        handle's 1s janitor covers quiesced-traffic ref release."""
        n = len(self._replicas)
        if not n:
            return None
        prune_at = min(self._max_queries, 32)
        for i in range(n):
            replica = self._replicas[(self._rr + i) % n]
            refs = self._inflight.get(replica["id"], [])
            if len(refs) >= prune_at:
                refs = self._prune_locked(replica["id"])
            if len(refs) < self._max_queries:
                self._rr = (self._rr + i + 1) % n
                return replica
        return None

    def prune(self) -> None:
        """Release completed refs from every book. Called by the
        handle's janitor so quiesced traffic doesn't pin results in the
        object store via our bookkeeping copies."""
        with self._lock:
            for rid in list(self._inflight):
                self._prune_locked(rid)

    def num_queued(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._inflight.values())
