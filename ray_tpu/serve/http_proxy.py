"""HTTP ingress for serve: an asyncio HTTP/1.1 server inside an actor.

Parity target: the reference's HTTPProxy
(reference: python/ray/serve/http_proxy.py:162) — an actor per ingress
node accepting HTTP traffic, routing by path prefix to deployments, and
forwarding to replicas with max-concurrent-queries flow control. The
reference fronts uvicorn/starlette; here the server is stdlib asyncio
(no external deps), the route table arrives over the controller's
long-poll channel, and replica assignment is fully async (awaiting
ObjectRefs on the actor's event loop) so thousands of connections share
one loop without threads.

Deployments receive an :class:`HTTPRequest`; they may return
``bytes`` / ``str`` / JSON-able objects or an :class:`HTTPResponse`
for full control. ``GET /-/routes`` returns the live route table.

Data path (the serving front door at speed):

* **Zero-copy ingress** — a request body at or above
  ``serve_ingress_shm_threshold`` is written straight into shm through
  the AllocSegment lease path (``core_worker.put_async``, scheduled on
  the core IO loop so the proxy's event loop never blocks on the seal)
  and crosses proxy -> router -> replica as an ObjectRef riding
  ``HTTPRequest.body_ref``; the replica resolves it before user code
  runs. Large replica returns already travel by reference (the task
  return plane seals them), so responses are symmetric for free.
* **SLO-aware load shedding** — an admission controller sheds at the
  door once waiting + in-flight requests exceed the deployment's queue
  budget (capacity x ``serve_shed_queue_factor``), or its observed p99
  exceeds ``serve_shed_p99_budget_s`` while every slot is busy.
  Sheds reply ``503`` with a backlog-scaled ``Retry-After`` — the
  typed :class:`~ray_tpu.exceptions.ServeOverloadedError` raised by a
  replica's own queue cap or decode scheduler renders the same way.
* **Tracing** — with ``RAY_TPU_TRACE=1`` every request runs inside an
  accept->reply span (util/tracing.py), so ``state.timeline()`` shows
  the HTTP edge on the same wall clock as the task/object/RPC planes.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import os
import time
import traceback
from typing import Any, Dict, List, Optional
from urllib.parse import parse_qsl, unquote, urlsplit

from ray_tpu import exceptions as exc
from ray_tpu._private import metrics as metrics_mod
from ray_tpu.serve.controller import ROUTES_KEY, SNAPSHOT_KEY
from ray_tpu.serve.long_poll import LongPollClient
from ray_tpu.util import tracing

logger = logging.getLogger(__name__)

PROXY_NAME = "SERVE_PROXY"
IDLE_KEEPALIVE_S = 60.0
MAX_HEADER_BYTES = 65536
MAX_BODY_BYTES = 512 * 1024 * 1024
# Rolling per-deployment latency reservoir behind the p99 half of the
# admission decision (newest-biased: appends drop the oldest).
LATENCY_SAMPLES = 256


class HTTPRequest:
    """What a deployment's callable receives for an HTTP-routed query.

    ``body`` is the raw bytes for small requests. Past the shm ingress
    threshold the proxy ships ``body_ref`` (an ObjectRef to the bytes)
    instead and the Replica wrapper resolves it back into ``body``
    before user code runs — deployment code never sees the difference.
    """

    __slots__ = ("method", "path", "route_prefix", "query_string", "query",
                 "headers", "body", "body_ref")

    def __init__(self, method: str, path: str, route_prefix: str,
                 query_string: str, headers: Dict[str, str], body: bytes,
                 body_ref: Any = None):
        self.method = method
        self.path = path
        self.route_prefix = route_prefix
        self.query_string = query_string
        self.query = dict(parse_qsl(query_string))
        self.headers = headers
        self.body = body
        self.body_ref = body_ref

    def __getstate__(self):
        return {s: getattr(self, s) for s in self.__slots__}

    def __setstate__(self, state):
        for s in self.__slots__:
            setattr(self, s, state.get(s))

    @property
    def text(self) -> str:
        return self.body.decode("utf-8", errors="replace")

    def json(self) -> Any:
        return json.loads(self.body) if self.body else None

    def __repr__(self) -> str:
        return f"HTTPRequest({self.method} {self.path!r})"


class HTTPResponse:
    """Explicit response: status, headers, raw body."""

    __slots__ = ("status", "body", "headers", "content_type")

    def __init__(self, body: Any = b"", status: int = 200,
                 content_type: Optional[str] = None,
                 headers: Optional[Dict[str, str]] = None):
        self.status = int(status)
        self.body = body
        self.content_type = content_type
        self.headers = dict(headers or {})


_REASONS = {200: "OK", 204: "No Content", 400: "Bad Request",
            404: "Not Found", 405: "Method Not Allowed",
            411: "Length Required", 500: "Internal Server Error",
            503: "Service Unavailable"}


def _encode_result(result: Any) -> HTTPResponse:
    if isinstance(result, HTTPResponse):
        return result
    if result is None:
        return HTTPResponse(b"", status=200, content_type="text/plain")
    if isinstance(result, (bytes, bytearray, memoryview)):
        return HTTPResponse(bytes(result),
                            content_type="application/octet-stream")
    if isinstance(result, str):
        return HTTPResponse(result.encode(),
                            content_type="text/plain; charset=utf-8")
    return HTTPResponse(json.dumps(result, default=str).encode(),
                        content_type="application/json")


class _AsyncReplicaSet:
    """Per-deployment replica selection on the proxy's event loop.

    The handle-side ReplicaSet (ray_tpu/serve/router.py) blocks a
    thread; inside the proxy every request is a coroutine, so
    saturation is awaited, not slept: when all replicas are at
    max_concurrent_queries the assigner waits on the in-flight futures
    and retries on first completion (reference: ReplicaSet.
    assign_replica, python/ray/serve/router.py:177).
    """

    def __init__(self, name: str):
        self.name = name
        self.replicas: List[dict] = []
        self.max_queries = 1
        self._inflight: Dict[str, set] = {}   # rid -> set[asyncio.Future]
        self._rr = 0
        self._changed = asyncio.Event()
        self._member_ids: set = set()
        # assign() coroutines parked waiting for a free slot — the
        # queue-depth half of the admission controller's view
        self.num_waiting = 0

    def inflight_count(self) -> int:
        return sum(len(s) for s in self._inflight.values())

    def capacity(self) -> int:
        return len(self.replicas) * self.max_queries

    def update_membership(self, snapshot: dict) -> None:
        self.replicas = list(snapshot.get("replicas", []))
        self.max_queries = max(
            1, int(snapshot.get("max_concurrent_queries", 1)))
        live = {r["id"] for r in self.replicas}
        # the controller's authoritative view (local evictions in
        # assign() don't touch this): died-replica retry policy keys
        # off whether the controller REMOVED the replica (a roll) or
        # still believes in it (a crash)
        self._member_ids = set(live)
        for rid in list(self._inflight):
            if rid not in live:
                del self._inflight[rid]
        self._changed.set()

    async def _safe_to_retry(self, rid: str, idempotent: bool) -> bool:
        """Whether a request whose replica died may be re-sent.

        A controlled roll drains before killing, so a died call never
        started executing — always safe. A spontaneous crash may have
        executed side effects, so only idempotent requests retry.
        Roll evidence = the controller's membership no longer lists the
        replica (waiting briefly for the in-flight push to land)."""
        if idempotent:
            return True
        loop = asyncio.get_running_loop()
        deadline = loop.time() + 2.0
        while rid in self._member_ids:
            remaining = deadline - loop.time()
            if remaining <= 0:
                return False  # controller still believes in it: crash
            self._changed.clear()
            try:
                await asyncio.wait_for(self._changed.wait(), remaining)
            except asyncio.TimeoutError:
                return False
        return True

    async def assign(self, method: str, args: tuple, kwargs: dict,
                     timeout_s: float = 30.0, idempotent: bool = False):
        """Submit to a replica with a free slot; returns the result."""
        deadline = asyncio.get_running_loop().time() + timeout_s
        while True:
            replica = self._try_pick()
            if replica is not None:
                rid = replica["id"]
                ref = replica["handle"].handle_request.remote(
                    method, args, kwargs)
                fut = asyncio.ensure_future(ref.as_future())
                book = self._inflight.setdefault(rid, set())
                book.add(fut)
                fut.add_done_callback(book.discard)
                try:
                    return await fut
                except exc.ActorDiedError:
                    self.replicas = [r for r in self.replicas
                                     if r["id"] != rid]
                    self._inflight.pop(rid, None)
                    if await self._safe_to_retry(rid, idempotent):
                        continue
                    raise
            waiters = [f for s in self._inflight.values() for f in s]
            self._changed.clear()
            timeout = deadline - asyncio.get_running_loop().time()
            if timeout <= 0:
                raise RuntimeError(
                    f"timed out waiting for a free slot on deployment "
                    f"{self.name!r} ({len(self.replicas)} replicas at "
                    f"max_concurrent_queries={self.max_queries})")
            membership = asyncio.ensure_future(self._changed.wait())
            self.num_waiting += 1
            try:
                # Wake on any completion OR a membership change.
                await asyncio.wait(
                    waiters + [membership],
                    timeout=min(timeout, 1.0),
                    return_when=asyncio.FIRST_COMPLETED)
            finally:
                self.num_waiting -= 1
                membership.cancel()

    def _try_pick(self) -> Optional[dict]:
        n = len(self.replicas)
        for i in range(n):
            replica = self.replicas[(self._rr + i) % n]
            if len(self._inflight.get(replica["id"], ())) < self.max_queries:
                self._rr = (self._rr + i + 1) % n
                return replica
        return None


class HTTPProxy:
    """Async actor hosting the ingress server.

    Lifecycle: the controller-facing side (route table, replica
    membership) updates via long-poll; connections are served on the
    actor's event loop. In-flight requests survive deployment updates:
    the controller drains replicas before killing them, and the proxy
    holds the ObjectRef until the reply lands.
    """

    def __init__(self, controller, host: str = "127.0.0.1", port: int = 0):
        self._controller = controller
        self._host = host
        self._port = port
        self._routes: Dict[str, str] = {}       # prefix -> deployment name
        self._sets: Dict[str, _AsyncReplicaSet] = {}
        self._server = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._long_poll: Optional[LongPollClient] = None
        # signaled on EVERY route/membership change: waiters (deploy
        # barrier, bootstrap-race requests) wake on the push instead of
        # a 20-50 ms poll timer (r3 verdict weak #5)
        self._changed: asyncio.Event = asyncio.Event()
        self.num_requests = 0
        self.num_errors = 0
        self.num_shed = 0
        self.num_ingress_shm = 0
        # knobs resolved in ready() (the worker's config is wired up by
        # the time the actor serves)
        self._ingress_threshold = 64 * 1024
        self._shed_queue_factor = 2.0
        self._shed_p99_budget_s = 0.0
        self._retry_after_floor_s = 1.0
        # per-deployment rolling latency samples (seconds) feeding the
        # p99 half of the admission decision
        self._latency: Dict[str, List[float]] = {}
        self._metrics = None  # serve_metrics(), bound in ready()

    def _signal_change(self) -> None:
        self._changed.set()
        self._changed = asyncio.Event()

    async def _wait_change(self, deadline: float) -> bool:
        """Wait for the next change signal (or deadline); True if
        signaled."""
        ev = self._changed
        remaining = deadline - asyncio.get_running_loop().time()
        if remaining <= 0:
            return False
        try:
            await asyncio.wait_for(ev.wait(), remaining)
            return True
        except asyncio.TimeoutError:
            return False

    async def ready(self) -> str:
        """Start the server (idempotent); returns 'host:port'."""
        if self._server is None:
            self._loop = asyncio.get_running_loop()
            try:
                import ray_tpu.worker as worker_mod
                cfg = worker_mod.global_worker.core.config
                self._ingress_threshold = int(
                    cfg.serve_ingress_shm_threshold)
                self._shed_queue_factor = max(
                    1.0, float(cfg.serve_shed_queue_factor))
                self._shed_p99_budget_s = float(cfg.serve_shed_p99_budget_s)
                self._retry_after_floor_s = max(
                    0.0, float(cfg.serve_retry_after_s))
            except Exception:  # noqa: BLE001 — standalone/unit harness:
                pass           # keep the defaults
            self._metrics = metrics_mod.serve_metrics()
            # Client first: _apply_routes registers per-deployment
            # membership callbacks on it, including for deployments
            # that predate the proxy.
            self._long_poll = LongPollClient(
                self._controller,
                {ROUTES_KEY: self._on_routes_changed})
            routes = await self._controller.get_routes.remote()
            await self._apply_routes(routes)
            self._server = await asyncio.start_server(
                self._handle_connection, host=self._host, port=self._port)
            self._port = self._server.sockets[0].getsockname()[1]
            logger.info("serve HTTP proxy listening on %s:%d",
                        self._host, self._port)
        return f"{self._host}:{self._port}"

    async def drain(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        if self._long_poll is not None:
            self._long_poll.stop()

    async def wait_for_route(self, name: str, prefix,
                             timeout_s: float = 10.0) -> bool:
        """Block until this proxy's applied route table reflects the
        deployment (deploy() calls this so the first HTTP request after
        a blocking deploy cannot 404 on a stale table; the reference's
        deploy waits on goal_id completion the same way,
        python/ray/serve/api.py Deployment.deploy). ``prefix`` is the
        raw config value: the ``__default__`` sentinel means /<name>,
        None means the deployment must NOT be routable."""
        deadline = asyncio.get_running_loop().time() + timeout_s
        if prefix == "__default__":
            prefix = "/" + name

        def applied() -> bool:
            if prefix is None:
                return name not in self._routes.values()
            return self._routes.get(prefix) == name

        while not applied():
            if not await self._wait_change(deadline):
                return applied()
        return True

    # ---- route/membership plumbing ----

    def _on_routes_changed(self, routes: Dict[str, str]) -> None:
        # Called from the long-poll thread; hop to the loop.
        if self._loop is not None:
            fut = asyncio.run_coroutine_threadsafe(
                self._apply_routes(routes), self._loop)

            def _log_err(f):
                if f.exception() is not None:
                    logger.error("route-table apply failed: %r",
                                 f.exception())
            fut.add_done_callback(_log_err)

    async def _apply_routes(self, routes: Dict[str, str]) -> None:
        self._routes = dict(routes or {})
        wanted = set(self._routes.values())
        for name in wanted - set(self._sets):
            rs = _AsyncReplicaSet(name)
            snapshot = await self._controller.get_replica_snapshot.remote(
                name)
            rs.update_membership(snapshot)
            self._sets[name] = rs
            if self._long_poll is not None:
                self._long_poll.add_callback(
                    SNAPSHOT_KEY.format(name=name),
                    self._membership_cb(name))
        for name in set(self._sets) - wanted:
            del self._sets[name]
        self._signal_change()

    def _membership_cb(self, name: str):
        def cb(snapshot: dict) -> None:
            if self._loop is None:
                return

            def apply() -> None:
                rs = self._sets.get(name)
                if rs is not None:
                    rs.update_membership(snapshot)
                self._signal_change()
            self._loop.call_soon_threadsafe(apply)
        return cb

    def _match_route(self, path: str):
        best = None
        for prefix, name in self._routes.items():
            if path == prefix or path.startswith(
                    prefix.rstrip("/") + "/") or prefix == "/":
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, name)
        return best

    # ---- admission control / shedding ----

    def _note_latency(self, name: str, seconds: float) -> None:
        samples = self._latency.setdefault(name, [])
        samples.append(seconds)
        if len(samples) > LATENCY_SAMPLES:
            del samples[:len(samples) - LATENCY_SAMPLES]
        if self._metrics is not None:
            self._metrics["latency"].observe(
                seconds, labels={"deployment": name})

    def _latency_stats(self, name: str):
        """(p99, mean) of the rolling reservoir, or (None, None)."""
        samples = self._latency.get(name)
        if not samples:
            return None, None
        s = sorted(samples)
        return metrics_mod.percentile(s, 0.99), sum(s) / len(s)

    def _set_queue_gauges(self, name: str, rs: _AsyncReplicaSet) -> None:
        if self._metrics is None:
            return
        labels = {"deployment": name, "router": f"proxy:{self._port}"}
        self._metrics["inflight"].set(rs.inflight_count(), labels=labels)
        self._metrics["queue_depth"].set(rs.num_waiting, labels=labels)

    def _admission_check(self, name: str,
                         rs: _AsyncReplicaSet) -> Optional[int]:
        """``None`` = admit; else the Retry-After hint (seconds) for a
        shed. Two triggers, both sized off the deployment's dispatch
        capacity (replicas x max_concurrent_queries):

        * queue budget — waiting + in-flight past capacity x
          ``serve_shed_queue_factor``: the backlog alone already costs
          more latency than the budget allows;
        * SLO budget — every slot busy AND observed p99 past
          ``serve_shed_p99_budget_s`` (when configured): degraded
          tails shed before the backlog doubles the damage.
        """
        cap = rs.capacity()
        if cap <= 0:
            return None  # bootstrap race: handled by the caller's wait
        queued = rs.inflight_count() + rs.num_waiting
        p99, mean = self._latency_stats(name)
        over_queue = queued >= cap * self._shed_queue_factor
        over_slo = (self._shed_p99_budget_s > 0 and queued >= cap
                    and p99 is not None
                    and p99 > self._shed_p99_budget_s)
        if not over_queue and not over_slo:
            return None
        # Retry-After scales with how long the current backlog needs
        # to drain; the floor covers the cold no-samples case.
        hint = self._retry_after_floor_s
        if mean:
            hint = max(hint, queued * mean / cap)
        return max(1, int(min(30.0, hint)))

    def _shed(self, name: str) -> None:
        self.num_shed += 1
        if self._metrics is not None:
            self._metrics["shed"].inc(labels={"deployment": name})

    # ---- zero-copy ingress ----

    async def _ingest_body_shm(self, body: bytes):
        """Write the body into shm via the AllocSegment lease path.
        ``put_async`` serializes on this thread (bytes are META_RAW —
        no copy) and schedules the segment fill + seal on the core IO
        loop, so the proxy loop keeps accepting while a huge body
        lands. Returns the ObjectRef, or None to fall back to the
        inline lane (no core worker yet, store full, ...)."""
        try:
            import ray_tpu.worker as worker_mod
            w = worker_mod.global_worker
            if w is None or w.core is None:
                return None
            ref, done = w.core.put_async(body)
        except Exception as e:  # noqa: BLE001 — ingress must degrade,
            # not fail: the inline lane is always correct
            logger.warning("shm ingress unavailable (%r); body inline", e)
            return None
        try:
            await asyncio.wrap_future(done)
        except Exception as e:  # noqa: BLE001 — seal failed (store
            # full): drop our ref, ship inline
            logger.warning("shm ingress seal failed (%r); body inline", e)
            return None
        self.num_ingress_shm += 1
        if self._metrics is not None:
            self._metrics["ingress_shm"].inc()
        return ref

    # ---- HTTP plumbing ----

    async def _handle_connection(self, reader: asyncio.StreamReader,
                                 writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                try:
                    request_line = await asyncio.wait_for(
                        reader.readline(), timeout=IDLE_KEEPALIVE_S)
                except asyncio.TimeoutError:
                    break
                if not request_line or request_line in (b"\r\n", b"\n"):
                    break
                keep_alive = await self._handle_request(
                    request_line, reader, writer)
                if not keep_alive:
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        except Exception:  # noqa: BLE001 — one bad conn can't kill the server
            logger.exception("connection handler error")
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:  # noqa: BLE001
                pass

    async def _handle_request(self, request_line: bytes,
                              reader: asyncio.StreamReader,
                              writer: asyncio.StreamWriter) -> bool:
        try:
            parts = request_line.decode("latin-1").strip().split()
            if len(parts) != 3:
                await self._write_response(
                    writer, HTTPResponse(b"bad request line", status=400),
                    keep_alive=False)
                return False
            method, target, http_version = parts
            headers: Dict[str, str] = {}
            total = 0
            while True:
                line = await reader.readline()
                total += len(line)
                if total > MAX_HEADER_BYTES:
                    raise ValueError("headers too large")
                if line in (b"\r\n", b"\n", b""):
                    break
                key, _, value = line.decode("latin-1").partition(":")
                headers[key.strip().lower()] = value.strip()
            if "chunked" in headers.get("transfer-encoding", "").lower():
                # Not implemented; misreading the chunk stream would
                # desynchronize keep-alive framing.
                await self._write_response(
                    writer,
                    HTTPResponse(b"chunked requests not supported; "
                                 b"send Content-Length", status=411),
                    keep_alive=False)
                return False
            length = int(headers.get("content-length", "0") or "0")
            if length > MAX_BODY_BYTES:
                raise ValueError("body too large")
            body = await reader.readexactly(length) if length else b""
        except (ValueError, asyncio.IncompleteReadError):
            await self._write_response(
                writer, HTTPResponse(b"malformed request", status=400),
                keep_alive=False)
            return False

        keep_alive = (http_version.upper() != "HTTP/1.0"
                      and headers.get("connection", "").lower() != "close")
        url = urlsplit(target)
        path = unquote(url.path)
        self.num_requests += 1

        if path == "/-/routes":
            await self._write_response(
                writer, _encode_result(self._routes), keep_alive)
            return keep_alive
        if path == "/-/healthz":
            await self._write_response(
                writer, _encode_result("ok"), keep_alive)
            return keep_alive

        match = self._match_route(path)
        if match is None:
            await self._write_response(
                writer,
                HTTPResponse(f"no deployment routes {path!r}".encode(),
                             status=404), keep_alive)
            return keep_alive
        prefix, name = match
        # Roll/startup race: the route table announces a deployment a
        # beat before its replica set finishes bootstrapping — give the
        # membership push a moment before failing the request.
        loop = asyncio.get_running_loop()
        deadline = loop.time() + 5.0
        rs = self._sets.get(name)
        while rs is None or not rs.replicas:
            if not await self._wait_change(deadline):
                rs = self._sets.get(name)
                break
            rs = self._sets.get(name)
        if rs is None or not rs.replicas:
            await self._write_response(
                writer, HTTPResponse(b"no replicas available", status=503),
                keep_alive)
            return keep_alive

        # Admission controller: shed at the door BEFORE touching shm or
        # a replica slot — a shed must cost microseconds, not queueing.
        retry_after = self._admission_check(name, rs)
        if retry_after is not None:
            self._shed(name)
            self._set_queue_gauges(name, rs)
            await self._write_response(
                writer,
                HTTPResponse(b"overloaded; retry later", status=503,
                             headers={"retry-after": str(retry_after)}),
                keep_alive)
            return keep_alive

        body_ref = None
        if (self._ingress_threshold > 0
                and len(body) >= self._ingress_threshold):
            body_ref = await self._ingest_body_shm(body)
            if body_ref is not None:
                body = b""  # the bytes ride shm, not the pickle lane

        request = HTTPRequest(method, path, prefix, url.query, headers,
                              body, body_ref=body_ref)
        if self._metrics is not None:
            self._metrics["requests"].inc(labels={"deployment": name})
        self._set_queue_gauges(name, rs)
        span_cm = (tracing.trace(
            f"http {method} {path}", kind="server",
            attributes={"deployment": name,
                        "shm_ingress": body_ref is not None})
            if tracing.enabled() else contextlib.nullcontext())
        t0 = time.perf_counter()
        try:
            with span_cm as span:
                try:
                    result = await rs.assign(
                        "__call__", (request,), {},
                        idempotent=method in ("GET", "HEAD", "OPTIONS"))
                    response = _encode_result(result)
                except exc.ServeOverloadedError as e:
                    # replica-side shed (queue cap / decode scheduler);
                    # isinstance holds through as_instanceof_cause and
                    # retry_after_s rides the grafted cause attributes
                    self._shed(name)
                    response = HTTPResponse(
                        str(e).encode() or b"overloaded; retry later",
                        status=503,
                        headers={"retry-after": str(max(1, int(
                            getattr(e, "retry_after_s", 1.0))))})
                except Exception:  # noqa: BLE001 — user code / replica
                    # failure
                    self.num_errors += 1
                    # tracebacks stay server-side: the ingress surface
                    # must not leak file paths / code structure to
                    # arbitrary clients
                    tb = traceback.format_exc()
                    logger.error("request to %s failed:\n%s", path, tb)
                    if os.environ.get("RAY_TPU_SERVE_DEBUG"):
                        body = tb.encode()
                    else:
                        body = b"internal error (see serve logs)"
                    response = HTTPResponse(body, status=500,
                                            content_type="text/plain")
                if span is not None:
                    span.attributes["status"] = response.status
        finally:
            self._note_latency(name, time.perf_counter() - t0)
            self._set_queue_gauges(name, rs)
        await self._write_response(writer, response, keep_alive)
        return keep_alive

    async def _write_response(self, writer: asyncio.StreamWriter,
                              response: HTTPResponse,
                              keep_alive: bool) -> None:
        body = response.body
        if isinstance(body, str):
            body = body.encode()
        elif not isinstance(body, (bytes, bytearray, memoryview)):
            body = json.dumps(body, default=str).encode()
        reason = _REASONS.get(response.status, "Unknown")
        headers = {
            "content-type": response.content_type or "text/plain",
            "server": "ray-tpu-serve",
        }
        headers.update({k.lower(): v for k, v in response.headers.items()})
        # Framing headers are the proxy's, always: a user-supplied
        # Content-Length would desynchronize keep-alive framing.
        headers["content-length"] = str(len(body))
        headers["connection"] = "keep-alive" if keep_alive else "close"
        headers.pop("transfer-encoding", None)
        head = [f"HTTP/1.1 {response.status} {reason}"]
        head += [f"{k}: {v}" for k, v in headers.items()]
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode("latin-1"))
        writer.write(bytes(body))
        await writer.drain()

    async def stats(self) -> dict:
        deployments = {}
        for name, rs in self._sets.items():
            p99, mean = self._latency_stats(name)
            deployments[name] = {
                "replicas": len(rs.replicas),
                "capacity": rs.capacity(),
                "inflight": rs.inflight_count(),
                "queue_depth": rs.num_waiting,
                "p99_s": p99,
                "mean_s": mean,
            }
        return {"num_requests": self.num_requests,
                "num_errors": self.num_errors,
                "num_shed": self.num_shed,
                "num_ingress_shm": self.num_ingress_shm,
                "routes": dict(self._routes),
                "deployments": deployments,
                "address": f"{self._host}:{self._port}"}
