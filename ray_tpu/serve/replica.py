"""Replica actor: hosts one copy of a deployment's callable.

Parity target: the reference's RayServeWrappedReplica / RayServeReplica
(reference: python/ray/serve/backend_worker.py). An async actor so many
requests interleave up to the deployment's max_concurrent_queries (the
hard cap is enforced caller-side by the ReplicaSet; the replica-side
counter exists for draining).
"""

from __future__ import annotations

import asyncio
import inspect
from typing import Any


class Replica:
    """Generic wrapper instantiated by the controller for every replica."""

    def __init__(self, callable_def: Any, init_args: tuple,
                 init_kwargs: dict):
        if inspect.isclass(callable_def):
            self._obj = callable_def(*init_args, **init_kwargs)
        else:
            self._obj = callable_def  # plain function deployment
        self._inflight = 0
        self._draining = False

    async def ready(self) -> str:
        """Health check the controller awaits before routing traffic."""
        return "ok"

    async def stats(self) -> dict:
        """Load signal for the controller's autoscaler (reference:
        autoscaling_policy.py scale() consumes per-router queue lens —
        here the replica self-reports concurrency)."""
        return {"inflight": self._inflight}

    async def handle_request(self, method: str, args: tuple,
                             kwargs: dict):
        # Note: a DRAINING replica still serves — a router that raced
        # the rolling update may send a few stragglers after the
        # controller switched the snapshot, and failing them would
        # surface errors for requests the user did nothing wrong with.
        # Drain completion just waits a little longer.
        self._inflight += 1
        try:
            # Class deployments: bound-method lookup; function
            # deployments: the function's own __call__.
            fn = getattr(self._obj, method)
            result = fn(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = await result
            return result
        finally:
            self._inflight -= 1

    async def drain(self) -> int:
        """Stop accepting work, wait for in-flight requests to finish.

        Returns the number of requests that were in flight when the
        drain began (for controller bookkeeping/tests).
        """
        self._draining = True
        started_with = self._inflight
        while self._inflight > 0:
            await asyncio.sleep(0.005)
        return started_with

    async def reconfigure(self, user_config: Any) -> None:
        """Push a new user_config without restarting the replica."""
        fn = getattr(self._obj, "reconfigure", None)
        if fn is not None:
            result = fn(user_config)
            if inspect.iscoroutine(result):
                await result
