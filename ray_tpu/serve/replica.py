"""Replica actor: hosts one copy of a deployment's callable.

Parity target: the reference's RayServeWrappedReplica / RayServeReplica
(reference: python/ray/serve/backend_worker.py). An async actor so many
requests interleave up to the deployment's max_concurrent_queries (the
hard cap is enforced caller-side by the ReplicaSet; the replica-side
counter exists for draining — plus a hard overload cap: multiple
routers each honor max_concurrent_queries LOCALLY, so their sum can
oversubscribe one replica. Past
``max_concurrent_queries + serve_max_queue_depth`` concurrent requests
the replica sheds with the typed
:class:`~ray_tpu.exceptions.ServeOverloadedError`, which the proxy
renders as ``503 + Retry-After``).

Zero-copy ingress lands here too: an :class:`HTTPRequest` carrying
``body_ref`` (shm ObjectRef) has its body resolved on the replica's
event loop before user code runs — deployment code always sees
``request.body`` as plain bytes.
"""

from __future__ import annotations

import asyncio
import inspect
from typing import Any

from ray_tpu.exceptions import ServeOverloadedError


class Replica:
    """Generic wrapper instantiated by the controller for every replica."""

    def __init__(self, callable_def: Any, init_args: tuple,
                 init_kwargs: dict, max_concurrent_queries: int = 100):
        if inspect.isclass(callable_def):
            self._obj = callable_def(*init_args, **init_kwargs)
        else:
            self._obj = callable_def  # plain function deployment
        self._inflight = 0
        self._shed = 0
        self._draining = False
        queue_depth = 16
        retry_after = 1.0
        try:
            import ray_tpu.worker as worker_mod
            cfg = worker_mod.global_worker.core.config
            queue_depth = int(cfg.serve_max_queue_depth)
            retry_after = float(cfg.serve_retry_after_s)
        except Exception:  # noqa: BLE001 — unit harness without a
            pass           # worker: keep the defaults
        self._max_inflight = int(max_concurrent_queries) + max(0, queue_depth)
        self._retry_after_s = max(0.0, retry_after)

    async def ready(self) -> str:
        """Health check the controller awaits before routing traffic."""
        return "ok"

    async def stats(self) -> dict:
        """Load signal for the controller's autoscaler (reference:
        autoscaling_policy.py scale() consumes per-router queue lens —
        here the replica self-reports concurrency). A deployment
        hosting a continuous-batching decode loop exposes it as
        ``self.decode_scheduler``; its occupancy/queue counters ride
        along for /api/serve."""
        out = {"inflight": self._inflight, "shed": self._shed}
        sched = getattr(self._obj, "decode_scheduler", None)
        if sched is not None:
            try:
                out["decode"] = sched.stats()
            except Exception:  # noqa: BLE001 — stats must never fail
                pass           # the autoscaler poll
        return out

    async def handle_request(self, method: str, args: tuple,
                             kwargs: dict):
        # Note: a DRAINING replica still serves — a router that raced
        # the rolling update may send a few stragglers after the
        # controller switched the snapshot, and failing them would
        # surface errors for requests the user did nothing wrong with.
        # Drain completion just waits a little longer.
        if self._inflight >= self._max_inflight:
            self._shed += 1
            raise ServeOverloadedError(
                f"replica at capacity ({self._inflight} in flight, cap "
                f"{self._max_inflight})",
                retry_after_s=self._retry_after_s)
        self._inflight += 1
        try:
            # Zero-copy ingress: resolve a by-reference body before the
            # user's callable sees the request.
            for a in args:
                ref = getattr(a, "body_ref", None)
                if ref is not None and hasattr(a, "body"):
                    a.body = bytes(await ref.as_future())
                    a.body_ref = None  # borrow ends; shm seg can free
            # Class deployments: bound-method lookup; function
            # deployments: the function's own __call__.
            fn = getattr(self._obj, method)
            result = fn(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = await result
            return result
        finally:
            self._inflight -= 1

    async def drain(self) -> int:
        """Stop accepting work, wait for in-flight requests to finish.

        Returns the number of requests that were in flight when the
        drain began (for controller bookkeeping/tests).
        """
        self._draining = True
        started_with = self._inflight
        while self._inflight > 0:
            await asyncio.sleep(0.005)
        sched = getattr(self._obj, "decode_scheduler", None)
        if sched is not None:
            try:
                await sched.aclose()
            except Exception:  # noqa: BLE001 — a wedged decode loop
                pass           # must not block the roll
        return started_with

    async def reconfigure(self, user_config: Any) -> None:
        """Push a new user_config without restarting the replica."""
        fn = getattr(self._obj, "reconfigure", None)
        if fn is not None:
            result = fn(user_config)
            if inspect.iscoroutine(result):
                await result
