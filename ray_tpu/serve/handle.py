"""DeploymentHandle: the caller-side entry point for serve queries.

Parity target: the reference's RayServeHandle
(reference: python/ray/serve/handle.py:44). ``handle.remote(...)``
returns an ObjectRef (compose with the rest of the task graph);
membership updates arrive over the controller's long-poll channel.
"""

from __future__ import annotations

import threading

import ray_tpu
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu.serve.long_poll import LongPollClient
from ray_tpu.serve.controller import SNAPSHOT_KEY
from ray_tpu.serve.router import ReplicaSet


class DeploymentHandle:
    def __init__(self, controller, deployment_name: str,
                 method_name: str = "__call__"):
        self._controller = controller
        self.deployment_name = deployment_name
        self._method = method_name
        self._replica_set = ReplicaSet(deployment_name)
        # Bootstrap synchronously so the first .remote() doesn't race
        # the long-poll thread's first listen.
        snapshot = ray_tpu.get(
            controller.get_replica_snapshot.remote(deployment_name))
        self._replica_set.update_membership(snapshot)
        self._long_poll = LongPollClient(
            controller,
            {SNAPSHOT_KEY.format(name=deployment_name):
             self._replica_set.update_membership})
        # Janitor: drop completed bookkeeping refs after traffic
        # quiesces so results aren't pinned in the object store. The
        # thread must NOT hold a reference to this handle (that would
        # keep __del__ from ever firing) — it closes over the replica
        # set and the stop event only.
        self._closed = threading.Event()
        self._janitor = threading.Thread(
            target=_janitor_loop, args=(self._replica_set, self._closed),
            name="serve-handle-janitor", daemon=True)
        self._janitor.start()

    def remote(self, *args, **kwargs) -> ObjectRef:
        """Route one query; blocks only when every replica is at its
        max_concurrent_queries cap (backpressure)."""
        return self._replica_set.assign(self._method, args, kwargs)

    def __del__(self):  # stop the helper threads with the handle
        try:
            self._long_poll.stop()
            self._closed.set()
        except Exception:  # noqa: BLE001 — interpreter shutdown
            pass

    def options(self, method_name: str) -> "DeploymentHandle":
        """A sibling handle invoking a different method of the class."""
        return DeploymentHandle(self._controller, self.deployment_name,
                                method_name=method_name)

    def __getattr__(self, name: str) -> "_MethodCaller":
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodCaller(self, name)

    def __repr__(self) -> str:
        return (f"DeploymentHandle(deployment="
                f"{self.deployment_name!r}, method={self._method!r})")


def _janitor_loop(replica_set: ReplicaSet,
                  closed: threading.Event) -> None:
    while not closed.wait(1.0):
        try:
            if replica_set.num_queued():
                replica_set.prune()
        except Exception:  # noqa: BLE001 — shutdown races
            pass


class _MethodCaller:
    """``handle.other_method.remote(...)`` sugar."""

    def __init__(self, handle: DeploymentHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs) -> ObjectRef:
        return self._handle._replica_set.assign(
            self._method, args, kwargs)
