"""Public exception types raised by the runtime.

Parity targets (reference: python/ray/exceptions.py): RayError,
RayTaskError, WorkerCrashedError, ActorDiedError / RayActorError,
ObjectLostError, GetTimeoutError, TaskCancelledError, ObjectStoreFullError,
RuntimeEnvSetupError.
"""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class RayTaskError(RayTpuError):
    """A task raised an exception on a remote worker.

    The remote traceback is captured as a string and re-raised at every
    ``get`` of any object whose lineage includes the failed task.
    """

    def __init__(self, function_name: str = "", traceback_str: str = "",
                 cause: Exception | None = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(function_name, traceback_str)

    def __str__(self):
        msg = f"task {self.function_name} failed"
        if self.traceback_str:
            msg += f"\n{self.traceback_str}"
        return msg

    def as_instanceof_cause(self) -> Exception:
        """Return an exception that is also an instance of the cause's type,
        so ``except UserError`` works across process boundaries."""
        cause = self.cause
        if cause is None or isinstance(cause, RayTaskError):
            return self
        cause_cls = type(cause)
        if cause_cls is RayTaskError:
            return self
        try:
            derived = type(
                "RayTaskError(" + cause_cls.__name__ + ")",
                (RayTaskError, cause_cls),
                {"__init__": RayTaskError.__init__, "__str__": RayTaskError.__str__},
            )
            err = derived(self.function_name, self.traceback_str, cause)
            return err
        except TypeError:
            return self


class TaskCancelledError(RayTpuError):
    """The task was cancelled before or during execution."""


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class ActorDiedError(RayTpuError):
    """The actor is dead: creation failed, it exhausted restarts, or its
    node/worker died and max_restarts was 0."""

    def __init__(self, reason: str = "actor died"):
        self.reason = reason
        super().__init__(reason)


# Alias matching the reference's name.
RayActorError = ActorDiedError


class ObjectLostError(RayTpuError):
    """All copies of the object were lost and reconstruction failed or was
    disabled."""

    def __init__(self, object_id_hex: str = "", reason: str = ""):
        self.object_id_hex = object_id_hex
        self.reason = reason
        super().__init__(f"object {object_id_hex} lost: {reason}")


class ObjectStoreFullError(RayTpuError):
    """The shared-memory object store cannot fit the object even after
    eviction and spilling."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get`` timed out before the object was available."""


class RuntimeEnvSetupError(RayTpuError):
    """Setting up the task/actor runtime environment failed."""


class RaySystemError(RayTpuError):
    """Internal system failure (e.g. a control-plane process died)."""


class PendingCallsLimitExceeded(RayTpuError):
    """Actor max_pending_calls exceeded."""


class AsyncioActorExit(RayTpuError):
    """Raised inside an async actor to exit it gracefully."""
