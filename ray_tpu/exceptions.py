"""Public exception types raised by the runtime.

Parity targets (reference: python/ray/exceptions.py): RayError,
RayTaskError, WorkerCrashedError, ActorDiedError / RayActorError,
ObjectLostError, GetTimeoutError, TaskCancelledError, ObjectStoreFullError,
RuntimeEnvSetupError.
"""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class RayTaskError(RayTpuError):
    """A task raised an exception on a remote worker.

    The remote traceback is captured as a string and re-raised at every
    ``get`` of any object whose lineage includes the failed task.
    """

    def __init__(self, function_name: str = "", traceback_str: str = "",
                 cause: Exception | None = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        # Exception directly, NOT super(): as_instanceof_cause builds a
        # (RayTaskError, cause_cls) diamond, and the cooperative chain
        # would feed these two positional strings into cause_cls.__init__
        # (ValueError from dict("traceback...") for cause-bearing types).
        Exception.__init__(self, function_name, traceback_str)

    def __str__(self):
        msg = f"task {self.function_name} failed"
        if self.traceback_str:
            msg += f"\n{self.traceback_str}"
        return msg

    def as_instanceof_cause(self) -> Exception:
        """Return an exception that is also an instance of the cause's type,
        so ``except UserError`` works across process boundaries."""
        cause = self.cause
        if cause is None or isinstance(cause, RayTaskError):
            return self
        cause_cls = type(cause)
        if cause_cls is RayTaskError:
            return self
        try:
            derived = type(
                "RayTaskError(" + cause_cls.__name__ + ")",
                (RayTaskError, cause_cls),
                {"__init__": RayTaskError.__init__, "__str__": RayTaskError.__str__},
            )
            err = derived(self.function_name, self.traceback_str, cause)
            # The wrapper IS an instance of the cause's type, so it
            # must answer for its attributes too (cause_info /
            # cause_kind / object_id_hex ...): cause_cls.__init__ never
            # ran on it, so graft the cause's state across.
            for k, v in vars(cause).items():
                err.__dict__.setdefault(k, v)
            return err
        except TypeError:
            return self


class TaskCancelledError(RayTpuError):
    """The task was cancelled before or during execution."""


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


def _format_cause(cause: dict) -> str:
    """Render a structured death cause for the message tail:
    ``[WORKER_DIED node=ab12cd worker=ef34..]``."""
    if not cause:
        return ""
    parts = [str(cause.get("kind", "UNKNOWN"))]
    for key, label in (("node_id", "node"), ("worker_id", "worker"),
                       ("last_failure", "after"), ("restarts", "restarts")):
        v = cause.get(key)
        if v not in (None, "", 0) or (key == "restarts" and v == 0 and
                                      cause.get("kind") ==
                                      "RESTARTS_EXHAUSTED"):
            parts.append(f"{label}={v}")
    return " [" + " ".join(parts) + "]"


class ActorDiedError(RayTpuError):
    """The actor is dead: creation failed, it exhausted restarts, or its
    node/worker died and max_restarts was 0.

    ``cause`` is the structured death cause recorded by the GCS actor
    table (and stamped into the task-event FAILED record shown by
    ``ray_tpu.state.list_tasks()``)::

        {"kind": "NODE_DIED" | "WORKER_DIED" | "RESTARTS_EXHAUSTED"
                 | "CREATION_FAILED" | "ACTOR_EXITED" | "KILLED",
         "node_id": hex, "worker_id": hex, "message": str,
         "restarts": int, "max_restarts": int,
         "last_failure": str}   # RESTARTS_EXHAUSTED: the final straw
    """

    def __init__(self, reason: str = "actor died", cause: dict | None = None):
        self.reason = reason
        self.cause_info = dict(cause or {})
        super().__init__(reason + _format_cause(self.cause_info))

    @property
    def cause_kind(self) -> str:
        return str(self.cause_info.get("kind", ""))


# Alias matching the reference's name.
RayActorError = ActorDiedError


class ObjectLostError(RayTpuError):
    """All copies of the object were lost and reconstruction failed or was
    disabled.

    ``cause`` mirrors :class:`ActorDiedError`'s structured death cause,
    with object-plane kinds: ``NO_OWNER`` / ``OWNER_UNREACHABLE`` /
    ``OWNER_RELEASED`` / ``PULL_FAILED`` / ``RECOVERY_FAILED``."""

    def __init__(self, object_id_hex: str = "", reason: str = "",
                 cause: dict | None = None):
        self.object_id_hex = object_id_hex
        self.reason = reason
        self.cause_info = dict(cause or {})
        super().__init__(f"object {object_id_hex} lost: {reason}"
                         + _format_cause(self.cause_info))

    @property
    def cause_kind(self) -> str:
        return str(self.cause_info.get("kind", ""))


class OutOfMemoryError(RayTpuError):
    """The node memory watchdog killed the worker executing the task.

    Raised at ``get`` once the task's dedicated OOM retry budget
    (``task_oom_retries``) is exhausted — or immediately for a
    non-retriable task (``max_retries=0``). Unlike a kernel OOM kill,
    this is an *ordered* eviction: store spill/evict pressure relief ran
    first, the raylet and GCS survive, and the kill is retriable.

    ``cause`` mirrors :class:`ActorDiedError`'s structured death cause::

        {"kind": "WORKER_OOM", "node_id": hex, "worker_id": hex,
         "usage_fraction": float, "threshold": float,
         "workers_rss": {worker_id12: rss_bytes, ...},  # at kill time
         "message": str}
    """

    def __init__(self, reason: str = "worker killed by the node memory "
                 "watchdog", cause: dict | None = None):
        self.reason = reason
        self.cause_info = dict(cause or {})
        super().__init__(reason + _format_cause(self.cause_info))

    @property
    def cause_kind(self) -> str:
        return str(self.cause_info.get("kind", ""))


class ObjectStoreFullError(RayTpuError):
    """The shared-memory object store cannot fit the object even after
    eviction and spilling."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get`` timed out before the object was available."""


class RuntimeEnvSetupError(RayTpuError):
    """Setting up the task/actor runtime environment failed."""


class RaySystemError(RayTpuError):
    """Internal system failure (e.g. a control-plane process died)."""


class PendingCallsLimitExceeded(RayTpuError):
    """Actor max_pending_calls exceeded."""


class ServeOverloadedError(RayTpuError):
    """Serve shed the request at admission instead of queueing it.

    The serving plane's typed analog of the lease protocol's
    ``retry_later`` backpressure verdict: a replica's queue-depth cap or
    the proxy's SLO budget (queue depth x observed latency) was
    exceeded, so the request was refused AT THE DOOR — the in-flight
    decode batch keeps its cadence instead of collapsing under a
    backlog it can never drain. ``retry_after_s`` is the server's
    backoff hint; the HTTP proxy renders this error as
    ``503 Service Unavailable`` with a ``Retry-After`` header.
    """

    def __init__(self, reason: str = "serve overloaded",
                 retry_after_s: float = 1.0):
        self.reason = reason
        self.retry_after_s = float(retry_after_s)
        super().__init__(reason)


class AsyncioActorExit(RayTpuError):
    """Raised inside an async actor to exit it gracefully."""


class GangPlacementError(RayTpuError):
    """An all-or-nothing SPMD gang lease could not be satisfied.

    Raised when the home raylet's booking round (RequestGangLease) came
    back short after every configured retry — no partial gang is ever
    adopted, so nothing was leased when this surfaces."""


class GangBrokenError(RayTpuError):
    """The SPMD gang lost a member and the incarnation is invalid.

    A dead member invalidates the WHOLE step (epoch fence, like actor
    incarnations): in-flight step tasks fail with
    :class:`WorkerCrashedError`, and further ``run()`` calls raise this
    until ``reform()`` books a fresh incarnation at epoch+1."""


class CollectiveError(RayTpuError):
    """A DistributedArray ring collective failed mid-flight.

    Raised by the driver-side ring engine when any rank's RingInit /
    RingStep / RingFinish round fails (peer raylet death, data-plane
    failure, store capacity): every surviving member was sent RingAbort
    first, so no partial accumulator segment outlives this. The
    collective verbs catch it and take the fold/naive fallback; it
    surfaces to user code only when every fallback is exhausted."""
