"""Runtime environments: per-task/actor env_vars + working_dir packages.

Parity: the reference's runtime-env plane —
``python/ray/_private/runtime_env/working_dir.py`` (zip packages keyed by
content hash, shipped through GCS KV), realized per node by the dashboard
agent (``src/ray/raylet/agent_manager.h:67`` CreateRuntimeEnv), with
workers reused by env hash (``src/ray/raylet/worker_pool.h:135``).

TPU-native redesign: there is no per-node agent process. The package
travels through the GCS KV (the only blob plane every node already
reaches), and the *worker* realizes it lazily — download, extract into the
session dir keyed by content hash, activate via ``sys.path`` + cwd — the
first time a task carrying that env executes there. Extraction is
cross-process safe (atomic rename) so many workers on a node share one
materialized copy. The raylet's worker pool prefers leasing a worker that
last ran the same env hash, so warm workers skip re-activation.
"""

from __future__ import annotations

import contextlib
import hashlib
import io
import json
import logging
import os
import sys
import tempfile
import zipfile
from typing import Any, Callable, Dict, Optional

logger = logging.getLogger(__name__)

PKG_KEY_PREFIX = b"rtpu:pkg:"
WHEEL_KEY_PREFIX = b"rtpu:whl:"
JOB_ENV_KEY_PREFIX = b"rtpu:job_env:"
# Parked module trees per package dir (see activate()): makes env-hash
# worker reuse skip re-imports.
_module_cache: Dict[str, Dict[str, Any]] = {}
URI_SCHEME = "pkg:"
WHEEL_URI_SCHEME = "kvwhl:"
SUPPORTED_KEYS = {"env_vars", "working_dir", "working_dir_uri", "pip",
                  "conda"}
MAX_PACKAGE_BYTES = 512 * 1024 * 1024
_DEFAULT_EXCLUDES = {"__pycache__", ".git", ".venv", "node_modules"}


def validate_runtime_env(runtime_env: Dict[str, Any]) -> None:
    unknown = set(runtime_env) - SUPPORTED_KEYS
    if unknown:
        raise ValueError(
            f"unsupported runtime_env keys {sorted(unknown)}; "
            f"supported: {sorted(SUPPORTED_KEYS)}")
    env_vars = runtime_env.get("env_vars") or {}
    if not isinstance(env_vars, dict):
        raise ValueError("runtime_env['env_vars'] must be a dict")
    pip = runtime_env.get("pip")
    if pip is not None and not isinstance(pip, (list, tuple, str)):
        raise ValueError(
            "runtime_env['pip'] must be a list of requirement strings / "
            "local wheel paths, or a path to a requirements.txt")
    conda = runtime_env.get("conda")
    if conda is not None and not isinstance(conda, (dict, str)):
        raise ValueError(
            "runtime_env['conda'] must be an environment spec dict "
            "(environment.yml structure), a path to an "
            "environment.yml, or the name of an existing conda env")
    if conda is not None and pip is not None:
        raise ValueError(
            "runtime_env: specify either 'conda' or 'pip', not both "
            "(put pip deps inside the conda spec)")


def hash_runtime_env(runtime_env: Optional[Dict[str, Any]]) -> str:
    """Stable identity of a (prepared) runtime env, for worker-pool
    matching (reference: worker_pool runtime_env_hash)."""
    if not runtime_env:
        return ""
    return hashlib.sha1(
        json.dumps(runtime_env, sort_keys=True, default=str).encode()
    ).hexdigest()[:16]


# ---------------------------------------------------------------- packaging


def package_working_dir(path: str,
                        excludes: Optional[set] = None) -> tuple:
    """Deterministically zip a directory; returns (zip_bytes, pkg_hash).

    The hash covers file names + contents, so identical trees dedupe to
    one KV entry regardless of mtimes (reference: _get_local_path /
    package hashing in runtime_env/packaging)."""
    path = os.path.abspath(os.path.expanduser(path))
    if not os.path.isdir(path):
        raise ValueError(f"working_dir {path!r} is not a directory")
    excludes = (excludes or set()) | _DEFAULT_EXCLUDES
    entries = []
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if d not in excludes)
        for fname in sorted(files):
            full = os.path.join(root, fname)
            rel = os.path.relpath(full, path)
            entries.append((rel, full))
    hasher = hashlib.sha1()
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for rel, full in entries:
            with open(full, "rb") as f:
                data = f.read()
            hasher.update(rel.encode())
            hasher.update(b"\0")
            hasher.update(data)
            # Fixed date → byte-identical archives for identical trees.
            info = zipfile.ZipInfo(rel, date_time=(2020, 1, 1, 0, 0, 0))
            info.external_attr = 0o644 << 16
            zf.writestr(info, data)
    blob = buf.getvalue()
    if len(blob) > MAX_PACKAGE_BYTES:
        raise ValueError(
            f"working_dir package is {len(blob)} bytes; "
            f"limit {MAX_PACKAGE_BYTES}")
    return blob, hasher.hexdigest()[:20]


def _dir_signature(path: str) -> str:
    """Cheap change detector (names + sizes + mtimes) so a driver that
    edits its working_dir between submissions re-packages, while
    unchanged trees skip the full content walk."""
    h = hashlib.sha1()
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if d not in _DEFAULT_EXCLUDES)
        for fname in sorted(files):
            full = os.path.join(root, fname)
            try:
                st = os.stat(full)
            except OSError:
                continue
            h.update(f"{os.path.relpath(full, path)}:"
                     f"{st.st_size}:{st.st_mtime_ns}".encode())
    return h.hexdigest()


def prepare_runtime_env(runtime_env: Optional[Dict[str, Any]],
                        kv_get: Callable[[bytes], Optional[bytes]],
                        kv_put: Callable[[bytes, bytes], None],
                        uploaded_cache: Dict[str, tuple]) -> Optional[Dict]:
    """Driver-side: validate and rewrite ``working_dir`` (a local path)
    into ``working_dir_uri`` (a content-hash URI), uploading the package
    to GCS KV if this driver hasn't already (cache invalidated when the
    directory changes)."""
    if not runtime_env:
        return runtime_env
    validate_runtime_env(runtime_env)
    wd = runtime_env.get("working_dir")
    pip = runtime_env.get("pip")
    conda = runtime_env.get("conda")
    if not wd and not pip and not isinstance(conda, str):
        return runtime_env
    out = {k: v for k, v in runtime_env.items() if k != "working_dir"}
    if wd:
        abspath = os.path.abspath(os.path.expanduser(wd))
        sig = _dir_signature(abspath)
        cached = uploaded_cache.get(abspath)
        if cached is not None and cached[0] == sig:
            out["working_dir_uri"] = cached[1]
        else:
            blob, pkg_hash = package_working_dir(wd)
            key = PKG_KEY_PREFIX + pkg_hash.encode()
            if kv_get(key) is None:
                kv_put(key, blob)
            uri = URI_SCHEME + pkg_hash
            uploaded_cache[abspath] = (sig, uri)
            out["working_dir_uri"] = uri
    if pip:
        out["pip"] = prepare_pip_entries(pip, kv_get, kv_put,
                                         uploaded_cache)
    conda = runtime_env.get("conda")
    if isinstance(conda, str) and conda.endswith((".yml", ".yaml")):
        # environment.yml path: ship its CONTENT so the env identity
        # is the spec, not a driver-local path (reference:
        # runtime_env/conda.py reads the file driver-side). Other
        # strings pass through: env names and prefix DIRECTORIES are
        # resolved node-side.
        with open(os.path.expanduser(conda)) as f:
            out["conda"] = {"__yaml__": f.read()}
    return out


def prepare_pip_entries(pip, kv_get, kv_put, cache=None) -> list:
    """Driver-side pip normalization (reference role:
    _private/runtime_env/conda.py + validation.py — dependencies become
    part of the env identity). A ``requirements.txt`` path expands to
    its lines; local wheel/sdist paths upload to the cluster KV by
    content hash and rewrite to ``kvwhl:<hash>:<filename>`` so a node
    with no index access (or no shared filesystem) can still install
    them; plain requirement strings pass through to pip untouched.
    Uploads cache by (size, mtime) signature — and a wheel deleted
    AFTER upload keeps resolving to its KV copy (only the cluster
    needs it now)."""
    if isinstance(pip, str):
        with open(os.path.expanduser(pip)) as f:
            entries = [ln.strip() for ln in f
                       if ln.strip() and not ln.strip().startswith("#")]
    else:
        entries = [str(e) for e in pip]
    out = []
    for e in entries:
        if not e.endswith((".whl", ".tar.gz", ".zip")):
            out.append(e)
            continue
        path = os.path.abspath(os.path.expanduser(e))
        cached = cache.get(path) if cache is not None else None
        if os.path.isfile(path):
            st = os.stat(path)
            sig = (st.st_size, st.st_mtime_ns)
            if cached is not None and cached[0] == sig:
                out.append(cached[1])
                continue
            with open(path, "rb") as f:
                blob = f.read()
            whl_hash = hashlib.sha1(blob).hexdigest()[:20]
            key = WHEEL_KEY_PREFIX + whl_hash.encode()
            if kv_get(key) is None:
                kv_put(key, blob)
            uri = f"{WHEEL_URI_SCHEME}{whl_hash}:{os.path.basename(path)}"
            if cache is not None:
                cache[path] = (sig, uri)
            out.append(uri)
        elif cached is not None:
            out.append(cached[1])  # uploaded earlier, source since deleted
        else:
            out.append(e)  # not a local file: hand to pip verbatim
    return out


# -------------------------------------------------------------- realization


def ensure_local_package(uri: str, base_dir: str,
                         kv_get: Callable[[bytes], Optional[bytes]]) -> str:
    """Worker-side: materialize a package dir for ``pkg:<hash>``; cached
    per node under ``<session>/runtime_resources/<hash>``. Concurrent
    extractions race benignly: extract to a temp dir, atomic rename."""
    if not uri.startswith(URI_SCHEME):
        raise ValueError(f"bad package uri {uri!r}")
    pkg_hash = uri[len(URI_SCHEME):]
    target = os.path.join(base_dir, "runtime_resources", pkg_hash)
    if os.path.isdir(target):
        return target
    blob = kv_get(PKG_KEY_PREFIX + pkg_hash.encode())
    if blob is None:
        raise RuntimeError(
            f"runtime_env package {uri} not found in the cluster KV "
            f"(was the driver's upload lost?)")
    os.makedirs(os.path.dirname(target), exist_ok=True)
    tmp = tempfile.mkdtemp(dir=os.path.dirname(target),
                           prefix=f".{pkg_hash}-")
    try:
        with zipfile.ZipFile(io.BytesIO(blob)) as zf:
            zf.extractall(tmp)
        try:
            os.rename(tmp, target)
        except OSError:
            pass  # somebody else won the race
    finally:
        if os.path.isdir(tmp) and os.path.isdir(target) and tmp != target:
            import shutil
            shutil.rmtree(tmp, ignore_errors=True)
    return target


def ensure_pip_env(entries, base_dir: str,
                   kv_get: Callable[[bytes], Optional[bytes]]) -> str:
    """Worker-side: materialize a pip environment directory for the
    normalized entry list; created ONCE per node under
    ``<session>/runtime_resources/pip/<hash>`` (atomic rename), shared
    by every worker on the node (reference role: per-node runtime-env
    agent materializing conda/pip envs, agent_manager.h:43 — here the
    first worker to need the env builds it).

    Isolation via ``pip install --target`` into the keyed dir (no venv
    spawn): activation is a sys.path prepend, so warm workers pay
    nothing and the host interpreter's site-packages stays untouched."""
    import subprocess
    import sys as _sys

    env_key = hashlib.sha1(
        json.dumps(list(entries)).encode()).hexdigest()[:16]
    target = os.path.join(base_dir, "runtime_resources", "pip", env_key)
    if os.path.isdir(target):
        return target
    os.makedirs(os.path.dirname(target), exist_ok=True)
    tmp = tempfile.mkdtemp(dir=os.path.dirname(target),
                           prefix=f".{env_key}-")
    wheel_dir = os.path.join(tmp, ".wheels")
    try:
        args = []
        all_kv = True
        for e in entries:
            if e.startswith(WHEEL_URI_SCHEME):
                whl_hash, _, fname = e[len(WHEEL_URI_SCHEME):].partition(":")
                blob = kv_get(WHEEL_KEY_PREFIX + whl_hash.encode())
                if blob is None:
                    raise RuntimeError(
                        f"pip wheel {fname} ({whl_hash}) not in cluster KV")
                os.makedirs(wheel_dir, exist_ok=True)
                local = os.path.join(wheel_dir, fname)
                with open(local, "wb") as f:
                    f.write(blob)
                args.append(local)
            else:
                args.append(e)
                all_kv = False
        cmd = [_sys.executable, "-m", "pip", "install", "--target", tmp,
               "--no-warn-script-location", "--disable-pip-version-check",
               "--quiet"]
        if all_kv:
            cmd += ["--no-index"]  # fully offline: every dep is a KV wheel
        try:
            r = subprocess.run(cmd + args, text=True, timeout=600,
                               stdout=subprocess.PIPE,
                               stderr=subprocess.STDOUT)
        except subprocess.TimeoutExpired:
            raise RuntimeError(
                "pip install for runtime_env timed out (600s)") from None
        if r.returncode != 0:
            raise RuntimeError(
                f"pip install for runtime_env failed "
                f"(exit {r.returncode}):\n{r.stdout[-2000:]}")
        import shutil
        shutil.rmtree(wheel_dir, ignore_errors=True)
        try:
            os.rename(tmp, target)
        except OSError:
            pass  # somebody else won the race
    finally:
        # rename moved tmp away on success; anything left (failed or
        # lost-race install) must not accumulate across task retries
        if os.path.isdir(tmp):
            import shutil
            shutil.rmtree(tmp, ignore_errors=True)
    return target


_named_env_cache: Dict[tuple, str] = {}  # (exe, env name) -> site-packages


def _conda_exe() -> Optional[str]:
    """The conda executable, or None (RAY_TPU_CONDA_EXE overrides the
    PATH lookup — tests point it at a stub; air-gapped nodes at a
    micromamba)."""
    import shutil

    exe = os.environ.get("RAY_TPU_CONDA_EXE")
    if exe:
        return exe if os.path.exists(exe) else None
    return shutil.which("conda")


def ensure_conda_env(spec, base_dir: str) -> str:
    """Worker-side: materialize a conda environment for the spec and
    return its site-packages path (reference:
    python/ray/_private/runtime_env/conda.py:154 — envs are created
    once per node, keyed by the spec hash, shared by every worker).

    ``spec``: a dict (environment.yml structure — JSON is a YAML
    subset, so it ships verbatim), {"__yaml__": text} for a shipped
    environment.yml, or a string naming an EXISTING conda env.
    Activation is a sys.path prepend of the env's site-packages (the
    same model as the pip tier — the host interpreter stays in charge;
    ABI-incompatible python versions in the spec are the user's
    responsibility, as with the reference's conda env python pinning).
    """
    import subprocess

    exe = _conda_exe()
    if exe is None:
        raise RuntimeError(
            "runtime_env['conda'] requested but no conda executable "
            "found (install conda/micromamba or set RAY_TPU_CONDA_EXE)")
    if isinstance(spec, str):
        # existing env by name or prefix path, cached for the worker's
        # lifetime (conda CLI startup costs seconds; the name->prefix
        # mapping is stable per node)
        cache_key = (exe, spec)
        cached = _named_env_cache.get(cache_key)
        if cached is not None:
            return cached
        if os.path.sep in spec:  # a prefix path, no registry lookup
            sp = _conda_site_packages(os.path.expanduser(spec))
            _named_env_cache[cache_key] = sp
            return sp

        def run_json(args):
            # stderr stays separate: conda warnings (version notices
            # etc.) must not corrupt the JSON document on stdout
            try:
                r = subprocess.run([exe, *args], text=True, timeout=120,
                                   stdout=subprocess.PIPE,
                                   stderr=subprocess.PIPE)
            except subprocess.TimeoutExpired:
                raise RuntimeError(
                    f"conda {' '.join(args)} timed out (120s)") from None
            if r.returncode != 0:
                raise RuntimeError(f"conda {' '.join(args)} failed: "
                                   f"{(r.stderr or r.stdout)[-500:]}")
            return json.loads(r.stdout)

        if spec == "base":
            # the root env's prefix basename is the install dir name
            # ('miniconda3'), never 'base' — ask conda info for it
            prefix = run_json(["info", "--json"]).get("root_prefix")
            if not prefix:
                raise RuntimeError("conda info reported no root_prefix")
            sp = _conda_site_packages(prefix)
            _named_env_cache[cache_key] = sp
            return sp
        for prefix in run_json(["env", "list", "--json"]).get("envs", []):
            if os.path.basename(prefix) == spec:
                sp = _conda_site_packages(prefix)
                _named_env_cache[cache_key] = sp
                return sp
        raise RuntimeError(f"conda env {spec!r} not found on this node")

    yaml_text = spec["__yaml__"] if "__yaml__" in spec \
        else json.dumps(spec)  # JSON is valid YAML
    env_key = hashlib.sha1(yaml_text.encode()).hexdigest()[:16]
    prefix = os.path.join(base_dir, "runtime_resources", "conda", env_key)
    if os.path.isdir(prefix):
        return _conda_site_packages(prefix)
    os.makedirs(os.path.dirname(prefix), exist_ok=True)
    tmp = tempfile.mkdtemp(dir=os.path.dirname(prefix),
                           prefix=f".{env_key}-")
    try:
        spec_path = os.path.join(tmp, "environment.yml")
        with open(spec_path, "w") as f:
            f.write(yaml_text)
        env_prefix = os.path.join(tmp, "env")
        try:
            r = subprocess.run(
                [exe, "env", "create", "-p", env_prefix, "-f", spec_path,
                 "--quiet"],
                text=True, timeout=1800, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT)
        except subprocess.TimeoutExpired:
            raise RuntimeError(
                "conda env create for runtime_env timed out "
                "(1800s)") from None
        if r.returncode != 0:
            raise RuntimeError(
                f"conda env create failed (exit {r.returncode}):\n"
                f"{r.stdout[-2000:]}")
        try:
            os.rename(env_prefix, prefix)  # atomic publish
        except OSError:
            if not os.path.isdir(prefix):  # lost a benign race
                raise
    finally:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    return _conda_site_packages(prefix)


def _conda_site_packages(prefix: str) -> str:
    """The env's site-packages dir (any python version inside)."""
    lib = os.path.join(prefix, "lib")
    if os.path.isdir(lib):
        for entry in sorted(os.listdir(lib)):
            sp = os.path.join(lib, entry, "site-packages")
            if entry.startswith("python") and os.path.isdir(sp):
                return sp
    sp = os.path.join(prefix, "site-packages")  # stub/minimal layout
    if os.path.isdir(sp):
        return sp
    raise RuntimeError(f"no site-packages found under conda env {prefix}")


@contextlib.contextmanager
def activate(runtime_env: Optional[Dict[str, Any]], base_dir: str,
             kv_get: Callable[[bytes], Optional[bytes]]):
    """Apply a runtime env around one task execution, then restore:
    env_vars into os.environ, the working_dir package onto sys.path[0]
    and as cwd (reference: workers/setup_worker.py + working_dir_manager
    setup_for_worker)."""
    if not runtime_env:
        yield
        return
    env_vars = {str(k): str(v)
                for k, v in (runtime_env.get("env_vars") or {}).items()}
    saved_env = {k: os.environ.get(k) for k in env_vars}
    os.environ.update(env_vars)
    uri = runtime_env.get("working_dir_uri")
    pip_entries = runtime_env.get("pip")
    saved_cwd = None
    pkg_dir = None
    pip_dir = None
    conda_spec = runtime_env.get("conda")
    if conda_spec:
        # conda tier shares the pip tier's activation model: the env's
        # site-packages rides sys.path for the task's duration
        pip_dir = ensure_conda_env(conda_spec, base_dir)
        sys.path.insert(0, pip_dir)
        for mod_name, mod in _module_cache.pop(pip_dir, {}).items():
            sys.modules.setdefault(mod_name, mod)
    elif pip_entries:
        pip_dir = ensure_pip_env(pip_entries, base_dir, kv_get)
        sys.path.insert(0, pip_dir)
        for mod_name, mod in _module_cache.pop(pip_dir, {}).items():
            sys.modules.setdefault(mod_name, mod)
    if uri:
        pkg_dir = ensure_local_package(uri, base_dir, kv_get)
        saved_cwd = os.getcwd()
        sys.path.insert(0, pkg_dir)
        os.chdir(pkg_dir)
        # Warm worker: restore this package's previously-imported
        # modules instead of re-importing them.
        for mod_name, mod in _module_cache.pop(pkg_dir, {}).items():
            sys.modules.setdefault(mod_name, mod)
    try:
        yield
    finally:
        if pkg_dir is not None:
            with contextlib.suppress(ValueError):
                sys.path.remove(pkg_dir)
            with contextlib.suppress(OSError):
                os.chdir(saved_cwd)
        # Reversibility includes imports: modules loaded FROM the
        # package / pip env must not leak into later tasks on this
        # worker (those tasks may carry a different env with a
        # same-named module). They are PARKED, not dropped: a later
        # task with the same env restores them without re-importing —
        # this is what makes env-hash worker affinity
        # (raylet _pop_idle_worker) worth having.
        for env_dir in (pkg_dir, pip_dir):
            if env_dir is None:
                continue
            if env_dir is pip_dir:
                with contextlib.suppress(ValueError):
                    sys.path.remove(pip_dir)
            parked = _module_cache.setdefault(env_dir, {})
            for mod_name, mod in list(sys.modules.items()):
                mod_file = getattr(mod, "__file__", None) or ""
                if mod_file.startswith(env_dir + os.sep):
                    parked[mod_name] = mod
                    del sys.modules[mod_name]
        for k, old in saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old


def activate_persistent(runtime_env: Optional[Dict[str, Any]],
                        base_dir: str,
                        kv_get: Callable[[bytes], Optional[bytes]]) -> None:
    """Apply an env for the lifetime of this worker (actor creation)."""
    if not runtime_env:
        return
    os.environ.update(
        {str(k): str(v)
         for k, v in (runtime_env.get("env_vars") or {}).items()})
    conda_spec = runtime_env.get("conda")
    pip_entries = runtime_env.get("pip")
    if conda_spec:
        sys.path.insert(0, ensure_conda_env(conda_spec, base_dir))
    elif pip_entries:
        sys.path.insert(0, ensure_pip_env(pip_entries, base_dir, kv_get))
    uri = runtime_env.get("working_dir_uri")
    if uri:
        pkg_dir = ensure_local_package(uri, base_dir, kv_get)
        sys.path.insert(0, pkg_dir)
        os.chdir(pkg_dir)
