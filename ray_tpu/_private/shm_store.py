"""Node-local shared-memory object store (plasma equivalent).

Role parity: reference plasma store (src/ray/object_manager/plasma/store.h,
client.h) — large objects live in POSIX shared memory, mapped zero-copy by
every worker on the node. Differences by design: instead of a dlmalloc arena
with fd-passing, each object is one named shm segment created by the
*writing* client and registered (sealed) with the node's store server (the
raylet), which owns eviction, pinning, spill-to-disk and unlink. Readers
attach by name — no data ever crosses a socket intra-node.

Segment layout: [u32 header_len][msgpack [metadata, [frame_len...]]]
[frame bytes...] with each frame 8-byte aligned so numpy/jax views are
aligned.
"""

from __future__ import annotations

import logging
import os
import secrets
import struct
import threading
import time
import weakref
from multiprocessing import resource_tracker, shared_memory
from typing import Any, Dict, List, Optional, Tuple

import msgpack

from ray_tpu._private.ids import ObjectID
from ray_tpu._private.serialization import SerializedObject

logger = logging.getLogger(__name__)

_U32 = struct.Struct("<I")


def _align8(n: int) -> int:
    return (n + 7) & ~7


_zombie_lock = threading.Lock()
_zombies: List[shared_memory.SharedMemory] = []
# Mappings whose munmap is deferred to consumer-view GC (see
# _QuietSharedMemory.close). A WeakSet: the only strong refs to these
# mmap objects are the consumers' buffer exports, so entries vanish
# from the set at the exact moment the mapping is deallocated.
_deferred: "weakref.WeakSet" = weakref.WeakSet()


def deferred_count() -> int:
    """Mappings detached by the store but still pinned by live zero-copy
    consumer views. These unmap deterministically when the last view is
    garbage-collected (normal operation, not a leak — a steadily growing
    value means user code holds zero-copy values forever)."""
    return len(_deferred)


def zombie_count() -> int:
    """Parked mappings on the guarded FALLBACK path (deferred release
    failed). Should always be 0; anything here is log-worthy."""
    with _zombie_lock:
        return len(_zombies)


class _QuietSharedMemory(shared_memory.SharedMemory):
    """A SharedMemory whose close() tolerates live zero-copy consumers.

    The view-release discipline here IS reference counting — by the
    mmap's own buffer exports: every deserialized array views a frame
    memoryview which views the mapping, so each consumer value holds a
    strong reference to the mmap object. close() called while exports
    exist therefore *drops our handles* (and closes the fd immediately)
    instead of unmapping: the mmap object stays alive exactly as long
    as consumer views do, and CPython's mmap deallocator munmaps it the
    instant the last view is garbage-collected. Deterministic release,
    no sweeping. Reference discipline: plasma client Release
    (src/ray/object_manager/plasma/client.cc) — there the refcount is
    explicit; here the buffer protocol keeps it for us."""

    def close(self):  # noqa: D102 - see class docstring
        try:
            shared_memory.SharedMemory.close(self)
            return
        except BufferError:
            pass
        # Deferred release. SharedMemory.close() released self._buf
        # before the mmap close raised, so only _mmap and _fd remain.
        try:
            mm, self._mmap = self._mmap, None
            if self._fd >= 0:
                os.close(self._fd)
                self._fd = -1
            _deferred.add(mm)
            del mm  # consumers now hold the only strong references
        except Exception:
            # Fallback: park the handle whole; sweep_zombies retries.
            logger.warning("deferred shm release failed; parking %s",
                           getattr(self, "_name", "?"), exc_info=True)
            try:
                with _zombie_lock:
                    _zombies.append(self)
            except Exception:
                pass  # interpreter teardown


def sweep_zombies() -> int:
    """Retry closing fallback-parked mappings whose consumers have since
    died. Returns the number of mappings still parked. (The normal
    deferred-release path never parks — see _QuietSharedMemory.close.)"""
    with _zombie_lock:
        parked, _zombies[:] = _zombies[:], []
    still = []
    for shm in parked:
        try:
            shared_memory.SharedMemory.close(shm)
        except BufferError:
            still.append(shm)
        except Exception:
            pass
    if still:
        with _zombie_lock:
            _zombies.extend(still)
    return len(still)


def _untrack(shm: shared_memory.SharedMemory) -> None:
    """Detach from the resource tracker: segment lifetime is owned by the
    store server, not whichever client process happened to create it."""
    try:
        resource_tracker.unregister(shm._name, "shared_memory")  # noqa: SLF001
    except Exception:
        pass


def _create_segment_buf(name: str, size: int):
    """Create a /dev/shm segment and return (mmap_or_shm, buffer).

    The direct-mmap path passes MAP_POPULATE so the kernel faults in
    (and zeroes) every page in ONE syscall — per-4K-page fault traps
    made fresh-segment writes 5x slower than warm copies (0.73 vs 3.66
    GB/s measured); POPULATE recovers ~1.7x of it. Falls back to
    multiprocessing.SharedMemory where /dev/shm or MAP_POPULATE is
    unavailable. Readers attach by name either way."""
    import mmap

    populate = getattr(mmap, "MAP_POPULATE", 0)
    if populate and os.path.isdir("/dev/shm"):
        try:
            fd = os.open(f"/dev/shm/{name}",
                         os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        except OSError:
            pass  # exotic /dev/shm permissions: use the fallback
        else:
            try:
                try:
                    os.ftruncate(fd, size)
                    mm = mmap.mmap(fd, size,
                                   flags=mmap.MAP_SHARED | populate)
                finally:
                    os.close(fd)
            except OSError:
                # ENOMEM et al.: remove the just-created file (the
                # store never learned this name) and fall back
                try:
                    os.unlink(f"/dev/shm/{name}")
                except OSError:
                    pass
            else:
                return mm, memoryview(mm)
    shm = shared_memory.SharedMemory(name=name, create=True, size=size)
    _untrack(shm)
    return shm, shm.buf


def _close_segment_owner(owner, buf) -> None:
    if isinstance(owner, shared_memory.SharedMemory):
        owner.close()
    else:  # raw mmap: release our view first
        buf.release()
        owner.close()


def write_segment(serialized: SerializedObject) -> Tuple[str, int]:
    """Create + fill a segment; returns (segment_name, total_size)."""
    meta, frames = serialized.metadata, serialized.frames
    raw_frames: List[memoryview] = []
    for f in frames:
        if hasattr(f, "raw"):  # PickleBuffer
            raw_frames.append(f.raw())
        else:
            raw_frames.append(memoryview(f))
    header = msgpack.packb(
        [meta, [f.nbytes for f in raw_frames]], use_bin_type=True)
    offset0 = _align8(4 + len(header))
    total = offset0
    offsets = []
    for f in raw_frames:
        offsets.append(total)
        total = _align8(total + f.nbytes)
    name = f"rtpu_{secrets.token_hex(8)}"
    owner, buf = _create_segment_buf(name, max(total, 1))
    buf[0:4] = _U32.pack(len(header))
    buf[4:4 + len(header)] = header
    for off, f in zip(offsets, raw_frames):
        buf[off:off + f.nbytes] = f.cast("B") if f.format != "B" or f.ndim != 1 else f
    _close_segment_owner(owner, buf)
    return name, total


class AttachedObject:
    """A reader-side mapping. Keeps the SharedMemory alive while any
    deserialized view of the data is alive."""

    __slots__ = ("shm", "metadata", "frames")

    def __init__(self, name: str):
        sweep_zombies()
        # Attach-only: python 3.12 does not resource-track attachments, so
        # no _untrack here (an unmatched unregister trips the tracker).
        self.shm = _QuietSharedMemory(name=name)
        buf = self.shm.buf
        (header_len,) = _U32.unpack(bytes(buf[0:4]))
        meta, frame_lens = msgpack.unpackb(bytes(buf[4:4 + header_len]), raw=False)
        self.metadata = meta
        self.frames = []
        off = _align8(4 + header_len)
        for ln in frame_lens:
            self.frames.append(buf[off:off + ln])
            off = _align8(off + ln)

    def close(self):
        self.frames = []
        try:
            self.shm.close()
        except Exception:
            pass
        sweep_zombies()


class ShmStoreServer:
    """Runs inside the raylet. Tracks sealed segments, enforces the store
    capacity with LRU eviction of unpinned objects, spills evicted-but-
    needed primaries to disk and restores them on demand (reference:
    LocalObjectManager, src/ray/raylet/local_object_manager.h)."""

    def __init__(self, capacity_bytes: int, spill_dir: str = "",
                 spilling_enabled: bool = True,
                 external_storage_url: str = ""):
        self.capacity = capacity_bytes
        self.spill_dir = spill_dir
        # External spill target (reference: external_storage.py:71 —
        # filesystem or S3 via smart_open; here any workflow-storage
        # URL: file:// shared fs, kv:// cluster KV, s3://). Local
        # spill_dir remains the default; the URL overrides it.
        self._ext = None
        self._ext_pool = None
        self._ext_futures: Dict[str, Any] = {}  # key -> upload future
        if external_storage_url:
            if external_storage_url.startswith("kv://"):
                # the cluster KV client needs a connected DRIVER; the
                # raylet is not one — kv:// spill would deadlock/raise
                raise ValueError(
                    "spill_external_storage_url must be file:// or "
                    "s3:// (kv:// is driver-side only)")
            from concurrent.futures import ThreadPoolExecutor

            from ray_tpu.workflow.storage import storage_from_url
            self._ext = storage_from_url(external_storage_url)
            # uploads/deletes run OFF the raylet loop: a burst of
            # multi-MB network puts must not stall RPC handling or
            # heartbeats (restore reads stay synchronous — they are
            # demand-driven single objects on the serving path)
            self._ext_pool = ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="rtpu-spill")
        self.spilling_enabled = spilling_enabled and \
            bool(spill_dir or self._ext is not None)
        if self.spill_dir:
            os.makedirs(self.spill_dir, exist_ok=True)
        # oid -> (segment_name, size, created_ts)
        self._objects: Dict[ObjectID, Tuple[str, int, float]] = {}
        self._pinned: Dict[ObjectID, int] = {}
        self._last_access: Dict[ObjectID, float] = {}
        self._spilled: Dict[ObjectID, Tuple[str, int]] = {}  # oid -> (path, size)
        self.used = 0
        self.num_evictions = 0
        self.num_spills = 0
        self.num_restores = 0

    # -- write path ---------------------------------------------------------

    def seal(self, object_id: ObjectID, segment_name: str, size: int) -> bool:
        if object_id in self._objects:
            # Duplicate seal (e.g. task retry): drop the new segment.
            self._unlink(segment_name)
            return True
        if self.used + size > self.capacity:
            self._evict(self.used + size - self.capacity)
        if self.used + size > self.capacity:
            self._unlink(segment_name)
            return False
        self._objects[object_id] = (segment_name, size, time.time())
        self._last_access[object_id] = time.time()
        self.used += size
        return True

    # -- read path ----------------------------------------------------------

    def lookup(self, object_id: ObjectID) -> Optional[str]:
        entry = self._objects.get(object_id)
        if entry is not None:
            self._last_access[object_id] = time.time()
            return entry[0]
        if object_id in self._spilled:
            return self._restore(object_id)
        return None

    def contains(self, object_id: ObjectID) -> bool:
        return object_id in self._objects or object_id in self._spilled

    # -- pinning (primary copies; owner-driven) ------------------------------

    def pin(self, object_id: ObjectID) -> None:
        self._pinned[object_id] = self._pinned.get(object_id, 0) + 1

    def unpin(self, object_id: ObjectID) -> None:
        n = self._pinned.get(object_id, 0) - 1
        if n <= 0:
            self._pinned.pop(object_id, None)
        else:
            self._pinned[object_id] = n

    # -- free / eviction / spilling -----------------------------------------

    def free(self, object_id: ObjectID) -> None:
        entry = self._objects.pop(object_id, None)
        self._pinned.pop(object_id, None)
        self._last_access.pop(object_id, None)
        if entry is not None:
            name, size, _ = entry
            self.used -= size
            self._unlink(name)
        spilled = self._spilled.pop(object_id, None)
        if spilled is not None:
            self._delete_spilled(spilled[0])

    def _delete_spilled(self, location: str) -> None:
        if location.startswith("ext:"):
            key = location[4:]
            upload = self._ext_futures.pop(key, None)

            def _del():
                if upload is not None:
                    try:  # the blob may still be uploading
                        upload.result(timeout=60)
                    except Exception:  # noqa: BLE001
                        pass
                try:
                    self._ext.delete(key)
                except Exception:  # noqa: BLE001 — best effort
                    logger.exception("external spill delete failed")

            self._ext_pool.submit(_del)
            return
        try:
            os.unlink(location)
        except OSError:
            pass

    def _evict(self, need_bytes: int) -> None:
        """Evict LRU unpinned objects; pinned primaries are spilled to disk
        instead of dropped when spilling is on."""
        victims = sorted(
            (oid for oid in self._objects if oid not in self._pinned),
            key=lambda o: self._last_access.get(o, 0.0))
        freed = 0
        for oid in victims:
            if freed >= need_bytes:
                break
            name, size, _ = self._objects.pop(oid)
            self._last_access.pop(oid, None)
            self.used -= size
            freed += size
            self.num_evictions += 1
            self._unlink(name)
        if freed < need_bytes and self.spilling_enabled:
            pinned_victims = sorted(
                (oid for oid in self._objects),
                key=lambda o: self._last_access.get(o, 0.0))
            for oid in pinned_victims:
                if freed >= need_bytes:
                    break
                freed += self._spill(oid)

    def _spill(self, object_id: ObjectID) -> int:
        name, size, _ = self._objects.pop(object_id)
        self._last_access.pop(object_id, None)
        try:
            shm = shared_memory.SharedMemory(name=name)
            if self._ext is not None:
                # copy to RAM + background upload: the loop thread must
                # not block on a network put (the copy's lifetime is
                # bounded by the 2-worker upload pool draining)
                key = f"spill/{object_id.hex()}"
                data = bytes(shm.buf[:size])
                self._ext_futures[key] = self._ext_pool.submit(
                    self._ext.put, key, data)
                location = "ext:" + key
            else:
                location = os.path.join(self.spill_dir, object_id.hex())
                with open(location, "wb") as f:
                    f.write(shm.buf[:size])
            shm.close()
        except Exception:
            logger.exception("spill of %s failed", object_id)
            self._objects[object_id] = (name, size, time.time())
            return 0
        self.used -= size
        self.num_spills += 1
        self._spilled[object_id] = (location, size)
        self._unlink(name)
        return size

    def _restore(self, object_id: ObjectID) -> Optional[str]:
        location, size = self._spilled[object_id]
        if self.used + size > self.capacity:
            self._evict(self.used + size - self.capacity)
        name = f"rtpu_{secrets.token_hex(8)}"
        try:
            if location.startswith("ext:"):
                key = location[4:]
                upload = self._ext_futures.pop(key, None)
                if upload is not None:  # still in flight: wait it out
                    upload.result(timeout=120)
                data = self._ext.get(key)
                if data is None:
                    raise FileNotFoundError(location)
            else:
                with open(location, "rb") as f:
                    data = f.read()
            owner, buf = _create_segment_buf(name, max(size, 1))
            buf[:len(data)] = data
            _close_segment_owner(owner, buf)
        except Exception:
            logger.exception("restore of %s failed", object_id)
            return None
        del self._spilled[object_id]
        self._delete_spilled(location)
        self._objects[object_id] = (name, size, time.time())
        self._last_access[object_id] = time.time()
        self.used += size
        self.num_restores += 1
        return name

    @staticmethod
    def _unlink(segment_name: str) -> None:
        try:
            shm = shared_memory.SharedMemory(name=segment_name)
            shm.close()
            shm.unlink()
        except FileNotFoundError:
            pass
        except Exception:
            logger.exception("unlink of %s failed", segment_name)

    def shutdown(self) -> None:
        for name, _, _ in self._objects.values():
            self._unlink(name)
        self._objects.clear()
        for location, _ in self._spilled.values():
            self._delete_spilled(location)
        self._spilled.clear()
        self.used = 0

    def stats(self) -> dict:
        return {
            "used_bytes": self.used,
            "capacity_bytes": self.capacity,
            "num_objects": len(self._objects),
            "num_pinned": len(self._pinned),
            "num_spilled": len(self._spilled),
            "num_evictions": self.num_evictions,
            "num_spills": self.num_spills,
            "num_restores": self.num_restores,
            # consumer-pinned mappings awaiting their views' GC (normal)
            "num_deferred_mappings": deferred_count(),
            # fallback-parked mappings (always 0 in healthy operation)
            "num_zombie_mappings": zombie_count(),
        }
