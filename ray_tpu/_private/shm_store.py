"""Node-local shared-memory object store (plasma equivalent).

Role parity: reference plasma store (src/ray/object_manager/plasma/store.h,
client.h) — large objects live in POSIX shared memory, mapped zero-copy by
every worker on the node. Differences by design: instead of a dlmalloc arena
with fd-passing, each object is one named shm segment created by the
*writing* client and registered (sealed) with the node's store server (the
raylet), which owns eviction, pinning, spill-to-disk and unlink. Readers
attach by name — no data ever crosses a socket intra-node.

Segment layout: [u32 header_len][msgpack [metadata, [frame_len...]]]
[frame bytes...] with each frame 8-byte aligned so numpy/jax views are
aligned.

Zero-copy put pipeline (see serialization.py for the serializer half):
``write_segment`` is a two-pass single-memcpy writer — plan the exact
layout from raw frame views, then copy each frame straight into the
segment via tiered writers (cached warm mapping + native striped
GIL-releasing memcpy > pwrite into the /dev/shm file > pure-Python
slice assignment). ``ShmStoreServer`` recycles freed segments (warm
tmpfs pages: on the bench box fresh page allocation costs ~5x the
copy) and leases them to writers via the raylet's AllocSegment RPC;
segments ever exposed for a foreign mmap are unlinked instead —
zero-copy consumer views may outlive the free and must never see a
recycled overwrite. Readers attach with MAP_POPULATE.
"""

from __future__ import annotations

import logging
import os
import secrets
import struct
import threading
import time
import weakref
from multiprocessing import resource_tracker, shared_memory
from typing import Any, Dict, List, Optional, Tuple

import msgpack

from ray_tpu._private import faultpoints, native
from ray_tpu._private import object_events as oev
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.serialization import SerializedObject

logger = logging.getLogger(__name__)

_U32 = struct.Struct("<I")

# Puts below this size skip the AllocSegment round trip (the RPC costs
# more than cold pages for small segments).
RECYCLE_MIN_BYTES = 1 << 20


def _align8(n: int) -> int:
    return (n + 7) & ~7


_zombie_lock = threading.Lock()
_zombies: List[shared_memory.SharedMemory] = []
# Mappings whose munmap is deferred to consumer-view GC (see
# _QuietSharedMemory.close). A WeakSet: the only strong refs to these
# mmap objects are the consumers' buffer exports, so entries vanish
# from the set at the exact moment the mapping is deallocated.
_deferred: "weakref.WeakSet" = weakref.WeakSet()


def deferred_count() -> int:
    """Mappings detached by the store but still pinned by live zero-copy
    consumer views. These unmap deterministically when the last view is
    garbage-collected (normal operation, not a leak — a steadily growing
    value means user code holds zero-copy values forever)."""
    return len(_deferred)


def zombie_count() -> int:
    """Parked mappings on the guarded FALLBACK path (deferred release
    failed). Should always be 0; anything here is log-worthy."""
    with _zombie_lock:
        return len(_zombies)


class _QuietSharedMemory(shared_memory.SharedMemory):
    """A SharedMemory whose close() tolerates live zero-copy consumers.

    The view-release discipline here IS reference counting — by the
    mmap's own buffer exports: every deserialized array views a frame
    memoryview which views the mapping, so each consumer value holds a
    strong reference to the mmap object. close() called while exports
    exist therefore *drops our handles* (and closes the fd immediately)
    instead of unmapping: the mmap object stays alive exactly as long
    as consumer views do, and CPython's mmap deallocator munmaps it the
    instant the last view is garbage-collected. Deterministic release,
    no sweeping. Reference discipline: plasma client Release
    (src/ray/object_manager/plasma/client.cc) — there the refcount is
    explicit; here the buffer protocol keeps it for us."""

    def __init__(self, name=None, create=False, size=0):
        super().__init__(name=name, create=create, size=size)
        if not create:
            self._populate_attach()

    def _populate_attach(self):
        """Swap the plain attach mapping for a MAP_POPULATE one: every
        PTE is installed in one syscall. A reader faulting resident
        tmpfs pages one at a time pays ~3.4us/page on this box (~1
        GiB/s); the populated mapping delivers ~14 GiB/s. Swapping is
        safe here: __init__ just created self._buf and nothing has
        exported it yet."""
        import mmap as _mmap

        populate = getattr(_mmap, "MAP_POPULATE", 0)
        if not populate or self._fd < 0 or self.size <= 0:
            return
        try:
            mm = _mmap.mmap(self._fd, self.size,
                            flags=_mmap.MAP_SHARED | populate)
        except (OSError, ValueError):
            return  # keep the ordinary mapping
        self._buf.release()
        self._mmap.close()
        self._mmap = mm
        self._buf = memoryview(mm)

    def close(self):  # noqa: D102 - see class docstring
        try:
            shared_memory.SharedMemory.close(self)
            return
        except BufferError:
            pass
        # Deferred release. SharedMemory.close() released self._buf
        # before the mmap close raised, so only _mmap and _fd remain.
        try:
            mm, self._mmap = self._mmap, None
            if self._fd >= 0:
                os.close(self._fd)
                self._fd = -1
            _deferred.add(mm)
            del mm  # consumers now hold the only strong references
        except Exception:
            # Fallback: park the handle whole; sweep_zombies retries.
            logger.warning("deferred shm release failed; parking %s",
                           getattr(self, "_name", "?"), exc_info=True)
            try:
                with _zombie_lock:
                    _zombies.append(self)
            # raylint: disable=exception-hygiene — interpreter teardown: module globals may already be None
            except Exception:
                pass


def sweep_zombies() -> int:
    """Retry closing fallback-parked mappings whose consumers have since
    died. Returns the number of mappings still parked. (The normal
    deferred-release path never parks — see _QuietSharedMemory.close.)"""
    with _zombie_lock:
        parked, _zombies[:] = _zombies[:], []
    still = []
    for shm in parked:
        try:
            shared_memory.SharedMemory.close(shm)
        except BufferError:
            still.append(shm)
        except OSError:
            pass  # segment already closed/unlinked elsewhere
    if still:
        with _zombie_lock:
            _zombies.extend(still)
    return len(still)


def _untrack(shm: shared_memory.SharedMemory) -> None:
    """Detach from the resource tracker: segment lifetime is owned by the
    store server, not whichever client process happened to create it."""
    try:
        resource_tracker.unregister(shm._name, "shared_memory")  # noqa: SLF001
    # raylint: disable=exception-hygiene — tracker process may already be dead; leak is bounded by the store sweep
    except Exception:
        pass


def _create_segment_buf(name: str, size: int):
    """Create a /dev/shm segment and return (mmap_or_shm, buffer).

    The direct-mmap path passes MAP_POPULATE so the kernel faults in
    (and zeroes) every page in ONE syscall — per-4K-page fault traps
    made fresh-segment writes 5x slower than warm copies (0.73 vs 3.66
    GB/s measured); POPULATE recovers ~1.7x of it. Falls back to
    multiprocessing.SharedMemory where /dev/shm or MAP_POPULATE is
    unavailable. Readers attach by name either way."""
    import mmap

    populate = getattr(mmap, "MAP_POPULATE", 0)
    if populate and os.path.isdir("/dev/shm"):
        try:
            fd = os.open(f"/dev/shm/{name}",
                         os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        except OSError:
            pass  # exotic /dev/shm permissions: use the fallback
        else:
            try:
                try:
                    os.ftruncate(fd, size)
                    mm = mmap.mmap(fd, size,
                                   flags=mmap.MAP_SHARED | populate)
                finally:
                    os.close(fd)
            except OSError:
                # ENOMEM et al.: remove the just-created file (the
                # store never learned this name) and fall back
                try:
                    os.unlink(f"/dev/shm/{name}")
                except OSError:
                    pass
            else:
                return mm, memoryview(mm)
    shm = shared_memory.SharedMemory(name=name, create=True, size=size)
    _untrack(shm)
    return shm, shm.buf


def _attach_segment_buf(name: str):
    """Attach an EXISTING segment for writing (recycled warm pages).

    Direct mmap with MAP_POPULATE where possible: the file's pages are
    resident but a fresh mapping still takes one minor fault per 4K
    page, which costs ~5x the copy itself on this box — POPULATE
    installs every PTE in one syscall."""
    import mmap

    populate = getattr(mmap, "MAP_POPULATE", 0)
    path = f"/dev/shm/{name}"
    if populate and os.path.exists(path):
        fd = os.open(path, os.O_RDWR)
        try:
            size = os.fstat(fd).st_size
            mm = mmap.mmap(fd, size, flags=mmap.MAP_SHARED | populate)
        finally:
            os.close(fd)
        return mm, memoryview(mm)
    shm = _QuietSharedMemory(name=name)
    return shm, shm.buf


def open_segment_for_read(name: str):
    """An unbuffered read-only file object on a segment's /dev/shm file
    — the sender-side seam of the cross-node data plane: os.sendfile
    streams chunk ranges straight from these pages to the peer's socket
    (no mapping, no userspace copy). Raises FileNotFoundError where the
    segment is not /dev/shm-backed (exotic platforms); the data server
    falls back to serving from a mapped attachment."""
    return open(f"/dev/shm/{name}", "rb", buffering=0)


def _close_segment_owner(owner, buf) -> None:
    if isinstance(owner, shared_memory.SharedMemory):
        owner.close()
    else:  # raw mmap: release our view first
        buf.release()
        owner.close()


def acquire_segment(alloc: Optional[Tuple[str, int]], size: int):
    """(name, owner, buf) for a writable segment of >= ``size`` bytes.

    ``alloc`` is a recycled (name, file_size) lease from the store's
    free pool (AllocSegment): its pages are already faulted in, so the
    fill runs at warm-memcpy speed instead of paying the kernel's
    fresh-page allocation cost (5-8x slower on this box). Falls back to
    creating a fresh segment when no lease is given or the lease is
    stale/undersized."""
    if alloc is not None:
        name = alloc[0]
        try:
            owner, buf = _attach_segment_buf(name)
        except (FileNotFoundError, OSError, ValueError):
            pass  # lease raced with a store teardown: create fresh
        else:
            if buf.nbytes >= size:
                return name, owner, buf
            _close_segment_owner(owner, buf)  # undersized (stale lease)
            ShmStoreServer._unlink(name)
    name = f"rtpu_{secrets.token_hex(8)}"
    owner, buf = _create_segment_buf(name, max(size, 1))
    return name, owner, buf


def plan_segment(serialized: SerializedObject):
    """First pass of the two-pass writer: (header, raw_frames, offsets,
    total). Raw uint8 frame views only — nothing is flattened."""
    raw_frames = serialized.frame_views()
    header = msgpack.packb(
        [serialized.metadata, [f.nbytes for f in raw_frames]],
        use_bin_type=True)
    total = _align8(4 + len(header))
    offsets = []
    for f in raw_frames:
        offsets.append(total)
        total = _align8(total + f.nbytes)
    return header, raw_frames, offsets, total


def segment_nbytes(serialized: SerializedObject) -> int:
    """Exact segment size a write of ``serialized`` will need."""
    return plan_segment(serialized)[3]


# Single pwrite syscall cap (the kernel truncates writes near 2 GiB);
# also the chunk size of the >2GiB-frame path. Tests shrink it.
PWRITE_CHUNK_BYTES = 1 << 30


class _WriterMapCache:
    """Per-process LRU of writable mappings of recycled segments.

    The last tier of the put pipeline: a hit skips attach AND PTE
    population entirely — the striped GIL-releasing memcpy runs against
    live page tables at near-DRAM speed (~2x the warm pwrite path,
    ~8x a cold write on this box). Entries are taken OUT of the cache
    while a write uses them (the store's lease protocol guarantees one
    writer per name) and validated by inode on take, so a segment the
    store unlinked meanwhile is just dropped; segment lifetime stays
    fully owned by the store server."""

    def __init__(self):
        # Cache cap bounds how much tmpfs the process can pin BEYOND
        # the store's accounting: entries whose file the store has
        # unlinked keep their pages alive until evicted here (the
        # sweep below reclaims them lazily). Kept well under typical
        # object_store_memory for that reason.
        cap_mb = int(os.environ.get("RAY_TPU_WRITER_MAP_CACHE_MB", "1024"))
        self.cap_bytes = 0 if os.environ.get("RAY_TPU_NO_MAP_CACHE") \
            else cap_mb * 1024 * 1024
        # largest mapping worth caching (bigger objects go via pwrite)
        self.entry_cap = min(self.cap_bytes, 256 * 1024 * 1024)
        self._entries: Dict[str, Tuple[int, Any, memoryview]] = {}
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    @property
    def enabled(self) -> bool:
        return self.cap_bytes > 0

    def take(self, name: str, need: int):
        """Remove and return (owner, buf) for ``name`` if the cached
        mapping is still the live file and large enough; else None."""
        with self._lock:
            entry = self._entries.pop(name, None)
            if entry is not None:
                self._bytes -= entry[2].nbytes
        if entry is None:
            self.misses += 1
            return None
        ino, owner, buf = entry
        try:
            st = os.stat(f"/dev/shm/{name}")
            valid = st.st_ino == ino and buf.nbytes >= need
        except OSError:
            valid = False
        if not valid:  # store unlinked/replaced the file: drop mapping
            _close_segment_owner(owner, buf)
            self.misses += 1
            return None
        self.hits += 1
        return owner, buf

    def put(self, name: str, owner, buf) -> bool:
        """Adopt a mapping after a write; returns False (caller closes)
        when caching is off or the entry doesn't fit."""
        if not self.enabled or buf.nbytes > self.entry_cap:
            return False
        try:
            ino = os.stat(f"/dev/shm/{name}").st_ino
        except OSError:
            return False  # not a /dev/shm-backed segment
        evicted = []
        with self._lock:
            if name in self._entries:  # shouldn't happen (lease protocol)
                return False
            while self._bytes + buf.nbytes > self.cap_bytes and self._entries:
                old_name = next(iter(self._entries))
                old = self._entries.pop(old_name)
                self._bytes -= old[2].nbytes
                evicted.append(old)
            self._entries[name] = (ino, owner, buf)
            self._bytes += buf.nbytes
        for _, old_owner, old_buf in evicted:
            _close_segment_owner(old_owner, old_buf)
        self._sweep_stale()
        return True

    def _sweep_stale(self) -> None:
        """Drop the oldest entry if the store has unlinked its file —
        amortized reclaim of pages pinned past eviction (one stat per
        insert, so a busy writer converges quickly)."""
        with self._lock:
            name = next(iter(self._entries), None)
            if name is None:
                return
            ino = self._entries[name][0]
            try:
                stale = os.stat(f"/dev/shm/{name}").st_ino != ino
            except OSError:
                stale = True
            if not stale:
                return
            old = self._entries.pop(name)
            self._bytes -= old[2].nbytes
        _close_segment_owner(old[1], old[2])

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "hits": self.hits, "misses": self.misses}

    def clear(self) -> None:
        with self._lock:
            entries, self._entries = list(self._entries.values()), {}
            self._bytes = 0
        for _, owner, buf in entries:
            _close_segment_owner(owner, buf)


_map_cache = _WriterMapCache()


def map_cache_stats() -> dict:
    return _map_cache.stats()


def _pwrite_all(fd: int, view, off: int) -> None:
    """Write a whole buffer at ``off``, chunked below the kernel's
    per-write cap and looping over partial writes. Each os.pwrite drops
    the GIL for the duration of the in-kernel copy."""
    mv = view if isinstance(view, memoryview) else memoryview(view)
    pos = 0
    n = mv.nbytes
    while pos < n:
        pos += os.pwrite(fd, mv[pos:pos + PWRITE_CHUNK_BYTES], off + pos)


def _acquire_segment_fd(alloc: Optional[Tuple[str, int]], size: int):
    """(name, fd) for the pwrite fast path, or (None, None) where
    /dev/shm (or the recycled lease) is unusable."""
    if os.environ.get("RAY_TPU_NO_PWRITE") or not os.path.isdir("/dev/shm"):
        return None, None
    if alloc is not None:
        try:
            fd = os.open(f"/dev/shm/{alloc[0]}", os.O_RDWR)
        except OSError:
            pass  # stale lease: fall through to a fresh segment
        else:
            if os.fstat(fd).st_size >= size:
                return alloc[0], fd
            os.close(fd)
            ShmStoreServer._unlink(alloc[0])  # undersized lease
    name = f"rtpu_{secrets.token_hex(8)}"
    try:
        fd = os.open(f"/dev/shm/{name}",
                     os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        os.ftruncate(fd, size)
    except OSError:
        return None, None  # exotic /dev/shm: mmap fallback path
    return name, fd


def write_segment(serialized: SerializedObject,
                  alloc: Optional[Tuple[str, int]] = None,
                  plan=None) -> Tuple[str, int]:
    """Fill a segment (recycled via ``alloc``, else fresh) with one
    direct copy per frame; returns (segment_name, total_size).

    Second pass of the two-pass pipeline: the plan sizes the segment
    exactly, then the pickle payload and each out-of-band buffer are
    copied STRAIGHT from their source memory into the segment — no
    intermediate ``bytes`` is ever materialized. Primary path: pwrite
    into the /dev/shm file (lands in the tmpfs page cache with no PTE
    faults; a recycled warm file takes it at memcpy speed, ~5 GB/s vs
    ~1 GB/s cold on this box), GIL dropped for every in-kernel copy.
    Fallback: mapped segment + native.copy_into (GIL-releasing striped
    memcpy, pure-Python memoryview assignment beneath that)."""
    # ``plan`` lets the caller reuse the plan it sized the AllocSegment
    # lease with (one header pack / frame-view pass per put, not two).
    header, raw_frames, offsets, total = plan or plan_segment(serialized)
    size = max(total, 1)

    def _fill(buf) -> None:
        buf[0:4] = _U32.pack(len(header))
        buf[4:4 + len(header)] = header
        for off, f in zip(offsets, raw_frames):
            native.copy_into(buf, off, f)

    # Tier 1: cached live mapping of the leased segment (warm PTEs).
    if alloc is not None and _map_cache.enabled:
        cached = _map_cache.take(alloc[0], size)
        if cached is not None:
            owner, buf = cached
            try:
                _fill(buf)
            except BaseException:
                _close_segment_owner(owner, buf)
                raise
            if not _map_cache.put(alloc[0], owner, buf):
                _close_segment_owner(owner, buf)
            return alloc[0], total
    lease_name = alloc[0] if alloc is not None else None

    def _discard_fresh(name: str) -> None:
        # Error-exit cleanup for segments this writer CREATED: without
        # the unlink a failed fill (ENOSPC mid-write of a multi-GiB
        # put) leaves a file the store never learned about linked in
        # /dev/shm forever. A leased name is NOT unlinked — the store
        # owns it (AbortSegment / the stale sweep reclaims).
        if name != lease_name:
            ShmStoreServer._unlink(name)

    # Tier 2: mapped write that SEEDS the cache for the next reuse of
    # this segment name (cacheable sizes only). Sub-lease-size
    # segments still write here but never seed: AllocSegment is only
    # asked for size >= RECYCLE_MIN_BYTES, so a cached smaller mapping
    # could never be taken — it would just pin dead pages.
    if _map_cache.enabled and size <= _map_cache.entry_cap:
        name, owner, buf = acquire_segment(alloc, size)
        try:
            _fill(buf)
        except BaseException:
            _close_segment_owner(owner, buf)
            _discard_fresh(name)
            raise
        if size < RECYCLE_MIN_BYTES or not _map_cache.put(name, owner, buf):
            _close_segment_owner(owner, buf)
        return name, total
    # Tier 3: pwrite straight into the /dev/shm file — no mapping, no
    # PTE population; the right path for huge one-shot segments.
    name, fd = _acquire_segment_fd(alloc, size)
    if fd is not None:
        try:
            _pwrite_all(fd, _U32.pack(len(header)) + header, 0)
            for off, f in zip(offsets, raw_frames):
                _pwrite_all(fd, f, off)
        except BaseException:
            _discard_fresh(name)
            raise
        finally:
            os.close(fd)
        return name, total
    # Tier 4: plain mapped write (no /dev/shm; SharedMemory fallback).
    name, owner, buf = acquire_segment(alloc, size)
    try:
        _fill(buf)
    except BaseException:
        _discard_fresh(name)
        raise
    finally:
        _close_segment_owner(owner, buf)
    return name, total


class AttachedObject:
    """A reader-side mapping. Keeps the SharedMemory alive while any
    deserialized view of the data is alive."""

    __slots__ = ("shm", "metadata", "frames")

    def __init__(self, name: str):
        sweep_zombies()
        # Attach-only: python 3.12 does not resource-track attachments, so
        # no _untrack here (an unmatched unregister trips the tracker).
        self.shm = _QuietSharedMemory(name=name)
        buf = self.shm.buf
        (header_len,) = _U32.unpack(bytes(buf[0:4]))
        meta, frame_lens = msgpack.unpackb(bytes(buf[4:4 + header_len]), raw=False)
        self.metadata = meta
        self.frames = []
        off = _align8(4 + header_len)
        for ln in frame_lens:
            self.frames.append(buf[off:off + ln])
            off = _align8(off + ln)

    def close(self):
        self.frames = []
        try:
            self.shm.close()
        except (BufferError, OSError):
            pass  # exported views still alive; the zombie sweep retries
        sweep_zombies()


class ShmStoreServer:
    """Runs inside the raylet. Tracks sealed segments, enforces the store
    capacity with LRU eviction of unpinned objects, spills evicted-but-
    needed primaries to disk and restores them on demand (reference:
    LocalObjectManager, src/ray/raylet/local_object_manager.h)."""

    def __init__(self, capacity_bytes: int, spill_dir: str = "",
                 spilling_enabled: bool = True,
                 external_storage_url: str = ""):
        self.capacity = capacity_bytes
        self.spill_dir = spill_dir
        # External spill target (reference: external_storage.py:71 —
        # filesystem or S3 via smart_open; here any workflow-storage
        # URL: file:// shared fs, kv:// cluster KV, s3://). Local
        # spill_dir remains the default; the URL overrides it.
        self._ext = None
        self._ext_pool = None
        self._ext_futures: Dict[str, Any] = {}  # key -> upload future
        if external_storage_url:
            if external_storage_url.startswith("kv://"):
                # the cluster KV client needs a connected DRIVER; the
                # raylet is not one — kv:// spill would deadlock/raise
                raise ValueError(
                    "spill_external_storage_url must be file:// or "
                    "s3:// (kv:// is driver-side only)")
            from concurrent.futures import ThreadPoolExecutor

            from ray_tpu.workflow.storage import storage_from_url
            self._ext = storage_from_url(external_storage_url)
            # uploads/deletes run OFF the raylet loop: a burst of
            # multi-MB network puts must not stall RPC handling or
            # heartbeats (restore reads stay synchronous — they are
            # demand-driven single objects on the serving path)
            self._ext_pool = ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="rtpu-spill")
        self.spilling_enabled = spilling_enabled and \
            bool(spill_dir or self._ext is not None)
        if self.spill_dir:
            os.makedirs(self.spill_dir, exist_ok=True)
        # oid -> (segment_name, size, created_ts)
        self._objects: Dict[ObjectID, Tuple[str, int, float]] = {}
        self._pinned: Dict[ObjectID, int] = {}
        self._last_access: Dict[ObjectID, float] = {}
        self._spilled: Dict[ObjectID, Tuple[str, int]] = {}  # oid -> (path, size)
        self.used = 0
        self.num_evictions = 0
        self.num_spills = 0
        self.num_restores = 0
        # Segment recycle pool (zero-copy put pipeline): freed segments
        # park here (insertion-ordered name -> file size) instead of
        # being unlinked, so the next put of a similar size reuses their
        # already-faulted pages — fresh tmpfs page allocation is the
        # dominant cost of a cold large put on this box. Bounded; the
        # pool is the FIRST thing evicted under memory pressure.
        # SAFETY: only segments never EXPOSED for foreign attach
        # (EnsureObjectLocal) are parked — a consumer's zero-copy view
        # of a freed object keeps its (unlinked) mapping valid forever,
        # but overwriting a still-linked recycled file would corrupt it.
        self._exposed: set = set()
        self._recycle: Dict[str, int] = {}
        self.recycle_bytes = 0
        self.recycle_cap = min(capacity_bytes // 2, 2 << 30)
        # Segments lent to writers (AllocSegment) but not yet sealed:
        # name -> (file size, lent_ts). Stale leases (writer died) are
        # reclaimed lazily.
        self._lent: Dict[str, Tuple[int, float]] = {}
        self.num_recycle_hits = 0
        self.num_recycle_misses = 0
        # Object-lifecycle recorder (object_events.ObjectEventBuffer),
        # installed by the raylet: the store owns the SEALED / PINNED /
        # EXPOSED / EVICTED / SPILLED / RESTORED / FREED transitions
        # and the segment-level RECYCLED / LEASE_ABORTED events, so it
        # stamps them. None (and cost-free) in writer processes.
        self.events = None
        self.node_tag = ""

    def _rec(self, object_id, state: str, attrs: dict) -> None:
        ev = self.events
        if ev is None or not ev.enabled:
            return
        attrs["node"] = self.node_tag
        ev.record(object_id.binary() if object_id is not None else b"",
                  state, attrs)

    # -- write path ---------------------------------------------------------

    def take_recycled(self, size: int) -> Optional[Tuple[str, int]]:
        """Lease a parked segment whose file can hold ``size`` bytes
        (bounded slack so a huge segment is never burned on a small
        object). Returns (name, file_size) or None."""
        if faultpoints.armed and \
                faultpoints.fire("shm.alloc", size=size) == "miss":
            # alloc fault: the pool reports empty — callers must fall
            # back to a fresh segment exactly as on a real miss
            self.num_recycle_misses += 1
            return None
        now = time.time()
        for name, (fsize, ts) in list(self._lent.items()):
            # Generous horizon: a live-but-slow writer (multi-GiB fill
            # under ASAN/swap) whose lease is reclaimed would seal an
            # orphaned inode; seal() double-checks file existence as
            # the backstop, so this only needs to catch dead writers.
            if now - ts > 600.0:
                del self._lent[name]
                self._unlink(name)
        # Slack bound: a segment is only reused for objects at least
        # half its file size, so untracked tail slack (seal accounts
        # the LOGICAL size) stays <= 1x per live recycled object.
        pick = None
        for name, fsize in self._recycle.items():
            if size <= fsize <= 2 * size:
                pick = (name, fsize)
                break
        if pick is None:
            self.num_recycle_misses += 1
            return None
        name, fsize = pick
        del self._recycle[name]
        self.recycle_bytes -= fsize
        self._lent[name] = (fsize, now)
        self.num_recycle_hits += 1
        return name, fsize

    def release_lease(self, name: str) -> None:
        """Close out an AllocSegment lease that will NOT be sealed
        (failed write/pull) or that an in-process writer seals itself.
        Keeps all lease bookkeeping inside the store."""
        self._lent.pop(name, None)

    def abort_lease(self, name: str) -> None:
        """AbortSegment RPC: a remote writer's fill failed — reclaim
        the lease NOW and re-park the (still warm) segment so the next
        put reuses its pages, instead of waiting for the stale sweep."""
        entry = self._lent.pop(name, None)
        if entry is None:
            return  # already sealed, swept, or never leased here
        self._rec(None, oev.LEASE_ABORTED, {"segment": name})
        self._park_segment(name, entry[0])

    def _park_segment(self, name: str, size_hint: int) -> None:
        """Recycle a freed segment instead of unlinking it (pool
        permitting). ``size_hint`` is the logical object size; the real
        file may be larger (itself recycled) — stat wins."""
        try:
            fsize = os.path.getsize(f"/dev/shm/{name}")
        except OSError:
            fsize = size_hint
        # Size floor: AllocSegment is only requested for puts of
        # >= RECYCLE_MIN_BYTES, so a smaller parked segment can never
        # be leased back (take_recycled needs fsize >= size) — it
        # would only crowd genuinely reusable segments out of the cap.
        if fsize < RECYCLE_MIN_BYTES \
                or self.recycle_bytes + fsize > self.recycle_cap \
                or name in self._recycle:
            self._unlink(name)
            return
        self._recycle[name] = fsize
        self.recycle_bytes += fsize
        self._rec(None, oev.RECYCLED, {"segment": name, "bytes": fsize})

    def _drain_recycle(self, need_bytes: int) -> int:
        """Unlink parked segments oldest-first until ``need_bytes`` are
        released (memory pressure evicts the pool before live data)."""
        freed = 0
        while self._recycle and freed < need_bytes:
            name = next(iter(self._recycle))
            freed += self._recycle.pop(name)
            self._unlink(name)
        self.recycle_bytes -= freed
        return freed

    def seal(self, object_id: ObjectID, segment_name: str, size: int,
             attrs: Optional[dict] = None) -> bool:
        # ``attrs``: extra keys folded into the SEALED object-plane
        # record (e.g. DistributedArray shard placement — rank / mesh
        # coords — so state.list_objects() can show WHERE each shard of
        # a sharded array landed without a second event).
        if faultpoints.armed and faultpoints.fire(
                "shm.seal", oid=object_id.hex(), size=size) == "refuse":
            # seal fault: the store refuses the segment (capacity-style
            # failure) — the writer's abort/error path must run
            self._lent.pop(segment_name, None)
            self._unlink(segment_name)
            return False
        self._lent.pop(segment_name, None)
        if os.path.isdir("/dev/shm") and \
                not os.path.exists(f"/dev/shm/{segment_name}"):
            # The segment vanished before sealing (stale-lease reclaim
            # racing a very slow writer): registering it would create
            # an object every reader fails to attach. Fail the put
            # loudly instead.
            logger.error("seal of %s: segment %s no longer exists",
                         object_id.hex()[:16], segment_name)
            return False
        if object_id in self._objects:
            # Duplicate seal (e.g. task retry): drop the new segment.
            self._park_segment(segment_name, size)
            return True
        if self.used + self.recycle_bytes + size > self.capacity:
            self._evict(self.used + self.recycle_bytes + size
                        - self.capacity)
        if self.used + size > self.capacity:
            self._unlink(segment_name)
            return False
        self._objects[object_id] = (segment_name, size, time.time())
        self._last_access[object_id] = time.time()
        self._exposed.discard(object_id)  # fresh segment, no foreign maps
        self.used += size
        ev_attrs = {"size": size, "segment": segment_name}
        if attrs:
            ev_attrs.update(attrs)
        self._rec(object_id, oev.SEALED, ev_attrs)
        return True

    # -- read path ----------------------------------------------------------

    def lookup(self, object_id: ObjectID) -> Optional[str]:
        entry = self._objects.get(object_id)
        if entry is not None:
            self._last_access[object_id] = time.time()
            return entry[0]
        if object_id in self._spilled:
            return self._restore(object_id)
        return None

    def contains(self, object_id: ObjectID) -> bool:
        return object_id in self._objects or object_id in self._spilled

    def entry(self, object_id: ObjectID) -> Optional[Tuple[str, int]]:
        """(segment_name, logical_size) for a stored object, restoring
        it from spill first if needed; None when unknown. The size is
        the sealed object size, which may be smaller than the segment
        file (recycled segments keep their larger file). NOTE: like
        ``lookup`` (every serve path uses one of the two), a spilled
        object restores SYNCHRONOUSLY on the calling thread — the
        store's tables are loop-confined, so callers on the raylet loop
        pay the restore there; making restore async is a store-wide
        refactor, tracked as future work."""
        name = self.lookup(object_id)
        if name is None:
            return None
        e = self._objects.get(object_id)
        return (name, e[1]) if e is not None else None

    def held_objects(self) -> List[Tuple[ObjectID, float]]:
        """Snapshot of everything this store is accountable for, as
        (object_id, sealed_ts) — the leak detector's sweep input (and a
        public alternative to peeking ``_objects``). SPILLED objects
        are included (ts 0.0: their seal time is long past): an
        orphaned spill file is a disk leak exactly like an orphaned
        segment, and ``free()`` reclaims both."""
        out = [(oid, e[2]) for oid, e in list(self._objects.items())]
        out.extend((oid, 0.0) for oid in list(self._spilled)
                   if oid not in self._objects)
        return out

    # -- pinning (primary copies; owner-driven) ------------------------------

    def pin(self, object_id: ObjectID) -> None:
        if object_id not in self._pinned:
            self._rec(object_id, oev.PINNED, {})
        self._pinned[object_id] = self._pinned.get(object_id, 0) + 1

    def unpin(self, object_id: ObjectID) -> None:
        n = self._pinned.get(object_id, 0) - 1
        if n <= 0:
            self._pinned.pop(object_id, None)
        else:
            self._pinned[object_id] = n

    # -- free / eviction / spilling -----------------------------------------

    def mark_exposed(self, object_id: ObjectID) -> None:
        """The object's segment name left the store server (a worker
        will mmap it): its segment must never be recycled — consumers
        may hold zero-copy views past the free."""
        if object_id not in self._exposed:
            # once per object, not per chunk serve: EXPOSED marks the
            # recycling waiver, which is a one-way transition
            self._rec(object_id, oev.EXPOSED, {})
        self._exposed.add(object_id)

    def free(self, object_id: ObjectID) -> None:
        entry = self._objects.pop(object_id, None)
        self._pinned.pop(object_id, None)
        self._last_access.pop(object_id, None)
        exposed = object_id in self._exposed
        self._exposed.discard(object_id)
        if entry is not None:
            name, size, _ = entry
            self.used -= size
            if exposed:
                # unlink keeps live consumer mappings valid; the pages
                # die with the last view
                self._unlink(name)
            else:
                self._park_segment(name, size)
        spilled = self._spilled.pop(object_id, None)
        if spilled is not None:
            self._delete_spilled(spilled[0])
        if entry is not None or spilled is not None:
            self._rec(object_id, oev.FREED, {})

    def _delete_spilled(self, location: str) -> None:
        if location.startswith("ext:"):
            key = location[4:]
            upload = self._ext_futures.pop(key, None)

            def _del():
                if upload is not None:
                    try:  # the blob may still be uploading
                        upload.result(timeout=60)
                    except Exception:
                        logger.warning("spill upload failed before delete",
                                       exc_info=True)
                try:
                    self._ext.delete(key)
                except Exception:  # noqa: BLE001 — best effort
                    logger.exception("external spill delete failed")

            self._ext_pool.submit(_del)
            return
        try:
            os.unlink(location)
        except OSError:
            pass

    def relieve_memory_pressure(self, need_bytes: int) -> int:
        """Node-memory-watchdog hook (memory_monitor.py): free up to
        ``need_bytes`` of tmpfs pages — recycle pool first (parked
        segments are free memory, not data), then the normal LRU
        evict/spill path. Returns the bytes actually released, so the
        watchdog can tell whether relief resolved the pressure crossing
        before it considers killing a worker."""
        if need_bytes <= 0:
            return 0
        before = self.used + self.recycle_bytes
        self._evict(need_bytes)
        return max(0, before - (self.used + self.recycle_bytes))

    def _evict(self, need_bytes: int) -> None:
        """Evict LRU unpinned objects; pinned primaries are spilled to disk
        instead of dropped when spilling is on. The recycle pool drains
        first — parked segments are free memory, not data."""
        need_bytes -= self._drain_recycle(need_bytes)
        if need_bytes <= 0:
            return
        victims = sorted(
            (oid for oid in self._objects if oid not in self._pinned),
            key=lambda o: self._last_access.get(o, 0.0))
        freed = 0
        for oid in victims:
            if freed >= need_bytes:
                break
            name, size, _ = self._objects.pop(oid)
            self._last_access.pop(oid, None)
            self._exposed.discard(oid)
            self.used -= size
            freed += size
            self.num_evictions += 1
            self._rec(oid, oev.EVICTED, {"size": size})
            self._unlink(name)  # pressure path: actually release pages
        if freed < need_bytes and self.spilling_enabled:
            pinned_victims = sorted(
                (oid for oid in self._objects),
                key=lambda o: self._last_access.get(o, 0.0))
            for oid in pinned_victims:
                if freed >= need_bytes:
                    break
                freed += self._spill(oid)

    def _spill(self, object_id: ObjectID) -> int:
        name, size, _ = self._objects.pop(object_id)
        self._last_access.pop(object_id, None)
        try:
            shm = _QuietSharedMemory(name=name)  # populated: fast read
            if self._ext is not None:
                # copy to RAM + background upload: the loop thread must
                # not block on a network put (the copy's lifetime is
                # bounded by the 2-worker upload pool draining)
                key = f"spill/{object_id.hex()}"
                data = bytes(shm.buf[:size])
                self._ext_futures[key] = self._ext_pool.submit(
                    self._ext.put, key, data)
                location = "ext:" + key
            else:
                location = os.path.join(self.spill_dir, object_id.hex())
                with open(location, "wb") as f:
                    f.write(shm.buf[:size])
            shm.close()
        except Exception:
            logger.exception("spill of %s failed", object_id)
            self._objects[object_id] = (name, size, time.time())
            return 0
        self.used -= size
        self.num_spills += 1
        self._spilled[object_id] = (location, size)
        self._rec(object_id, oev.SPILLED, {"size": size})
        self._unlink(name)
        return size

    def _restore(self, object_id: ObjectID) -> Optional[str]:
        location, size = self._spilled[object_id]
        if self.used + size > self.capacity:
            self._evict(self.used + size - self.capacity)
        try:
            if location.startswith("ext:"):
                key = location[4:]
                upload = self._ext_futures.pop(key, None)
                if upload is not None:  # still in flight: wait it out
                    upload.result(timeout=120)
                data = self._ext.get(key)
                if data is None:
                    raise FileNotFoundError(location)
            else:
                with open(location, "rb") as f:
                    data = f.read()
            name, owner, buf = acquire_segment(
                self.take_recycled(size) if size >= RECYCLE_MIN_BYTES
                else None, max(size, 1))
            self.release_lease(name)  # registered below, in-process
            try:
                native.copy_into(buf, 0, data)
            finally:
                _close_segment_owner(owner, buf)
        except Exception:
            logger.exception("restore of %s failed", object_id)
            return None
        del self._spilled[object_id]
        self._delete_spilled(location)
        self._objects[object_id] = (name, size, time.time())
        self._exposed.discard(object_id)  # restored into a new segment
        self._last_access[object_id] = time.time()
        self.used += size
        self.num_restores += 1
        self._rec(object_id, oev.RESTORED, {"size": size})
        return name

    @staticmethod
    def _unlink(segment_name: str) -> None:
        try:
            shm = shared_memory.SharedMemory(name=segment_name)
            shm.close()
            shm.unlink()
        except FileNotFoundError:
            pass
        except Exception:
            logger.exception("unlink of %s failed", segment_name)

    def shutdown(self) -> None:
        for name, _, _ in self._objects.values():
            self._unlink(name)
        self._objects.clear()
        for name in list(self._recycle):
            self._unlink(name)
        self._recycle.clear()
        self.recycle_bytes = 0
        for name in list(self._lent):
            self._unlink(name)
        self._lent.clear()
        for location, _ in self._spilled.values():
            self._delete_spilled(location)
        self._spilled.clear()
        self.used = 0

    def stats(self) -> dict:
        return {
            "used_bytes": self.used,
            "capacity_bytes": self.capacity,
            "num_objects": len(self._objects),
            "num_pinned": len(self._pinned),
            "num_spilled": len(self._spilled),
            "num_evictions": self.num_evictions,
            "num_spills": self.num_spills,
            "num_restores": self.num_restores,
            # zero-copy put pipeline: warm-segment reuse effectiveness
            "recycle_pool_segments": len(self._recycle),
            "recycle_pool_bytes": self.recycle_bytes,
            "recycle_lent_segments": len(self._lent),
            "recycle_lent_bytes": sum(sz for sz, _ in
                                      self._lent.values()),
            "num_recycle_hits": self.num_recycle_hits,
            "num_recycle_misses": self.num_recycle_misses,
            # consumer-pinned mappings awaiting their views' GC (normal)
            "num_deferred_mappings": deferred_count(),
            # fallback-parked mappings (always 0 in healthy operation)
            "num_zombie_mappings": zombie_count(),
        }
