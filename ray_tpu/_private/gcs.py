"""GCS: the global control service (head process).

Role parity: reference GCS server (src/ray/gcs/gcs_server/) — node table +
liveness (GcsHeartbeatManager), actor table + scheduling + restart policy
(GcsActorManager/GcsActorScheduler), job table (GcsJobManager), KV store
(GcsKvManager / internal KV), pubsub fanout (C27 long-poll pubsub; here:
push messages over persistent subscriber connections), placement groups
(GcsPlacementGroupManager, 2PC reserve/commit against raylets), and a
resource view for scheduling (GcsResourceManager).

State is kept in process memory with an optional append-only journal for
restart recovery (the analog of GcsTableStorage over the in-memory store
client; Redis is deliberately not a dependency).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu._private import faultpoints, protocol, rpc
from ray_tpu._private.config import RayTpuConfig
from ray_tpu._private.events import ClusterEventTable
from ray_tpu._private.ids import ActorID, JobID, NodeID, PlacementGroupID
from ray_tpu._private.object_events import ObjectTable
from ray_tpu._private.task_events import TaskEventTable

# Exported tracing spans live under this KV prefix (util/tracing.py);
# the GCS caps their count (config.tracing_max_spans) with oldest-trace
# eviction so RAY_TPU_TRACE=1 on a long-running cluster cannot leak the
# KV and its journal.
TRACE_KV_PREFIX = b"__traces__/"
TRACE_DROPPED_KEY = b"__rtpu_trace_dropped__"

logger = logging.getLogger(__name__)

# Actor states (reference: rpc::ActorTableData states in gcs.proto).
ACTOR_PENDING = "PENDING_CREATION"
ACTOR_ALIVE = "ALIVE"
ACTOR_RESTARTING = "RESTARTING"
ACTOR_DEAD = "DEAD"

PG_PENDING = "PENDING"
PG_CREATED = "CREATED"
PG_REMOVED = "REMOVED"


# Human-facing cluster status page (reference: dashboard/client React
# app's node/actor/job views — here one dependency-free static page over
# the same /api/* routes, refreshed client-side).
_STATUS_PAGE = b"""<!doctype html>
<html><head><meta charset="utf-8"><title>ray_tpu dashboard</title>
<style>
 body{font:13px/1.5 system-ui,sans-serif;margin:1.2em;color:#222}
 h1{font-size:18px} h2{font-size:14px;margin:1.2em 0 .3em}
 table{border-collapse:collapse;width:100%;margin-bottom:.6em}
 th,td{border:1px solid #ccc;padding:2px 8px;text-align:left;
       font:12px/1.4 ui-monospace,monospace}
 th{background:#f0f0f0} .dead{color:#b00} .alive{color:#070}
 #err{color:#b00}
</style></head><body>
<h1>ray_tpu cluster <span id="ts"></span></h1><div id="err"></div>
<h2>Cluster</h2><table id="cluster"></table>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Object stores / hosts</h2><table id="stores"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Objects</h2><table id="objects"></table>
<h2>Tasks</h2><table id="tasks"></table>
<h2>Jobs</h2><table id="jobs"></table>
<h2>Placement groups</h2><table id="pgs"></table>
<h2>RPC methods (cluster-wide)</h2><table id="rpc"></table>
<h2>Recent events</h2><table id="events"></table>
<script>
function row(tr, cells, tag) {
  var r = document.createElement('tr');
  cells.forEach(function(c){
    var td = document.createElement(tag||'td');
    if (c && c.cls) { td.textContent = c.v; td.className = c.cls; }
    else if (c && c.links) {
      // injection-safe anchors: node ids come from registration data
      c.links.forEach(function(l, i){
        if (i) td.appendChild(document.createTextNode(' '));
        var a = document.createElement('a');
        a.href = l.href; a.textContent = l.text;
        td.appendChild(a);
      });
    }
    else td.textContent = (typeof c === 'object') ? JSON.stringify(c) : c;
    r.appendChild(td);
  });
  tr.appendChild(r);
}
function fill(id, hdr, rows) {
  var t = document.getElementById(id); t.innerHTML = '';
  row(t, hdr, 'th'); rows.forEach(function(r){ row(t, r); });
}
async function tick() {
  try {
    var j = async function(p){ return (await fetch(p)).json(); };
    var c = await j('/api/cluster');
    fill('cluster', Object.keys(c), [Object.values(c)]);
    var nodes = await j('/api/nodes');
    fill('nodes', ['node_id','address','state','cpu_avail/total',
                   'heartbeat_age_s','logs'],
      nodes.map(function(n){ return [n.node_id.slice(0,12), n.address,
        {v: n.alive ? 'ALIVE' : 'DEAD', cls: n.alive ? 'alive' : 'dead'},
        (n.resources_available.CPU||0)+'/'+(n.resources_total.CPU||0),
        n.last_heartbeat_age_s,
        {links: [
          {href: '/api/logs?node=' + encodeURIComponent(n.node_id),
           text: 'tail'},
          {href: '/api/stacks?node=' + encodeURIComponent(n.node_id),
           text: 'stacks'}]}]; }));
    var mb = function(b){ return b==null ? '' : (b/1048576).toFixed(1); };
    fill('stores', ['node_id','workers','pending','store_mb','objects',
                    'pinned','recycle_mb','lent','pull_mb','leaked',
                    'spills','evictions','host_cpu%','host_mem_mb'],
      nodes.map(function(n){ var s = n.stats || {};
        return [n.node_id.slice(0,12), s.num_workers,
          s.num_pending_leases,
          mb(s.store_used_bytes) + '/' + mb(s.store_capacity_bytes),
          s.store_num_objects, s.store_num_pinned,
          mb(s.store_recycle_bytes), s.store_lent_segments,
          mb(s.data_plane_inflight_bytes),
          {v: s.objects_leaked || 0,
           cls: s.objects_leaked ? 'dead' : ''},
          s.store_num_spills,
          s.store_num_evictions, s.host_cpu_percent,
          mb(s.host_mem_used_bytes) + '/' +
          mb(s.host_mem_total_bytes)]; }));
    var actors = await j('/api/actors');
    fill('actors', ['actor_id','name','class','state','restarts','node'],
      actors.map(function(a){ return [a.actor_id.slice(0,12), a.name,
        a.class_name, a.state, a.num_restarts+'/'+a.max_restarts,
        a.node_id.slice(0,12)]; }));
    var ob = await j('/api/objects');
    fill('objects', ['object_id','owner','size_mb','state','leaked',
                     'transitions'],
      ob.objects.slice(-25).reverse().map(function(o){ return [
        o.object_id.slice(0,12), o.owner, mb(o.size), o.state,
        {v: o.leaked ? 'LEAKED' : '', cls: o.leaked ? 'dead' : ''},
        o.events.length]; }));
    var tk = await j('/api/tasks');
    fill('tasks', ['task_id','name','state','attempt','transitions'],
      tk.tasks.slice(-25).reverse().map(function(t){ return [
        t.task_id.slice(0,12), t.name, t.state, t.attempt,
        t.events.length]; }));
    var jobs = await j('/api/jobs');
    fill('jobs', jobs.length ? Object.keys(jobs[0]) : ['job_id'],
      jobs.map(function(x){ return Object.values(x); }));
    var pgs = await j('/api/placement_groups');
    fill('pgs', ['pg_id','name','strategy','state','bundles'],
      pgs.map(function(p){ return [p.pg_id.slice(0,12), p.name||'',
        p.strategy, p.state, p.bundles]; }));
    var rpc = await j('/api/rpc');
    var meths = Object.keys(rpc.summary).sort();
    fill('rpc', ['method','count','errors','inflight','max_ms',
                 'exec_p99_ms','queue_p99_ms','mb_in','mb_out'],
      meths.map(function(m){ var d = rpc.summary[m];
        return [m, d.count, d.errors, d.inflight, d.max_ms,
          d.exec_p99_ms, d.queue_p99_ms, mb(d.bytes_in),
          mb(d.bytes_out)]; }));
    var evs = await j('/api/events');
    fill('events', ['seq','time','severity','label','source','message'],
      (evs.events||[]).slice(-25).reverse().map(function(e){ return [
        e.seq, new Date(e.timestamp*1000).toLocaleTimeString(),
        e.severity, e.label, e.source_type, e.message]; }));
    document.getElementById('ts').textContent =
      '- ' + new Date().toLocaleTimeString();
    document.getElementById('err').textContent = '';
  } catch (e) { document.getElementById('err').textContent = 'refresh failed: ' + e; }
}
tick(); setInterval(tick, 5000);
</script></body></html>
"""


class NodeEntry:
    def __init__(self, node_id: bytes, address: str, resources: Dict[str, float],
                 node_name: str = "", data_address: str = ""):
        self.node_id = node_id
        self.address = address
        # bulk-transfer (data plane) endpoint; "" = peer pulls from this
        # node ride the control-plane chunk path
        self.data_address = data_address
        self.node_name = node_name
        self.resources_total = dict(resources)
        self.resources_available = dict(resources)
        self.last_heartbeat = time.time()
        self.alive = True
        self.conn: Optional[rpc.Connection] = None
        self.stats: dict = {}  # last heartbeat-piggybacked node stats
        # RegisterNode version handshake: what the node advertised and
        # what both sides agreed to speak (rolling upgrades: min of the
        # two; a pre-versioning raylet registers as version 1)
        self.protocol_version = protocol.MIN_PROTOCOL_VERSION
        self.negotiated_protocol_version = protocol.MIN_PROTOCOL_VERSION


class ActorEntry:
    def __init__(self, actor_id: bytes, spec_header: dict, spec_frames: List[bytes],
                 name: str = "", namespace: str = "", max_restarts: int = 0,
                 job_id: bytes = b""):
        self.actor_id = actor_id
        self.spec_header = spec_header
        self.spec_frames = spec_frames
        self.name = name
        self.namespace = namespace
        self.max_restarts = max_restarts
        self.num_restarts = 0
        self.job_id = job_id
        self.state = ACTOR_PENDING
        self.address = ""          # actor worker's RPC address once alive
        self.node_id = b""
        self.death_cause = ""
        # Structured death cause (see exceptions.ActorDiedError.cause):
        # {"kind", "message", "node_id", "worker_id", "restarts",
        #  "max_restarts", "last_failure"} — journaled with the actor so
        # post-restart lookups still explain the death.
        self.death_info: dict = {}
        self.incarnation = 0


class GcsServer:
    def __init__(self, config: RayTpuConfig):
        self.config = config
        self.nodes: Dict[bytes, NodeEntry] = {}
        self.actors: Dict[bytes, ActorEntry] = {}
        self.named_actors: Dict[Tuple[str, str], bytes] = {}
        self.jobs: Dict[bytes, dict] = {}
        self.kv: Dict[bytes, bytes] = {}
        self.placement_groups: Dict[bytes, dict] = {}
        self._job_counter = itertools.count(1)
        self._subscribers: Dict[str, List[rpc.Connection]] = {}
        self._server = rpc.RpcServer(self._handlers(), name="gcs")
        self._node_rr = 0
        self._monitor_task: Optional[asyncio.Task] = None
        self._profile_events: List[dict] = []
        # Cluster-event plane (events.py): the capped, eviction-counted
        # queryable table behind state.list_cluster_events() and
        # /api/events. Fed by heartbeat piggybacks (raylets),
        # AddClusterEvents batches (workers/drivers) and the GCS's own
        # emissions (node death, restarts).
        self.cluster_events = ClusterEventTable(
            getattr(config, "cluster_events_max", 10_000))
        # Per-reporter RPC telemetry (rpc.py flight recorder): raylets
        # ship on the heartbeat, workers/drivers via
        # ReportRpcTelemetry; read by state.list_rpc()/summary_rpc(),
        # /api/rpc and timeline()'s cat="rpc" slices.
        self.rpc_telemetry = rpc.RpcTelemetryTable()
        # Process-wide telemetry config (shared module state: an
        # in-process head shares it with the raylet/driver anyway).
        rpc.telemetry.configure(config)
        # Optional append-only journal (reference: GcsTableStorage +
        # GcsInitData reload) — enabled via config.gcs_journal_path.
        self.journal = None
        # Observability: per-reporter user-metric snapshots (reference:
        # per-node MetricsAgent re-exporting Prometheus,
        # python/ray/_private/metrics_agent.py:61) and the HTTP
        # endpoint serving the merged cluster view.
        self._metric_snapshots: Dict[str, dict] = {}
        self._http_server = None
        self.metrics_address = ""
        # Task-lifecycle table (task_events.py): per-task transition
        # histories with a capped per-job index; fed by AddTaskEvents
        # batches and heartbeat piggybacks, read by the state API,
        # timeline export and the /api/tasks dashboard route.
        self.task_events = TaskEventTable(
            config.task_events_max_tasks_per_job)
        # Object-lifecycle table (object_events.py): the object-plane
        # twin — fed by AddObjectEvents batches and heartbeat
        # piggybacks, read by state.list_objects()/summary_objects()/
        # memory_summary(), timeline() and /api/objects.
        self.object_events = ObjectTable(
            config.object_events_max_objects_per_job)
        # Tracing-span KV cap bookkeeping: trace_id -> {key: True}
        # (insertion-ordered = first-span-seen order, the eviction
        # order), plus honest drop accounting.
        self._trace_keys: Dict[bytes, Dict[bytes, bool]] = {}
        self._trace_span_count = 0
        self.trace_spans_dropped = 0

    # ------------------------------------------------------------------ wiring

    def _handlers(self):
        return {
            "RegisterNode": self.handle_register_node,
            "Heartbeat": self.handle_heartbeat,
            "GetAllNodeInfo": self.handle_get_all_node_info,
            "DrainNode": self.handle_drain_node,
            "RegisterActor": self.handle_register_actor,
            "ReportActorAlive": self.handle_report_actor_alive,
            "ReportActorDeath": self.handle_report_actor_death,
            "GetActorInfo": self.handle_get_actor_info,
            "GetNamedActor": self.handle_get_named_actor,
            "ListNamedActors": self.handle_list_named_actors,
            "KillActor": self.handle_kill_actor,
            "AddJob": self.handle_add_job,
            "MarkJobFinished": self.handle_mark_job_finished,
            "GetAllJobInfo": self.handle_get_all_job_info,
            "KVPut": self.handle_kv_put,
            "KVGet": self.handle_kv_get,
            "KVDel": self.handle_kv_del,
            "KVKeys": self.handle_kv_keys,
            "KVGetPrefix": self.handle_kv_get_prefix,
            "Subscribe": self.handle_subscribe,
            "Publish": self.handle_publish,
            "CreatePlacementGroup": self.handle_create_placement_group,
            "RemovePlacementGroup": self.handle_remove_placement_group,
            "GetPlacementGroup": self.handle_get_placement_group,
            "GetAllPlacementGroups": self.handle_get_all_placement_groups,
            "ReportResourceUsage": self.handle_report_resource_usage,
            "GetClusterResources": self.handle_get_cluster_resources,
            "AddProfileEvents": self.handle_add_profile_events,
            "GetProfileEvents": self.handle_get_profile_events,
            "AddTaskEvents": self.handle_add_task_events,
            "GetTaskEvents": self.handle_get_task_events,
            "GetTaskSummary": self.handle_get_task_summary,
            "AddObjectEvents": self.handle_add_object_events,
            "GetObjectEvents": self.handle_get_object_events,
            "GetObjectSummary": self.handle_get_object_summary,
            "AddClusterEvent": self.handle_add_cluster_event,
            "AddClusterEvents": self.handle_add_cluster_events,
            "GetClusterEvents": self.handle_get_cluster_events,
            "ReportRpcTelemetry": self.handle_report_rpc_telemetry,
            "GetRpcTelemetry": self.handle_get_rpc_telemetry,
            "ReportMetrics": self.handle_report_metrics,
            "GetNodeStatsSummary": self.handle_get_node_stats_summary,
        }

    async def start(self, address: str = "") -> str:
        journal_path = getattr(self.config, "gcs_journal_path", "")
        if journal_path:
            replayed = self._replay_journal(journal_path)
            from ray_tpu._private.gcs_storage import GcsJournal
            self.journal = GcsJournal(journal_path)
            # Boot-time compaction: replaying history once is enough —
            # snapshot the rebuilt tables so the next restart is O(state).
            self._compact_journal()
            if replayed:
                # a non-empty replay means this GCS came back from a
                # previous incarnation: record the restart in the (new,
                # in-memory — bounded loss by design) event table
                self._emit_cluster_event(
                    "WARNING", "GCS_RESTARTED",
                    f"GCS restarted: replayed {replayed} journal "
                    f"records", replayed_records=replayed)
        addr = await self._server.listen(address)
        self._monitor_task = asyncio.get_running_loop().create_task(
            self._liveness_monitor())
        try:
            await self._start_metrics_http(addr)
        except OSError as e:
            # A port conflict degrades observability; it must not take
            # down the control plane.
            logger.warning("metrics endpoint failed to bind: %s", e)
        # Actors caught mid-scheduling by a crash (journaled PENDING /
        # RESTARTING) need their scheduling loop restarted — raylets
        # re-register within the loop's retry window.
        for actor in self.actors.values():
            if actor.state in (ACTOR_PENDING, ACTOR_RESTARTING):
                rpc.spawn_logged(self._schedule_actor(actor),
                                 "gcs-schedule-actor")
        logger.info("GCS listening at %s", addr)
        return addr

    async def stop(self):
        if self._monitor_task:
            self._monitor_task.cancel()
        if self._http_server is not None:
            self._http_server.close()
        await self._server.close()
        if self.journal is not None:
            self.journal.close()

    # -------------------------------------------------------- observability

    async def _start_metrics_http(self, rpc_addr: str) -> None:
        """Prometheus text endpoint (reference: metrics agent export on
        metrics_export_port, metrics_agent.py:61). Serves the merged
        built-in + user metrics on GET /metrics."""
        # rpc_addr is "tcp://host:port" or "unix://path"
        if rpc_addr.startswith("tcp://"):
            host = rpc_addr[len("tcp://"):].rsplit(":", 1)[0]
        else:
            host = "127.0.0.1"
        port = getattr(self.config, "metrics_export_port", 0)
        self._http_server = await asyncio.start_server(
            self._handle_http, host, port)
        bound = self._http_server.sockets[0].getsockname()
        self.metrics_address = f"{host}:{bound[1]}"
        self.kv[b"__rtpu_metrics_address__"] = self.metrics_address.encode()

    async def _handle_http(self, reader, writer):
        try:
            request = await asyncio.wait_for(reader.readline(), 10)
            while True:  # drain headers
                line = await asyncio.wait_for(reader.readline(), 10)
                if line in (b"\r\n", b"\n", b""):
                    break
            path = request.split(b" ")[1] if request.count(b" ") else b"/"
            if path.startswith(b"/metrics"):
                body = self._render_metrics().encode()
                status, ctype = b"200 OK", b"text/plain; version=0.0.4"
            elif path.startswith(b"/api/"):
                body, status = await self._dashboard_api(
                    path.decode("latin-1", errors="replace"))
                ctype = b"application/json"
            elif path in (b"/", b"/index.html", b"/dashboard"):
                body = _STATUS_PAGE
                status, ctype = b"200 OK", b"text/html; charset=utf-8"
            else:
                body = (b"ray_tpu head: status page at /; scrape /metrics; "
                        b"dashboard API under /api/ (nodes|actors|jobs|"
                        b"cluster|placement_groups|metrics|logs|stacks|"
                        b"serve)\n")
                status, ctype = b"200 OK", b"text/plain"
            writer.write(b"HTTP/1.1 " + status +
                         b"\r\nContent-Type: " + ctype +
                         b"\r\nContent-Length: " +
                         str(len(body)).encode() +
                         b"\r\nConnection: close\r\n\r\n" + body)
            await writer.drain()
        # raylint: disable=exception-hygiene — malformed scrape: HTTP endpoint must never take down the GCS
        except Exception:
            pass
        finally:
            try:
                writer.close()
            except OSError:
                pass  # peer already gone

    async def _dashboard_api(self, path: str):
        """Dashboard-lite: JSON cluster state straight off the GCS
        tables (reference: dashboard/head.py + datacenter.py aggregate
        the same node/actor/job views; the human-facing view is the
        static status page at ``/`` rendering these routes). ``/api/
        logs`` and ``/api/stacks`` proxy to the node's raylet for
        per-node depth (reference: dashboard/modules/log + `ray
        stack`)."""
        import json
        from urllib.parse import parse_qs

        def dump(obj):
            return json.dumps(obj, default=str).encode(), b"200 OK"

        route, _, qs = path.partition("?")
        route = route.rstrip("/")
        params = {k: v[0] for k, v in parse_qs(qs).items()}
        if route in ("/api/logs", "/api/stacks"):
            node = None
            want = params.get("node", "")
            for n in self.nodes.values():
                if n.alive and (not want or n.node_id.hex().startswith(want)):
                    node = n
                    break
            if node is None:
                return dump({"error": f"no alive node matching {want!r}"})
            from ray_tpu._private import rpc as rpc_mod
            try:
                conn = await rpc_mod.connect(node.address,
                                             peer_name="dashboard")
                try:
                    if route == "/api/stacks":
                        reply, _b = await conn.call(
                            "DumpWorkerStacks", {}, timeout=15.0)
                        reply.pop("node_id", None)
                        reply["node"] = node.node_id.hex()
                    else:
                        reply, _b = await conn.call("GetLogs", {
                            "name": params.get("name", ""),
                            "tail": params.get("tail", "200"),
                        }, timeout=10.0)
                        reply["node"] = node.node_id.hex()
                    return dump(reply)
                finally:
                    # shield: a cancelled dashboard request must still
                    # finish closing the one-shot raylet conn, or the
                    # socket and its recv task leak
                    await asyncio.shield(conn.close())
            except (ConnectionError, asyncio.TimeoutError) as e:
                return dump({"error": f"raylet unreachable: {e}"})
        if route == "/api/nodes":
            return dump([{
                "node_id": n.node_id.hex(), "address": n.address,
                "node_name": n.node_name, "alive": n.alive,
                "resources_total": n.resources_total,
                "resources_available": n.resources_available,
                "last_heartbeat_age_s":
                    round(time.time() - n.last_heartbeat, 3),
                "stats": n.stats,
                "protocol_version": n.protocol_version,
                "negotiated_protocol_version":
                    n.negotiated_protocol_version,
            } for n in self.nodes.values()])
        if route == "/api/actors":
            return dump([{
                "actor_id": a.actor_id.hex(), "name": a.name,
                "namespace": a.namespace, "state": a.state,
                "class_name": a.spec_header.get("name", ""),
                "node_id": a.node_id.hex() if a.node_id else "",
                "address": a.address,
                "num_restarts": a.num_restarts,
                "max_restarts": a.max_restarts,
                "death_kind": a.death_info.get("kind", ""),
                "job_id": a.job_id.hex() if a.job_id else "",
            } for a in self.actors.values()])
        if route == "/api/jobs":
            return dump([{
                "job_id": job_id.hex(), **{
                    k: v for k, v in record.items()
                    if isinstance(v, (str, int, float, bool, type(None)))}
            } for job_id, record in self.jobs.items()])
        if route == "/api/placement_groups":
            return dump([{
                "pg_id": pg_id.hex(),
                **{k: v for k, v in pg.items()
                   if k in ("name", "strategy", "state")},
                "bundles": pg.get("bundles"),
            } for pg_id, pg in self.placement_groups.items()])
        if route == "/api/cluster":
            total: Dict[str, float] = {}
            avail: Dict[str, float] = {}
            for n in self.nodes.values():
                if not n.alive:
                    continue
                for k, v in n.resources_total.items():
                    total[k] = total.get(k, 0.0) + v
                for k, v in n.resources_available.items():
                    avail[k] = avail.get(k, 0.0) + v
            return dump({
                "nodes_alive": sum(1 for n in self.nodes.values()
                                   if n.alive),
                "nodes_total": len(self.nodes),
                "actors": len(self.actors),
                "jobs": len(self.jobs),
                "placement_groups": len(self.placement_groups),
                "resources_total": total,
                "resources_available": avail,
            })
        if route == "/api/tasks":
            try:
                limit = int(params.get("limit", "200"))
            except ValueError:
                limit = 200
            return dump({
                "tasks": self.task_events.list(
                    state=params.get("state"),
                    name=params.get("name"),
                    node=params.get("node"),
                    limit=limit),
                "summary": self.task_events.summary(),
            })
        if route == "/api/objects":
            try:
                limit = int(params.get("limit", "200"))
            except ValueError:
                limit = 200
            leaked = params.get("leaked")
            return dump({
                "objects": self.object_events.list(
                    state=params.get("state"),
                    owner=params.get("owner"),
                    node=params.get("node"),
                    leaked={"1": True, "true": True, "0": False,
                            "false": False}.get(str(leaked).lower())
                    if leaked is not None else None,
                    limit=limit),
                "summary": self.object_events.summary(),
            })
        if route == "/api/metrics":
            return dump(self._merged_metrics())
        if route == "/api/events":
            # structured cluster events off the capped table (the
            # dashboard's event module analog), filterable like
            # state.list_cluster_events()
            try:
                limit = int(params.get("limit", "200"))
            except ValueError:
                limit = 200
            return dump({
                "events": self.cluster_events.list(
                    severity=params.get("severity"),
                    label=params.get("label"),
                    source=params.get("source"),
                    node=params.get("node"),
                    limit=limit),
                "summary": self.cluster_events.summary(),
            })
        if route == "/api/serve":
            # serving front door: the controller's published deployment
            # view (GCS KV, see serve/controller.py SERVE_STATE_KEY)
            # joined with the per-router serve metrics. Gauges sum
            # across routers (each router owns its label set; the
            # cluster view is the total queue/in-flight).
            state = {}
            raw = self.kv.get(b"serve:state")
            if raw:
                try:
                    state = json.loads(raw)
                except ValueError:
                    state = {"error": "unparseable serve:state"}
            merged = self._merged_metrics()
            per_dep: Dict[str, Dict[str, float]] = {}
            gauge_of = {"ray_tpu_serve_inflight": "inflight",
                        "ray_tpu_serve_queue_depth": "queue_depth"}
            counter_of = {"ray_tpu_serve_requests_total": "requests",
                          "ray_tpu_serve_shed_total": "shed",
                          "ray_tpu_serve_ingress_shm_total":
                              "ingress_shm"}
            for metric, field in {**gauge_of, **counter_of}.items():
                m = merged.get(metric)
                if not m:
                    continue
                for pairs, value in m["values"]:
                    labels = dict(tuple(p) for p in pairs)
                    dep = labels.get("deployment", "")
                    row = per_dep.setdefault(dep, {})
                    row[field] = row.get(field, 0.0) + value
            lat = merged.get("ray_tpu_serve_request_seconds")
            return dump({
                "routes": state.get("routes", {}),
                "deployments": state.get("deployments", {}),
                "load": per_dep,
                "latency_histogram": lat,
            })
        if route == "/api/rpc":
            # the control-plane flight recorder: per-(reporter, side,
            # method) rows + cluster-wide per-method aggregate + the
            # slow-call ring
            self._rpc_telemetry_self_row()
            t = self.rpc_telemetry
            return dump({
                "rpc": t.rows(method=params.get("method"),
                              reporter=params.get("reporter"),
                              side=params.get("side")),
                "summary": t.summary(),
                "loops": t.loops(),
                "slow_calls": list(t.slow_calls)[-200:],
                "slow_calls_dropped": t.slow_dropped,
            })
        return (json.dumps({"error": f"unknown route {route!r}"}).encode(),
                b"404 Not Found")

    def _builtin_metrics(self) -> dict:
        """Cluster-state gauges computed from GCS tables + per-node
        stats piggybacked on heartbeats (reference: metric_defs.h
        gauges like LocalAvailableResource/ObjectStoreUsedMemory)."""
        g = {}

        def gauge(name, desc, values):
            g[name] = {"kind": "gauge", "description": desc,
                       "boundaries": [],
                       "values": [[list(k), v] for k, v in values]}

        gauge("ray_tpu_gcs_nodes_alive", "Live raylet count",
              [((), float(sum(1 for n in self.nodes.values() if n.alive)))])
        by_state: Dict[str, int] = {}
        for a in self.actors.values():
            by_state[a.state] = by_state.get(a.state, 0) + 1
        gauge("ray_tpu_gcs_actors", "Actors by state",
              [(((("state", s),)), float(c)) for s, c in by_state.items()])
        gauge("ray_tpu_gcs_jobs", "Registered jobs",
              [((), float(len(self.jobs)))])
        gauge("ray_tpu_gcs_placement_groups", "Placement groups",
              [((), float(len(self.placement_groups)))])
        node_gauges = [
            ("num_workers", "ray_tpu_node_workers", "Worker processes"),
            ("num_pending_leases", "ray_tpu_node_pending_leases",
             "Lease requests queued"),
            ("num_leases_granted", "ray_tpu_node_leases_granted_total",
             "Legacy (request/grant) leases granted"),
            ("num_credit_grants", "ray_tpu_node_lease_credits_total",
             "Streamed lease credits granted"),
            ("num_credit_revoked",
             "ray_tpu_node_lease_credits_revoked_total",
             "Streamed lease credits revoked/reclaimed"),
            ("num_credit_windows", "ray_tpu_node_credit_windows",
             "Live streaming-lease credit windows"),
            ("num_spillbacks", "ray_tpu_node_spillbacks_total",
             "Lease requests spilled to other nodes"),
            ("store_used_bytes", "ray_tpu_object_store_bytes_used",
             "Shared-memory store bytes in use"),
            ("store_num_objects", "ray_tpu_object_store_objects",
             "Objects resident in the store"),
            ("store_num_spills", "ray_tpu_object_store_spills_total",
             "Objects spilled to external storage"),
            ("store_num_evictions", "ray_tpu_object_store_evictions_total",
             "Objects evicted from the store"),
            # object-plane occupancy truth (ISSUE 13): recycle pool,
            # lent (AllocSegment) leases, pinned primaries, data-plane
            # admission in flight, and the leak-detector verdicts
            ("store_recycle_bytes", "ray_tpu_object_store_recycle_bytes",
             "Segment recycle-pool bytes parked"),
            ("store_lent_segments", "ray_tpu_object_store_lent_segments",
             "Segments lent to writers (unsealed AllocSegment leases)"),
            ("store_lent_bytes", "ray_tpu_object_store_lent_bytes",
             "Bytes lent to writers (unsealed AllocSegment leases)"),
            ("store_num_pinned", "ray_tpu_object_store_pinned",
             "Pinned primary copies resident in the store"),
            ("data_plane_inflight_bytes",
             "ray_tpu_data_plane_pull_inflight_bytes",
             "Cross-node pull bytes admitted and in flight"),
            ("objects_leaked", "ray_tpu_objects_leaked",
             "Store-held objects whose owner holds no reference"),
            ("leak_reclaims", "ray_tpu_objects_leak_reclaims_total",
             "Leaked objects reclaimed by the sweep"),
            # instrumented-event-loop truth (rpc.py _LoopProbe): lag a
            # READY callback waits on each node's raylet loop
            ("loop_lag_p50_ms", "ray_tpu_loop_lag_p50_ms",
             "Event-loop scheduling delay p50 (ms)"),
            ("loop_lag_p99_ms", "ray_tpu_loop_lag_p99_ms",
             "Event-loop scheduling delay p99 (ms)"),
            ("loop_lag_max_ms", "ray_tpu_loop_lag_max_ms",
             "Event-loop scheduling delay windowed max (ms)"),
            ("loop_slow_callbacks", "ray_tpu_loop_slow_callbacks_total",
             "Handlers/callbacks over loop_slow_callback_threshold_ms"),
            # host stats collected by the raylet via psutil (reference:
            # reporter_agent.py:126)
            ("host_cpu_percent", "ray_tpu_node_cpu_percent",
             "Host CPU utilization"),
            ("host_mem_used_bytes", "ray_tpu_node_mem_used_bytes",
             "Host memory used"),
            ("host_mem_total_bytes", "ray_tpu_node_mem_total_bytes",
             "Host memory total"),
            ("host_disk_used_bytes", "ray_tpu_node_disk_used_bytes",
             "Session-dir disk used"),
            ("raylet_rss_bytes", "ray_tpu_raylet_rss_bytes",
             "Raylet process RSS"),
        ]
        for key, name, desc in node_gauges:
            vals = []
            for n in self.nodes.values():
                if n.alive and key in n.stats:
                    vals.append(((("node", n.node_id.hex()[:12]),),
                                 float(n.stats[key])))
            if vals:
                gauge(name, desc, vals)
        return g

    def _merged_metrics(self) -> dict:
        """Reporter snapshots (TTL-pruned) + builtin gauges, shared by
        the Prometheus rendering and the /api/metrics JSON view."""
        from ray_tpu._private import metrics as metrics_mod

        cutoff = time.time() - self.METRIC_SNAPSHOT_TTL_S
        for key in [k for k, (ts, _) in self._metric_snapshots.items()
                    if ts < cutoff]:
            del self._metric_snapshots[key]
        snaps = [s for _, s in self._metric_snapshots.values()]
        if not metrics_mod.core_reporter():
            # standalone GCS process: no CoreWorker ships this
            # process's registry or RPC histograms — merge its own
            # per-method latency histograms here (an in-process head's
            # driver ships the shared snapshot under its reporter id)
            snaps = snaps + [rpc.telemetry.prom_snapshot()]
        merged = metrics_mod.merge_snapshots(snaps)
        merged.update(self._builtin_metrics())
        # the GCS process's own loop lag (per-node raylet lag rides the
        # heartbeat stats -> node gauges above)
        lp = rpc.telemetry.loop_probe("gcs").snapshot()
        merged["ray_tpu_gcs_loop_lag_p99_ms"] = {
            "kind": "gauge",
            "description": "GCS event-loop scheduling delay p99 (ms)",
            "boundaries": [],
            "values": [[[], float(lp["lag"].get("p99_ms", 0.0))]]}
        return merged

    def _render_metrics(self) -> str:
        from ray_tpu._private import metrics as metrics_mod

        return metrics_mod.render_prometheus(self._merged_metrics())

    # Reporters that stop reporting (dead workers) age out: their
    # gauges must not be served forever, nor their snapshots leak.
    METRIC_SNAPSHOT_TTL_S = 60.0

    async def handle_report_metrics(self, conn, header, bufs):
        self._metric_snapshots[header["reporter_id"]] = (
            time.time(), header["snapshot"])
        return {"ok": True}

    async def handle_get_node_stats_summary(self, conn, header, bufs):
        return {"nodes": [{
            "node_id": n.node_id, "address": n.address, "alive": n.alive,
            "node_name": n.node_name,
            "resources_total": n.resources_total,
            "resources_available": n.resources_available,
            "stats": n.stats,
        } for n in self.nodes.values()]}

    # ----------------------------------------------------------- persistence

    # Compact once the live journal exceeds this size (snapshot of the
    # current tables replaces the full history).
    JOURNAL_COMPACT_BYTES = 32 * 1024 * 1024

    def _journal_append(self, op: str, payload):
        if self.journal is not None:
            self.journal.append(op, payload)
            if faultpoints.armed:
                # crash window: the record is durable but the client's
                # reply is not out yet — a ``kill`` here is the
                # canonical "did my mutation land?" failure; client
                # retries must be idempotent against the replayed state
                faultpoints.fire("gcs.journal.append", op=op)
            if self.journal.size() > self.JOURNAL_COMPACT_BYTES:
                self._compact_journal()

    def _snapshot_records(self):
        """Current tables as replayable records (compaction payload)."""
        records = []
        for job_id, record in self.jobs.items():
            records.append(("job_add", {
                "job_id": job_id, "record": record,
                "job_num": JobID(job_id).int_value()}))
        for key, value in self.kv.items():
            records.append(("kv_put", {"key": key, "value": value}))
        for actor in self.actors.values():
            records.append(("actor_register", {
                "actor_id": actor.actor_id, "spec": actor.spec_header,
                "frames": actor.spec_frames, "name": actor.name,
                "namespace": actor.namespace,
                "max_restarts": actor.max_restarts,
                "job_id": actor.job_id}))
            records.append(("actor_update", {
                "actor_id": actor.actor_id, "state": actor.state,
                "address": actor.address, "node_id": actor.node_id,
                "incarnation": actor.incarnation,
                "num_restarts": actor.num_restarts,
                "max_restarts": actor.max_restarts,
                "death_cause": actor.death_cause,
                "death_info": actor.death_info}))
        for pg_id, record in self.placement_groups.items():
            records.append(("pg_upsert", {"pg_id": pg_id, "record": record}))
        return records

    def _compact_journal(self):
        if self.journal is None:
            return
        before = self.journal.size()
        self.journal.rewrite(self._snapshot_records())
        logger.info("GCS journal compacted: %d -> %d bytes", before,
                    self.journal.size())

    def _journal_actor(self, actor: "ActorEntry"):
        """Persist an actor's full mutable state (replayed last-wins)."""
        self._journal_append("actor_update", {
            "actor_id": actor.actor_id, "state": actor.state,
            "address": actor.address, "node_id": actor.node_id,
            "incarnation": actor.incarnation,
            "num_restarts": actor.num_restarts,
            "max_restarts": actor.max_restarts,
            "death_cause": actor.death_cause,
            "death_info": actor.death_info,
        })

    def _replay_journal(self, path: str):
        """Rebuild tables from the journal (reference: GcsInitData load on
        gcs_server restart). Nodes are NOT replayed — live raylets
        re-register over fresh connections."""
        from ray_tpu._private import gcs_storage

        n = 0
        max_job = 0
        for op, p in gcs_storage.replay(path):
            n += 1
            if faultpoints.armed:
                # replay-time crash window: a GCS that dies mid-replay
                # must come back to a consistent (prefix) state on the
                # next boot — the journal is append-only, so any prefix
                # is valid
                faultpoints.fire("gcs.journal.replay", op=op, n=n)
            if op == "job_add":
                self.jobs[p["job_id"]] = p["record"]
                max_job = max(max_job, p.get("job_num", 0))
            elif op == "job_finish":
                job = self.jobs.get(p["job_id"])
                if job:
                    job["finished"] = True
            elif op == "kv_put":
                self.kv[p["key"]] = p["value"]
                if p["key"] == TRACE_DROPPED_KEY:
                    # carry the pre-restart drop total forward (max:
                    # replay-time evictions below may already have
                    # advanced the in-process counter)
                    try:
                        self.trace_spans_dropped = max(
                            self.trace_spans_dropped, int(p["value"]))
                    except ValueError:
                        pass
                elif p["key"].startswith(TRACE_KV_PREFIX):
                    # rebuild the span-cap index so the cap survives a
                    # restart (replay runs before the journal reopens,
                    # so eviction here deletes without re-journaling;
                    # the boot-time compaction snapshots the result)
                    self._note_trace_span(p["key"])
            elif op == "kv_del":
                self.kv.pop(p["key"], None)
                if p["key"].startswith(TRACE_KV_PREFIX):
                    self._unindex_trace_key(p["key"])
            elif op == "actor_register":
                actor = ActorEntry(
                    actor_id=p["actor_id"], spec_header=p["spec"],
                    spec_frames=list(p["frames"]),
                    name=p.get("name", ""), namespace=p.get("namespace", ""),
                    max_restarts=p.get("max_restarts", 0),
                    job_id=p.get("job_id", b""))
                self.actors[actor.actor_id] = actor
                if actor.name:
                    self.named_actors[(actor.namespace, actor.name)] = \
                        actor.actor_id
            elif op == "actor_update":
                actor = self.actors.get(p["actor_id"])
                if actor is not None:
                    actor.state = p["state"]
                    actor.address = p["address"]
                    actor.node_id = p["node_id"]
                    actor.incarnation = p["incarnation"]
                    actor.num_restarts = p["num_restarts"]
                    actor.max_restarts = p["max_restarts"]
                    actor.death_cause = p["death_cause"]
                    actor.death_info = p.get("death_info") or {}
            elif op == "pg_upsert":
                self.placement_groups[p["pg_id"]] = p["record"]
            elif op == "pg_remove":
                self.placement_groups.pop(p["pg_id"], None)
        if max_job:
            self._job_counter = itertools.count(max_job + 1)
        if n:
            logger.info("GCS journal replay: %d records -> %d jobs, "
                        "%d actors, %d kv keys", n, len(self.jobs),
                        len(self.actors), len(self.kv))
        return n

    # --------------------------------------------------------------- pubsub

    async def _publish(self, channel: str, message: Any):
        dead = []
        for conn in self._subscribers.get(channel, []):
            try:
                await conn.push("Published", {"channel": channel, "msg": message})
            except ConnectionError:
                dead.append(conn)
        for conn in dead:
            self._subscribers[channel].remove(conn)

    async def handle_subscribe(self, conn, header, bufs):
        channel = header["channel"]
        subs = self._subscribers.setdefault(channel, [])
        if conn not in subs:
            subs.append(conn)
            conn.on_disconnect.append(
                lambda c: subs.remove(c) if c in subs else None)
        return {"ok": True}

    async def handle_publish(self, conn, header, bufs):
        await self._publish(header["channel"], header["msg"])
        return {"ok": True}

    # --------------------------------------------------------------- nodes

    @staticmethod
    def _node_alive_msg(entry: NodeEntry) -> dict:
        return {"event": "alive",
                "node_id": entry.node_id,
                "address": entry.address,
                "data_address": entry.data_address,
                "resources": entry.resources_total}

    async def handle_register_node(self, conn, header, bufs):
        req = protocol.RegisterNodeRequest.from_header(header)
        entry = NodeEntry(req.node_id, req.address,
                          req.resources, req.get("node_name", ""),
                          req.get("data_address", ""))
        # Version handshake: the stub's compat default decodes a
        # pre-versioning raylet as version 1; both sides speak the min.
        # protocol_version records what the node ADVERTISED (a v3 node
        # must be visible as v3 even while we clamp to v2), negotiated
        # what the pair actually speaks — both in node info so a
        # rolling upgrade is observable.
        try:
            entry.protocol_version = int(req.protocol_version)
        except (TypeError, ValueError):
            entry.protocol_version = protocol.MIN_PROTOCOL_VERSION
        entry.negotiated_protocol_version = \
            protocol.negotiate(entry.protocol_version)
        conn.peer_protocol_version = entry.negotiated_protocol_version
        entry.conn = conn
        self.nodes[entry.node_id] = entry
        conn.tags["node_id"] = entry.node_id
        # ONE disconnect callback per connection, reading the LATEST
        # entry off the tags: a flapping node re-registers over the
        # same live conn (the dead-node heartbeat reply forces it), and
        # appending a closure per registration would grow the list —
        # and retain every stale NodeEntry — without bound.
        conn.tags["node_entry"] = entry
        if not conn.tags.get("node_death_cb_armed"):
            conn.tags["node_death_cb_armed"] = True

            def _on_drop(c):
                e = c.tags.get("node_entry")
                if e is not None:
                    rpc.spawn_logged(self._on_node_connection_lost(e),
                                     "gcs-node-connection-lost")

            conn.on_disconnect.append(_on_drop)
        await self._publish("NODE", self._node_alive_msg(entry))
        return protocol.RegisterNodeReply(
            ok=True, num_nodes=len(self.nodes),
            protocol_version=protocol.PROTOCOL_VERSION,
            negotiated_protocol_version=entry.negotiated_protocol_version,
        ).to_header()

    async def handle_heartbeat(self, conn, header, bufs):
        req = protocol.HeartbeatRequest.from_header(header)
        # Piggybacked task-lifecycle events ingest FIRST: the raylet
        # drained its buffer irreversibly before this call, so an
        # early ok=False return (unknown node after a GCS restart /
        # dead node forcing re-registration) must not silently discard
        # the batch — the table keys by task, not node, and "honest
        # truncation everywhere" is the series contract.
        if req.get("task_events") or req.get("task_events_dropped"):
            self.task_events.ingest(req.get("task_events") or (),
                                    req.get("task_events_dropped", 0))
        # Object-lifecycle piggybacks ingest under the same contract.
        if req.get("object_events") or req.get("object_events_dropped"):
            self.object_events.ingest(
                req.get("object_events") or (),
                req.get("object_events_dropped", 0))
        # Cluster-event piggybacks (events.py plane): the raylet's
        # emitter buffer rides the beat — ingest before any early
        # return, same honest-truncation contract as task events.
        if header.get("cluster_events") or \
                header.get("cluster_events_dropped"):
            self.cluster_events.ingest(
                header.get("cluster_events") or (),
                header.get("cluster_events_dropped", 0))
        # RPC-telemetry piggyback (rpc.py flight recorder): standalone
        # raylet processes ship their per-method stats here (an
        # in-process head's CoreWorker ships via ReportRpcTelemetry).
        if header.get("rpc_telemetry"):
            self.rpc_telemetry.ingest(
                f"node-{req.node_id.hex()[:12]}",
                header.get("rpc_telemetry"))
        entry = self.nodes.get(req.node_id)
        if entry is None:
            return protocol.HeartbeatReply(
                ok=False, reason="unknown node").to_header()
        if not entry.alive:
            # The node was declared dead (heartbeat partition) but its
            # raylet is clearly alive: force a re-registration instead
            # of silently feeding a dead entry — beats into a dead node
            # would otherwise keep it invisible to scheduling FOREVER
            # while the raylet believes everything is fine (chaos soak
            # finding: heartbeat_partition schedule).
            return protocol.HeartbeatReply(
                ok=False, reason="node marked dead").to_header()
        entry.last_heartbeat = time.time()
        if req.resources_available is not protocol.UNSET:
            entry.resources_available = req.resources_available
        if req.stats is not protocol.UNSET:
            entry.stats = req.stats
        # Standalone raylet processes ship their metric registry here
        # (no CoreWorker reporter in-process; see metrics.core_reporter).
        if req.get("metrics"):
            self._metric_snapshots[
                f"node-{req.node_id.hex()[:12]}"] = (
                time.time(), req.metrics)
        return protocol.HeartbeatReply(ok=True).to_header()

    async def handle_report_resource_usage(self, conn, header, bufs):
        entry = self.nodes.get(header["node_id"])
        if entry is not None:
            entry.resources_available = header["resources_available"]
            # any raylet traffic proves liveness
            entry.last_heartbeat = time.time()
        return {"ok": True}

    async def handle_get_all_node_info(self, conn, header, bufs):
        return {"nodes": [{
            "node_id": n.node_id, "address": n.address, "alive": n.alive,
            "data_address": n.data_address,
            "node_name": n.node_name,
            "resources_total": n.resources_total,
            "resources_available": n.resources_available,
            # the RegisterNode version handshake, observable per node
            # (rolling-upgrade visibility)
            "protocol_version": n.protocol_version,
            "negotiated_protocol_version": n.negotiated_protocol_version,
        } for n in self.nodes.values()]}

    async def handle_get_cluster_resources(self, conn, header, bufs):
        total: Dict[str, float] = {}
        avail: Dict[str, float] = {}
        for n in self.nodes.values():
            if not n.alive:
                continue
            for k, v in n.resources_total.items():
                total[k] = total.get(k, 0.0) + v
            for k, v in n.resources_available.items():
                avail[k] = avail.get(k, 0.0) + v
        return {"total": total, "available": avail}

    async def handle_drain_node(self, conn, header, bufs):
        await self._mark_node_dead(header["node_id"], "drained")
        return {"ok": True}

    async def _on_node_connection_lost(self, entry: NodeEntry):
        if self.nodes.get(entry.node_id) is not entry:
            # A stale connection's teardown racing a re-registration
            # (partition recovery / reconnect): the node table already
            # holds a FRESH entry for this node — marking it dead here
            # would kill a live node on the old socket's word (chaos
            # soak finding: gcs_restart + heartbeat_partition mix).
            return
        await self._mark_node_dead(entry.node_id, "connection lost")

    async def _mark_node_dead(self, node_id: bytes, reason: str):
        entry = self.nodes.get(node_id)
        if entry is None or not entry.alive:
            return
        entry.alive = False
        log = logger.info if reason == "drained" else logger.warning
        log("node %s marked dead: %s", node_id.hex()[:8], reason)
        # node death is a first-class cluster event: ordered (GCS seq),
        # queryable via state.list_cluster_events() — the SIGKILLed-
        # raylet acceptance reads exactly this record
        self._emit_cluster_event(
            "INFO" if reason == "drained" else "ERROR", "NODE_DIED",
            f"node {node_id.hex()[:12]} marked dead: {reason}",
            node=node_id.hex()[:12], reason=reason)
        await self._publish("NODE", {"event": "dead", "node_id": node_id,
                                     "reason": reason})
        # Actors on the dead node die / restart (reference:
        # GcsActorManager::OnNodeDead).
        for actor in list(self.actors.values()):
            if actor.node_id == node_id and actor.state == ACTOR_ALIVE:
                await self._on_actor_failure(
                    actor, f"node died: {reason}",
                    cause={"kind": "NODE_DIED",
                           "node_id": node_id.hex()})

    async def _liveness_monitor(self):
        period = self.config.raylet_heartbeat_period_ms / 1000.0
        timeout = period * self.config.num_heartbeats_timeout
        while True:
            await asyncio.sleep(period)
            # loop-lag probe rides this existing cadence (no new
            # thread/timer): the GCS loop's scheduling delay is the
            # one every handler on this process pays
            rpc.telemetry.loop_probe("gcs").tick()
            now = time.time()
            for node in list(self.nodes.values()):
                if node.alive and now - node.last_heartbeat > timeout:
                    await self._mark_node_dead(node.node_id, "heartbeat timeout")

    # --------------------------------------------------------------- actors

    def _pick_node_for_actor(self, resources: Dict[str, float]) -> Optional[NodeEntry]:
        """Resource-feasible round robin (the GcsBased strategy's spirit:
        GCS picks the node using its resource view, reference:
        gcs_actor_distribution.h)."""
        alive = [n for n in self.nodes.values() if n.alive]
        if not alive:
            return None
        feasible = [n for n in alive
                    if all(n.resources_total.get(k, 0.0) >= v
                           for k, v in resources.items() if v > 0)]
        if not feasible:
            return None
        best = sorted(
            feasible,
            key=lambda n: sum(n.resources_available.get(k, 0.0)
                              for k in ("CPU",)),
            reverse=True)
        self._node_rr += 1
        return best[self._node_rr % max(1, min(2, len(best)))] \
            if len(best) > 1 else best[0]

    async def handle_register_actor(self, conn, header, bufs):
        # Idempotent by actor id: the client's _gcs_call may re-send after
        # a dropped reply — re-registering the same actor must not raise a
        # name collision or spawn a second scheduling loop.
        if header["actor_id"] in self.actors:
            return {"ok": True}
        actor = ActorEntry(
            actor_id=header["actor_id"],
            spec_header=header["spec"],
            spec_frames=list(bufs),
            name=header.get("name") or "",
            namespace=header.get("namespace") or "",
            max_restarts=header.get("max_restarts", 0),
            job_id=header.get("job_id", b""),
        )
        if actor.name:
            key = (actor.namespace, actor.name)
            if key in self.named_actors:
                existing_id = self.named_actors[key]
                existing = self.actors.get(existing_id)
                if existing is not None and existing.state != ACTOR_DEAD:
                    raise ValueError(
                        f"actor name {actor.name!r} already taken in "
                        f"namespace {actor.namespace!r}")
            self.named_actors[key] = actor.actor_id
        self.actors[actor.actor_id] = actor
        self._journal_append("actor_register", {
            "actor_id": actor.actor_id, "spec": actor.spec_header,
            "frames": actor.spec_frames, "name": actor.name,
            "namespace": actor.namespace,
            "max_restarts": actor.max_restarts, "job_id": actor.job_id})
        rpc.spawn_logged(self._schedule_actor(actor),
                         "gcs-schedule-actor")
        return {"ok": True}

    async def _schedule_actor(self, actor: ActorEntry):
        resources = actor.spec_header.get("resources", {"CPU": 1.0})
        # Pin the incarnation this scheduling attempt serves: a concurrent
        # kill/restart bumps it (or marks DEAD), and this attempt must then
        # abandon rather than create a duplicate live incarnation.
        incarnation = actor.incarnation
        # The deadline guards INFEASIBILITY only: while some node's
        # total resources can hold the actor, it stays pending however
        # long worker spawn takes (reference: pending actor creations
        # wait indefinitely on a feasible cluster — a 1-core node
        # serially spawning hundreds of actor workers must not fail
        # the tail of the queue).
        deadline = time.time() + 60.0
        while time.time() < deadline:
            if actor.state in (ACTOR_DEAD, ACTOR_ALIVE) or \
                    actor.incarnation != incarnation:
                # DEAD/superseded — or ALIVE already: a journal-replayed
                # scheduling loop must not create a second live instance
                # when the pre-crash worker survived and re-reported.
                return
            node = self._pick_node_for_actor(resources)
            if node is not None:
                deadline = time.time() + 60.0  # feasible: keep pending
            if node is not None and node.conn is not None and not node.conn.closed:
                try:
                    reply, _ = await node.conn.call(
                        "ScheduleActorCreation",
                        {"actor_id": actor.actor_id,
                         "spec": actor.spec_header,
                         "incarnation": incarnation},
                        bufs=actor.spec_frames)
                    if reply.get("ok"):
                        actor.node_id = node.node_id
                        # Raylet reports ReportActorAlive when the worker has
                        # the instance constructed.
                        return
                    logger.warning("actor scheduling on node %s rejected: %s",
                                   node.node_id.hex()[:8], reply.get("reason"))
                except ConnectionError:
                    pass
            await asyncio.sleep(0.2)
        await self._fail_actor(actor, "no feasible node for actor",
                               cause={"kind": "SCHEDULING_FAILED"})

    async def handle_report_actor_alive(self, conn, header, bufs):
        actor = self.actors.get(header["actor_id"])
        if actor is None:
            return {"ok": False}
        # Reject stale reports (a superseded incarnation, or a worker that
        # finished constructing after the actor was killed): the raylet
        # tears that worker down on a not-ok reply.
        if actor.state == ACTOR_DEAD or \
                header.get("incarnation", actor.incarnation) != actor.incarnation:
            return {"ok": False, "reason": "stale incarnation"}
        actor.state = ACTOR_ALIVE
        actor.address = header["address"]
        actor.node_id = header.get("node_id", actor.node_id)
        self._journal_actor(actor)
        await self._publish("ACTOR", {
            "actor_id": actor.actor_id, "state": ACTOR_ALIVE,
            "address": actor.address, "incarnation": actor.incarnation})
        return {"ok": True}

    async def handle_report_actor_death(self, conn, header, bufs):
        actor = self.actors.get(header["actor_id"])
        if actor is None:
            return {"ok": False}
        if header.get("expected"):
            # Graceful exit (actor_exit / job teardown): no restart.
            actor.max_restarts = actor.num_restarts
        cause = header.get("cause") or {}
        if not cause.get("kind"):
            cause = dict(cause)
            cause["kind"] = "ACTOR_EXITED" if header.get("expected") \
                else "WORKER_DIED"
        await self._on_actor_failure(actor,
                                     header.get("reason", "worker died"),
                                     cause=cause)
        return {"ok": True}

    async def _on_actor_failure(self, actor: ActorEntry, reason: str,
                                cause: Optional[dict] = None):
        if actor.state == ACTOR_DEAD:
            return
        if actor.state == ACTOR_RESTARTING:
            return  # a restart is already in flight; don't double-schedule
        if actor.max_restarts == -1 or actor.num_restarts < actor.max_restarts:
            actor.num_restarts += 1
            actor.incarnation += 1
            actor.state = ACTOR_RESTARTING
            actor.address = ""
            self._journal_actor(actor)
            await self._publish("ACTOR", {
                "actor_id": actor.actor_id, "state": ACTOR_RESTARTING,
                "incarnation": actor.incarnation})
            logger.info("restarting actor %s (%d/%s)", actor.actor_id.hex()[:8],
                        actor.num_restarts,
                        "inf" if actor.max_restarts == -1 else actor.max_restarts)
            rpc.spawn_logged(self._schedule_actor(actor),
                             "gcs-schedule-actor")
        else:
            cause = dict(cause or {})
            kind = cause.get("kind") or "WORKER_DIED"
            if actor.max_restarts > 0 and kind in ("WORKER_DIED",
                                                   "NODE_DIED"):
                # the actor HAD a restart budget and an INVOLUNTARY
                # failure burnt the last of it: the headline cause is
                # exhaustion, the final failure rides along so
                # operators still see what kept killing it. Voluntary
                # ends (ACTOR_EXITED / KILLED / CREATION_FAILED) keep
                # their own kind — a graceful exit after a past restart
                # is not "restarts exhausted".
                exhausted = {"kind": "RESTARTS_EXHAUSTED",
                             "last_failure": kind}
                for key in ("node_id", "worker_id"):
                    # only truthy ids: an empty placeholder would block
                    # _fail_actor's setdefault from filling the known id
                    if cause.get(key):
                        exhausted[key] = cause[key]
                cause = exhausted
            await self._fail_actor(actor, reason, cause)

    async def _fail_actor(self, actor: ActorEntry, reason: str,
                          cause: Optional[dict] = None):
        actor.state = ACTOR_DEAD
        actor.death_cause = reason
        info = dict(cause or {})
        info.setdefault("kind", "WORKER_DIED")
        info.setdefault("node_id",
                        actor.node_id.hex() if actor.node_id else "")
        info["message"] = reason
        info["restarts"] = actor.num_restarts
        info["max_restarts"] = actor.max_restarts
        actor.death_info = info
        self._journal_actor(actor)
        await self._publish("ACTOR", {
            "actor_id": actor.actor_id, "state": ACTOR_DEAD, "reason": reason,
            "death_info": info,
            "incarnation": actor.incarnation})

    async def handle_get_actor_info(self, conn, header, bufs):
        actor = self.actors.get(header["actor_id"])
        if actor is None:
            return {"found": False}
        return {"found": True, "state": actor.state, "address": actor.address,
                "name": actor.name, "incarnation": actor.incarnation,
                "death_cause": actor.death_cause,
                "death_info": actor.death_info, "node_id": actor.node_id}

    async def handle_get_named_actor(self, conn, header, bufs):
        key = (header.get("namespace") or "", header["name"])
        actor_id = self.named_actors.get(key)
        if actor_id is None:
            return {"found": False}
        actor = self.actors.get(actor_id)
        if actor is None or actor.state == ACTOR_DEAD:
            return {"found": False}
        return {"found": True, "actor_id": actor_id, "state": actor.state,
                "address": actor.address,
                "spec": actor.spec_header}

    async def handle_list_named_actors(self, conn, header, bufs):
        ns = header.get("namespace")
        out = []
        for (namespace, name), actor_id in self.named_actors.items():
            actor = self.actors.get(actor_id)
            if actor is None or actor.state == ACTOR_DEAD:
                continue
            if ns is None or ns == namespace:
                out.append({"namespace": namespace, "name": name,
                            "actor_id": actor_id})
        return {"actors": out}

    async def handle_kill_actor(self, conn, header, bufs):
        actor = self.actors.get(header["actor_id"])
        if actor is None:
            return {"ok": False}
        no_restart = header.get("no_restart", True)
        if no_restart:
            actor.max_restarts = actor.num_restarts
        node = self.nodes.get(actor.node_id)
        if node is not None and node.conn is not None and not node.conn.closed:
            try:
                await node.conn.call("KillActorWorker",
                                     {"actor_id": actor.actor_id})
            except ConnectionError:
                pass
        # The raylet pops the worker handle before the process dies, so no
        # death report arrives for kills — drive the state change here:
        # fail outright, or go through the restart path when allowed.
        if actor.state != ACTOR_DEAD:
            if no_restart:
                await self._fail_actor(actor, "killed via KillActor",
                                       cause={"kind": "KILLED"})
            else:
                await self._on_actor_failure(actor, "killed via KillActor",
                                             cause={"kind": "KILLED"})
        return {"ok": True}

    # --------------------------------------------------------------- jobs

    async def handle_add_job(self, conn, header, bufs):
        job_num = next(self._job_counter)
        job_id = JobID.from_int(job_num).binary()
        record = {
            "job_id": job_id, "driver_address": header.get("driver_address", ""),
            "start_time": time.time(), "finished": False,
            "namespace": header.get("namespace", ""),
            "metadata": header.get("metadata", {}),
        }
        self.jobs[job_id] = record
        self._journal_append("job_add", {"job_id": job_id, "record": record,
                                         "job_num": job_num})
        return {"job_id": job_id}

    async def handle_mark_job_finished(self, conn, header, bufs):
        job = self.jobs.get(header["job_id"])
        if job:
            job["finished"] = True
            job["end_time"] = time.time()
            self._journal_append("job_finish", {"job_id": header["job_id"]})
        await self._publish("JOB", {"event": "finished",
                                    "job_id": header["job_id"]})
        return {"ok": True}

    async def handle_get_all_job_info(self, conn, header, bufs):
        return {"jobs": list(self.jobs.values())}

    # --------------------------------------------------------------- KV

    async def handle_kv_put(self, conn, header, bufs):
        req = protocol.KVPutRequest.from_header(header)
        overwrite = req.get("overwrite", True)
        key = req.key
        if not overwrite and key in self.kv:
            return protocol.KVPutReply(added=False).to_header()
        self.kv[key] = bufs[0] if bufs else b""
        self._journal_append("kv_put", {"key": key, "value": self.kv[key]})
        if key.startswith(TRACE_KV_PREFIX):
            self._note_trace_span(key)
        return protocol.KVPutReply(added=True).to_header()

    def _note_trace_span(self, key: bytes) -> None:
        """Bound exported tracing spans (config.tracing_max_spans):
        beyond the cap the OLDEST whole trace is evicted (its spans
        deleted from the KV, kv_del journaled so a replay stays
        bounded too) and the drop is counted — long-running clusters
        with RAY_TPU_TRACE=1 must not leak the KV journal."""
        trace_id = key[len(TRACE_KV_PREFIX):].split(b"/", 1)[0]
        keys = self._trace_keys.setdefault(trace_id, {})
        if key in keys:
            return  # span overwrite: no growth
        keys[key] = True
        self._trace_span_count += 1
        cap = self.config.tracing_max_spans
        if cap <= 0 or self._trace_span_count <= cap:
            return
        dropped = 0
        while self._trace_span_count > cap and len(self._trace_keys) > 1:
            old_tid = next(iter(self._trace_keys))
            if old_tid == trace_id:
                break  # never evict the trace being written from under it
            old_keys = self._trace_keys.pop(old_tid)
            for k in old_keys:
                if self.kv.pop(k, None) is not None:
                    self._journal_append("kv_del", {"key": k})
            self._trace_span_count -= len(old_keys)
            dropped += len(old_keys)
        if self._trace_span_count > cap:
            # a single trace larger than the whole cap: drop the newest
            # span rather than grow without bound (journaled like the
            # eviction loop — a replay must not resurrect it)
            del keys[key]
            if self.kv.pop(key, None) is not None:
                self._journal_append("kv_del", {"key": key})
            self._trace_span_count -= 1
            dropped += 1
        if dropped:
            self.trace_spans_dropped += dropped
            self.kv[TRACE_DROPPED_KEY] = \
                str(self.trace_spans_dropped).encode()

    async def handle_kv_get(self, conn, header, bufs):
        req = protocol.KVGetRequest.from_header(header)
        val = self.kv.get(req.key)
        if val is None:
            return protocol.KVGetReply(found=False).to_header()
        return protocol.KVGetReply(found=True).to_header(), [val]

    def _unindex_trace_key(self, key: bytes) -> None:
        """Keep the span-cap index consistent with deletions (explicit
        clear_trace()/clear_all(), and journal-replayed kv_dels)."""
        trace_id = key[len(TRACE_KV_PREFIX):].split(b"/", 1)[0]
        keys = self._trace_keys.get(trace_id)
        if keys is not None and keys.pop(key, None):
            self._trace_span_count -= 1
            if not keys:
                del self._trace_keys[trace_id]

    async def handle_kv_del(self, conn, header, bufs):
        req = protocol.KVDelRequest.from_header(header)
        key = req.key
        existed = self.kv.pop(key, None) is not None
        if existed:
            self._journal_append("kv_del", {"key": key})
            if key.startswith(TRACE_KV_PREFIX):
                self._unindex_trace_key(key)
        return protocol.KVDelReply(deleted=existed).to_header()

    async def handle_kv_keys(self, conn, header, bufs):
        req = protocol.KVKeysRequest.from_header(header)
        prefix = req.get("prefix", b"")
        return protocol.KVKeysReply(
            keys=[k for k in self.kv if k.startswith(prefix)]).to_header()

    async def handle_kv_get_prefix(self, conn, header, bufs):
        """Bulk read of every key under a prefix in ONE round-trip.
        The timeline's span fetch reads up to tracing_max_spans (100k)
        entries — a per-key KVGet loop would storm the control plane
        with 100k sequential RPCs exactly when an operator is
        diagnosing a straggler."""
        prefix = header.get("prefix", b"")
        return {"pairs": [[k, v] for k, v in self.kv.items()
                          if k.startswith(prefix)]}

    # ------------------------------------------------------- placement groups

    async def handle_create_placement_group(self, conn, header, bufs):
        """2PC: Prepare bundle resources on chosen nodes, then Commit
        (reference: GcsPlacementGroupScheduler's prepare/commit RPC pair)."""
        pg_id = header["pg_id"]
        bundles = header["bundles"]          # list of {resource: amount}
        strategy = header.get("strategy", "PACK")
        pg = {"pg_id": pg_id, "bundles": bundles, "strategy": strategy,
              "state": PG_PENDING, "bundle_nodes": [], "name": header.get("name", "")}
        self.placement_groups[pg_id] = pg
        placement = self._place_bundles(bundles, strategy)
        if placement is None:
            pg["state"] = PG_PENDING
            return {"ok": False, "reason": "infeasible"}
        prepared: List[Tuple[NodeEntry, int]] = []
        ok = True
        for bundle_idx, node in placement:
            try:
                reply, _ = await node.conn.call("PreparePGBundle", {
                    "pg_id": pg_id, "bundle_index": bundle_idx,
                    "resources": bundles[bundle_idx]})
                if not reply.get("ok"):
                    ok = False
                    break
                prepared.append((node, bundle_idx))
            except ConnectionError:
                ok = False
                break
        if not ok:
            for node, bundle_idx in prepared:
                try:
                    await node.conn.call("ReturnPGBundle", {
                        "pg_id": pg_id, "bundle_index": bundle_idx})
                except ConnectionError:
                    pass
            return {"ok": False, "reason": "prepare failed"}
        for node, bundle_idx in prepared:
            await node.conn.call("CommitPGBundle", {
                "pg_id": pg_id, "bundle_index": bundle_idx})
        pg["state"] = PG_CREATED
        pg["bundle_nodes"] = [node.node_id for node, _ in
                              sorted(prepared, key=lambda p: p[1])]
        self._journal_append("pg_upsert", {"pg_id": pg_id, "record": pg})
        await self._publish("PG", {"pg_id": pg_id, "state": PG_CREATED})
        return {"ok": True, "bundle_nodes": pg["bundle_nodes"]}

    def _place_bundles(self, bundles, strategy):
        alive = [n for n in self.nodes.values() if n.alive and n.conn]
        if not alive:
            return None
        placement = []
        avail = {n.node_id: dict(n.resources_available) for n in alive}

        def fits(node, req):
            a = avail[node.node_id]
            return all(a.get(k, 0.0) >= v for k, v in req.items())

        def take(node, req):
            a = avail[node.node_id]
            for k, v in req.items():
                a[k] = a.get(k, 0.0) - v

        if strategy in ("STRICT_PACK",):
            for n in alive:
                trial = {n.node_id: dict(avail[n.node_id])}
                ok = True
                for b in bundles:
                    if all(trial[n.node_id].get(k, 0.0) >= v for k, v in b.items()):
                        for k, v in b.items():
                            trial[n.node_id][k] -= v
                    else:
                        ok = False
                        break
                if ok:
                    for b_idx, b in enumerate(bundles):
                        take(n, b)
                        placement.append((b_idx, n))
                    return placement
            return None
        if strategy in ("STRICT_SPREAD",):
            if len(bundles) > len(alive):
                return None
            used: Set[bytes] = set()
            for b_idx, b in enumerate(bundles):
                cand = [n for n in alive if n.node_id not in used and fits(n, b)]
                if not cand:
                    return None
                n = cand[0]
                used.add(n.node_id)
                take(n, b)
                placement.append((b_idx, n))
            return placement
        # PACK / SPREAD: best-effort ordering preference.
        order = alive if strategy == "PACK" else sorted(
            alive, key=lambda n: -sum(avail[n.node_id].values()))
        for b_idx, b in enumerate(bundles):
            cand = [n for n in order if fits(n, b)]
            if not cand:
                return None
            n = cand[0] if strategy == "PACK" else cand[b_idx % len(cand)]
            take(n, b)
            placement.append((b_idx, n))
        return placement

    async def handle_remove_placement_group(self, conn, header, bufs):
        pg = self.placement_groups.get(header["pg_id"])
        if pg is None:
            return {"ok": False}
        for bundle_idx, node_id in enumerate(pg.get("bundle_nodes", [])):
            node = self.nodes.get(node_id)
            if node and node.conn and not node.conn.closed:
                try:
                    await node.conn.call("ReturnPGBundle", {
                        "pg_id": pg["pg_id"], "bundle_index": bundle_idx})
                except ConnectionError:
                    pass
        pg["state"] = PG_REMOVED
        self._journal_append("pg_remove", {"pg_id": pg["pg_id"]})
        await self._publish("PG", {"pg_id": pg["pg_id"], "state": PG_REMOVED})
        return {"ok": True}

    async def handle_get_placement_group(self, conn, header, bufs):
        pg = self.placement_groups.get(header["pg_id"])
        if pg is None:
            return {"found": False}
        return {"found": True, **pg}

    async def handle_get_all_placement_groups(self, conn, header, bufs):
        return {"placement_groups": list(self.placement_groups.values())}

    # --------------------------------------------------------------- events

    async def handle_add_task_events(self, conn, header, bufs):
        """One reporter's batch of task-lifecycle transitions (workers
        and drivers flush on the metrics-report cadence; raylets ride
        the heartbeat instead — see handle_heartbeat)."""
        req = protocol.AddTaskEventsRequest.from_header(header)
        self.task_events.ingest(req.get("events") or (),
                                req.get("dropped", 0),
                                req.get("job_id") or b"")
        return protocol.AddTaskEventsReply(ok=True).to_header()

    async def handle_get_task_events(self, conn, header, bufs):
        """Filterable task-table dump for ray_tpu.state.list_tasks() /
        timeline(): per-task ordered transition histories plus the
        data-plane transfer records, with honest truncation counters."""
        t = self.task_events
        # transfer_limit <= 0 (or absent) means NO transfer records —
        # list_tasks() doesn't want them; `[-0:]` would be the whole
        # 10k-entry buffer, the opposite of the ask.
        try:
            transfer_limit = int(header.get("transfer_limit") or 0)
        except (TypeError, ValueError):
            transfer_limit = 0
        return {
            "tasks": t.list(state=header.get("state"),
                            name=header.get("name"),
                            node=header.get("node"),
                            job_id=header.get("job_id"),
                            limit=header.get("limit", 1000)),
            "transfers": t.transfers[-transfer_limit:]
            if transfer_limit > 0 else [],
            "evicted_tasks": {k.hex() if isinstance(k, bytes) else str(k): v
                              for k, v in t.evicted_tasks.items()},
            "dropped_events": t.dropped_events,
        }

    async def handle_get_task_summary(self, conn, header, bufs):
        return {"summary": self.task_events.summary()}

    async def handle_add_object_events(self, conn, header, bufs):
        """One reporter's batch of object-lifecycle transitions
        (workers/drivers flush on the metrics-report cadence; raylets
        ride the heartbeat instead — see handle_heartbeat)."""
        req = protocol.AddObjectEventsRequest.from_header(header)
        self.object_events.ingest(req.get("events") or (),
                                  req.get("dropped", 0))
        return protocol.AddObjectEventsReply(ok=True).to_header()

    async def handle_get_object_events(self, conn, header, bufs):
        """Filterable object-table dump for state.list_objects() /
        timeline(): per-object ordered lifecycle histories plus the
        segment-level recycle-pool events, with honest truncation
        counters. Same slicing contract as GetTaskEvents:
        ``segment_limit`` <= 0 (or absent) means NO segment events."""
        t = self.object_events
        try:
            segment_limit = int(header.get("segment_limit") or 0)
        except (TypeError, ValueError):
            segment_limit = 0
        leaked = header.get("leaked")
        return {
            "objects": t.list(state=header.get("state"),
                              owner=header.get("owner"),
                              node=header.get("node"),
                              job_id=header.get("job_id"),
                              leaked=leaked if isinstance(leaked, bool)
                              else None,
                              limit=header.get("limit", 1000)),
            "segment_events": t.segment_events[-segment_limit:]
            if segment_limit > 0 else [],
            "summary": t.summary(),
        }

    async def handle_get_object_summary(self, conn, header, bufs):
        return protocol.GetObjectSummaryReply(
            summary=self.object_events.summary()).to_header()

    async def handle_add_profile_events(self, conn, header, bufs):
        self._profile_events.extend(header["events"])
        if len(self._profile_events) > 100_000:
            self._profile_events = self._profile_events[-50_000:]
        return {"ok": True}

    async def handle_get_profile_events(self, conn, header, bufs):
        return {"events": self._profile_events}

    async def handle_add_cluster_event(self, conn, header, bufs):
        """Single-event compat shim (pre-flight-recorder reporters);
        batched reporters use AddClusterEvents."""
        self.cluster_events.add(header["event"])
        return {"ok": True}

    async def handle_add_cluster_events(self, conn, header, bufs):
        """One reporter's batch of cluster events (workers/drivers
        flush on the metrics-report cadence; raylets ride the heartbeat
        instead — see handle_heartbeat)."""
        self.cluster_events.ingest(header.get("events") or (),
                                   header.get("dropped", 0))
        return {"ok": True}

    async def handle_get_cluster_events(self, conn, header, bufs):
        """Filterable cluster-event feed for state.list_cluster_events()
        / /api/events, with the honest truncation summary."""
        return {
            "events": self.cluster_events.list(
                severity=header.get("severity"),
                label=header.get("label"),
                source=header.get("source"),
                node=header.get("node"),
                limit=header.get("limit", 1000)),
            "summary": self.cluster_events.summary(),
        }

    def _emit_cluster_event(self, severity: str, label: str,
                            message: str, **fields) -> None:
        """GCS-local emission straight into the table (node death, GCS
        restarts — control-plane truths only the GCS witnesses)."""
        self.cluster_events.add({
            "timestamp": time.time(), "severity": severity,
            "label": label, "message": message, "source_type": "gcs",
            "pid": os.getpid(), "custom_fields": fields,
        })

    # ------------------------------------------------------ rpc telemetry

    async def handle_report_rpc_telemetry(self, conn, header, bufs):
        """One reporter's RPC-telemetry payload (workers/drivers on the
        metrics-report cadence; raylets piggyback on the heartbeat —
        see handle_heartbeat)."""
        self.rpc_telemetry.ingest(header["reporter_id"],
                                  {"snapshot": header.get("snapshot"),
                                   "slow_calls": header.get("slow_calls"),
                                   "slow_calls_dropped":
                                       header.get("slow_calls_dropped", 0)})
        return {"ok": True}

    def _rpc_telemetry_self_row(self) -> None:
        """Fold this GCS process's OWN telemetry in at read time. An
        in-process head skips it: the driver CoreWorker ships the
        (shared, process-wide) snapshot under its reporter id already —
        two rows would double every count (same rule as
        metrics.core_reporter)."""
        from ray_tpu._private import metrics as metrics_mod

        if metrics_mod.core_reporter():
            return
        slow, dropped = rpc.telemetry.drain_slow_calls()
        self.rpc_telemetry.ingest("gcs", {
            "snapshot": rpc.telemetry.wire(probe="gcs"),
            "slow_calls": slow, "slow_calls_dropped": dropped})

    async def handle_get_rpc_telemetry(self, conn, header, bufs):
        """Queryable per-method RPC telemetry for state.list_rpc() /
        summary_rpc() / timeline(): flat per-(reporter, side, method)
        rows, per-reporter loop-lag blocks, and the bounded slow-call
        ring (drained into cat="rpc" timeline slices)."""
        self._rpc_telemetry_self_row()
        t = self.rpc_telemetry
        return {
            "rows": t.rows(method=header.get("method"),
                           reporter=header.get("reporter"),
                           side=header.get("side")),
            "summary": t.summary(),
            "loops": t.loops(),
            "slow_calls": list(t.slow_calls),
            "slow_calls_dropped": t.slow_dropped,
        }
