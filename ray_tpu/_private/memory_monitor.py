"""Node memory watchdog: ordered degradation instead of kernel OOM roulette.

Role parity: the reference's raylet-side memory monitor
(reference: src/ray/common/memory_monitor.h MemoryMonitor +
src/ray/raylet/worker_killing_policy.cc RetriableFIFOWorkerKillingPolicy):
a user task that balloons RSS must get the *task* killed — retriably,
observably — never a random process picked by the kernel OOM killer
(which on a loaded node is as likely to be the raylet or the GCS as the
offender, turning one bad task into a whole-node death).

The watchdog piggybacks on the raylet heartbeat cadence (no extra
thread, no extra timer): every ``memory_monitor_interval_s`` it reads
node memory usage (cgroup v2 / cgroup v1 / ``/proc/meminfo`` — a
container's limit wins over the host total) and a per-worker RSS
snapshot from ``/proc/<pid>/statm``. Crossing
``memory_usage_threshold`` triggers, IN ORDER:

1. **Store pressure relief** — ``ShmStoreServer.relieve_memory_pressure``
   drains the recycle pool and evicts/spills LRU objects (tmpfs pages
   ARE node memory; freeing data beats killing compute).
2. **Worker kill** — if relief couldn't free enough, SIGKILL the worker
   running the MOST-RECENTLY-STARTED retriable task (reference policy:
   newest first, so long-running work is protected). Never the last
   leased worker making progress, never actor workers, never drivers
   (drivers aren't in the raylet's worker table). The owner is told
   first (``WorkerOOMKilled`` push) so the death surfaces as a
   retriable :class:`ray_tpu.exceptions.OutOfMemoryError` with the RSS
   snapshot in ``cause_info`` — retried under the dedicated
   ``task_oom_retries`` budget with jittered backoff, not the generic
   worker-death budget.
3. **Lease backpressure** — while above the threshold the raylet stops
   granting new leases: requests are answered with the existing
   spillback reply when a remote node has capacity (work drains off
   the hot node) or a typed ``retry_later`` the owner backs off on —
   instead of admitting more work the watchdog would immediately kill.

Determinism: the ``memory.poll`` faultpoint lets tests inject a
simulated usage fraction / per-pid RSS (``hook`` action mutating the
``sim`` ctx dict), ``memory.kill`` fires before every kill (``drop``
suppresses it), and ``lease.backpressure`` fires per rejected lease —
the whole sequence replays from a seeded schedule (tests/chaos.py
``oom_storm``). Zero cost disarmed: one ``faultpoints.armed`` check.

Counters: ``ray_tpu_memory_monitor_kills_total`` and
``ray_tpu_lease_backpressure_rejects_total`` on the cluster /metrics
endpoint, plus honest per-node counts in heartbeat stats /
``GetNodeStats`` / ``ray_tpu.state.summary_nodes()``.
"""

from __future__ import annotations

import logging
import os
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

from ray_tpu._private import faultpoints

logger = logging.getLogger(__name__)

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096

# cgroup limits at or above this are "no limit" sentinels (v1 reports
# PAGE_COUNTER_MAX ~= 2^63/PAGE_SIZE when unlimited).
_CGROUP_NO_LIMIT = 1 << 60

# Per-poll ceiling on store relief work: _evict/_spill do synchronous
# file writes on the raylet event loop, and an unbounded node-scale
# deficit (GBs over threshold) would stall heartbeats for seconds —
# risking the dead-node timeout the watchdog exists to prevent.
# Successive polls continue the relief incrementally.
RELIEF_MAX_BYTES_PER_POLL = 256 * 1024 * 1024


# --------------------------------------------------------------------------
# Prometheus-side counters (same lazy-registration pattern as
# data_channel._plane_metrics: registered in whichever process runs the
# raylet, shipped by that process's metric reporter).
# --------------------------------------------------------------------------

_prom = None


def _monitor_metrics() -> dict:
    global _prom
    if _prom is None:
        from ray_tpu._private import metrics as m
        _prom = {
            "kills": m.Counter(
                "ray_tpu_memory_monitor_kills_total",
                "Workers SIGKILLed by the node memory watchdog (each "
                "kill surfaces as a retriable OutOfMemoryError at the "
                "task's owner)"),
            "backpressure_rejects": m.Counter(
                "ray_tpu_lease_backpressure_rejects_total",
                "Lease requests rejected (spilled or told retry-later) "
                "because the node was above memory_usage_threshold"),
        }
    return _prom


# --------------------------------------------------------------------------
# memory readers (cgroup-aware; tiny procfs/sysfs reads, never disk IO)
# --------------------------------------------------------------------------


def _read_int_file(path: str) -> Optional[int]:
    try:
        # one-line procfs/sysfs read: µs-scale, memory-backed, never disk
        with open(path, "rb") as f:
            raw = f.read().strip()
    except OSError:
        return None
    if raw == b"max":
        return None
    try:
        return int(raw)
    except ValueError:
        return None


def _cgroup_memory() -> Optional[Tuple[int, int]]:
    """(used, limit) from the cgroup this process lives in, or None when
    uncontained (no cgroup files, or an unlimited limit). A container's
    limit is the honest "node total" — the kernel OOM killer fires at
    the cgroup boundary, not the host's."""
    # v2 unified hierarchy
    cur = _read_int_file("/sys/fs/cgroup/memory.current")
    if cur is not None:
        lim = _read_int_file("/sys/fs/cgroup/memory.max")
        if lim is not None and 0 < lim < _CGROUP_NO_LIMIT:
            return cur, lim
    # v1
    cur = _read_int_file("/sys/fs/cgroup/memory/memory.usage_in_bytes")
    if cur is not None:
        lim = _read_int_file("/sys/fs/cgroup/memory/memory.limit_in_bytes")
        if lim is not None and 0 < lim < _CGROUP_NO_LIMIT:
            return cur, lim
    return None


def _meminfo_memory() -> Optional[Tuple[int, int]]:
    """(used, total) from /proc/meminfo: used = total - available, the
    same definition the kernel OOM heuristics work from."""
    total = avail = None
    try:
        # /proc/meminfo is memory-backed (µs-scale read)
        with open("/proc/meminfo", "rb") as f:
            for line in f:
                if line.startswith(b"MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith(b"MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
                if total is not None and avail is not None:
                    break
    except (OSError, ValueError, IndexError):
        return None
    if total is None or avail is None or total <= 0:
        return None
    return total - avail, total


def _psutil_memory() -> Optional[Tuple[int, int]]:
    try:
        import psutil
        vm = psutil.virtual_memory()
        return int(vm.total - vm.available), int(vm.total)
    except Exception:  # noqa: BLE001 — no psutil / exotic platform
        return None


# Resolved memory source, cached after the first successful read: the
# full probe chain (cgroup v2 -> cgroup v1 -> meminfo -> psutil) costs
# ~0.5ms when the box is uncontained — fallthrough attempts against
# files that don't exist or report "max" — while the steady-state
# winner reads in ~60µs. Re-resolved only if the cached source fails.
_memory_source: Optional[Any] = None


def node_memory_usage() -> Tuple[int, int]:
    """(used_bytes, total_bytes) for this node — cgroup limit first
    (container-aware: the kernel OOM killer fires at the cgroup
    boundary), /proc/meminfo next, psutil as the portable fallback.
    (0, 0) when nothing is readable (the watchdog then idles: no
    relief, no kills, no backpressure)."""
    global _memory_source
    src = _memory_source
    if src is not None:
        got = src()
        if got is not None:
            return got
        _memory_source = None  # cached source vanished: re-resolve
    for fn in (_cgroup_memory, _meminfo_memory, _psutil_memory):
        got = fn()
        if got is not None:
            _memory_source = fn
            return got
    return 0, 0


def process_rss(pid: int) -> int:
    """Resident set size of ``pid`` in bytes via /proc/<pid>/statm
    (field 2 = resident pages). 0 for a dead/unreadable pid."""
    try:
        # one-line procfs read: µs-scale, memory-backed
        with open(f"/proc/{pid}/statm", "rb") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE
    except (OSError, ValueError, IndexError):
        return 0


class MemoryMonitor:
    """The per-raylet watchdog. Owns no thread: the raylet's heartbeat
    loop calls :meth:`poll` and the interval gate inside decides whether
    this beat actually samples. Collaborators arrive as callables so the
    monitor is unit-testable without a raylet:

    * ``workers()`` -> iterable of WorkerHandle-shaped objects
      (``state``/``pid``/``worker_id``/``leased_at``/``lease_retriable``)
    * ``kill_worker(handle, cause_dict)`` -> performs owner notification
      + SIGKILL (the raylet's ``_oom_kill_worker``)
    * ``store`` -> ShmStoreServer (``relieve_memory_pressure``)
    """

    def __init__(self, config, store, nid12: str,
                 workers: Callable[[], Iterable[Any]],
                 kill_worker: Callable[[Any, dict], None]):
        self.enabled = bool(getattr(config, "memory_monitor_enabled", True))
        self.threshold = float(
            getattr(config, "memory_usage_threshold", 0.95))
        self.interval_s = float(
            getattr(config, "memory_monitor_interval_s", 0.5))
        self.store = store
        self.nid12 = nid12
        self.workers = workers
        self.kill_worker = kill_worker
        self._last_poll = 0.0
        # last-poll snapshot (served by GetNodeStats / heartbeat stats)
        self.pressure = False
        self.used = 0
        self.total = 0
        self.usage_fraction = 0.0
        self.workers_rss: Dict[str, int] = {}     # wid12 -> bytes
        # honest cumulative counters (process lifetime)
        self.kills = 0
        self.backpressure_rejects = 0
        self.relief_bytes = 0
        self.polls = 0
        # last 64 watchdog actions, for observability and the ordering
        # test (relief must precede any kill within a poll)
        self.history: Any = deque(maxlen=64)

    # ------------------------------------------------------------- sampling

    def _workers_rss(self, sim_rss: Optional[Dict[int, int]]
                     ) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for w in self.workers():
            if not w.pid or w.state == "dead":
                continue
            if sim_rss and w.pid in sim_rss:
                rss = int(sim_rss[w.pid])
            else:
                rss = process_rss(w.pid)
            out[w.worker_id.hex()[:12]] = rss
        return out

    def _pick_victim(self):
        """The most-recently-started retriable task's worker — never the
        last leased worker (someone must keep making progress), never
        actors (their restart machinery is a different failure domain),
        never drivers (not in the raylet's worker table at all)."""
        leased = [w for w in self.workers()
                  if w.state == "leased" and w.pid]
        if len(leased) < 2:
            return None
        # oom_kill_pending: a victim already dispatched to the (async,
        # owner-acked) kill path but not yet dead — re-selecting it on
        # the next poll would double-count the kill and double-notify
        # the owner.
        cands = [w for w in leased
                 if getattr(w, "lease_retriable", False)
                 and not getattr(w, "oom_kill_pending", False)]
        if not cands:
            return None
        return max(cands, key=lambda w: getattr(w, "leased_at", 0.0))

    # --------------------------------------------------------------- poll

    def note_backpressure(self) -> None:
        """One lease request rejected under pressure (counted by the
        raylet's lease path; the Prometheus counter rides along)."""
        self.backpressure_rejects += 1
        _monitor_metrics()["backpressure_rejects"].inc()

    def note_kill(self) -> None:
        """One watchdog kill actually LANDED (the raylet's async kill
        path calls this at SIGKILL time): honest counters never count
        a dispatch the re-grant guard aborted."""
        self.kills += 1
        _monitor_metrics()["kills"].inc()

    def poll(self, force: bool = False) -> None:
        """One watchdog evaluation (interval-gated unless ``force``).
        Runs the ordered degradation sequence when over the threshold:
        store relief first, then at most ONE worker kill per poll (a
        storm kills one victim per interval, not the whole pool at
        once — each kill frees memory the next poll re-measures)."""
        if not self.enabled:
            # never leave pressure LATCHED by a disable: the raylet
            # gates lease admission on this flag, and no future poll
            # could clear it — every lease would retry-later forever
            self.pressure = False
            return
        now = time.monotonic()
        if not force and now - self._last_poll < self.interval_s:
            return
        self._last_poll = now
        self.polls += 1
        sim: Dict[str, Any] = {}
        if faultpoints.armed:
            # simulated-RSS seam: a ``hook`` mutates ``sim`` (keys
            # ``usage_fraction`` and ``rss_by_pid``) to drive the whole
            # sequence deterministically; ``drop`` skips this poll.
            # ``pids`` carries the live worker pids so seeded chaos
            # hooks can ramp a random worker's simulated RSS.
            pids = [w.pid for w in self.workers()
                    if w.pid and w.state != "dead"]
            act = faultpoints.fire("memory.poll", node=self.nid12,
                                   sim=sim, pids=pids)
            if act == "drop":
                return
        used, total = node_memory_usage()
        if "usage_fraction" in sim and total > 0:
            used = int(float(sim["usage_fraction"]) * total)
        self.workers_rss = self._workers_rss(sim.get("rss_by_pid"))
        self.used, self.total = used, total
        self.usage_fraction = used / total if total else 0.0
        if total <= 0 or self.usage_fraction < self.threshold:
            self.pressure = False
            return
        self.pressure = True
        # (1) pressure relief: recycle-pool drain + LRU evict/spill.
        # tmpfs store pages are node memory — freeing data is strictly
        # cheaper than killing compute, so it always runs first. The
        # ask is clamped per poll (bounded loop stall; see
        # RELIEF_MAX_BYTES_PER_POLL).
        need = used - int(self.threshold * total)
        ask = min(need, RELIEF_MAX_BYTES_PER_POLL)
        freed = self.store.relieve_memory_pressure(ask)
        if freed:
            self.relief_bytes += freed
            self.history.append({"ts": time.time(), "action": "relief",
                                 "freed_bytes": freed, "need_bytes": need,
                                 "ask_bytes": ask})
        if freed >= ask:
            # relief delivered its full slice: still making progress,
            # nobody dies this poll (the next poll re-measures and
            # continues — or escalates once the store runs dry)
            return
        # (2) one kill per poll: newest retriable leased worker.
        victim = self._pick_victim()
        if victim is None:
            return  # backpressure (3) is the raylet lease path's job
        wid12 = victim.worker_id.hex()[:12]
        if faultpoints.armed:
            act = faultpoints.fire("memory.kill", node=self.nid12,
                                   worker=wid12, pid=victim.pid)
            if act == "drop":
                return
        cause = {
            "kind": "WORKER_OOM",
            "node_id": self.nid12,
            "worker_id": victim.worker_id.hex(),
            "message": (f"node memory {self.usage_fraction:.1%} above "
                        f"threshold {self.threshold:.0%}; watchdog "
                        f"killed the newest retriable task's worker"),
            "usage_fraction": round(self.usage_fraction, 4),
            "threshold": self.threshold,
            "workers_rss": dict(self.workers_rss),
        }
        victim.oom_kill_pending = True
        # counters increment in note_kill() when the SIGKILL actually
        # lands — a dispatch aborted by the raylet's re-grant guard
        # (the lease completed during the owner-ack wait) is not a kill
        self.history.append({"ts": time.time(), "action": "kill",
                             "worker": wid12, "pid": victim.pid,
                             "rss": self.workers_rss.get(wid12, 0)})
        logger.warning(
            "memory watchdog killing worker %s (pid %s, rss %s): node "
            "at %.1f%% >= %.0f%%", wid12, victim.pid,
            self.workers_rss.get(wid12, 0), self.usage_fraction * 100,
            self.threshold * 100)
        self.kill_worker(victim, cause)

    # -------------------------------------------------------------- stats

    def snapshot(self) -> dict:
        """Watchdog state for GetNodeStats (full) — heartbeat stats
        carry the flat subset (see raylet._heartbeat_stats)."""
        return {
            "enabled": self.enabled,
            "threshold": self.threshold,
            "interval_s": self.interval_s,
            "pressure": self.pressure,
            "used_bytes": self.used,
            "total_bytes": self.total,
            "usage_fraction": round(self.usage_fraction, 4),
            "workers_rss_bytes": dict(self.workers_rss),
            "kills_total": self.kills,
            "backpressure_rejects_total": self.backpressure_rejects,
            "relief_bytes_total": self.relief_bytes,
            "polls": self.polls,
            "history": list(self.history),
        }
