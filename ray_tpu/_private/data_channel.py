"""Striped zero-copy data plane for cross-node object transfer.

The control plane (rpc.py) multiplexes every RPC of a peer pair over ONE
msgpack-framed TCP/unix stream; before this module, chunked object pulls
rode that same stream — each chunk was decoded into a Python ``bytes``
by the recv loop and then copied again into the destination shm segment.
The reference separates the two planes for exactly this reason (chunked
Push/Pull rides its own buffered path: src/ray/object_manager/
push_manager.h + ObjectBufferPool), and the Dask overhead analysis
(arXiv:2010.11105) shows runtime copies, not the network, capping
transfer rates.

This module is the bulk transport under that control plane:

* ``DataPlaneServer`` — a raw-socket listener each raylet runs next to
  its RPC server. Chunk requests are served with ``os.sendfile`` (via
  ``loop.sock_sendfile``) straight from the segment's /dev/shm file to
  the peer's socket: the sender never maps, reads, or re-buffers object
  bytes in userspace.
* ``DataChannelClient`` — N striped non-blocking connections per peer.
  Chunk payloads are received DIRECTLY into the destination shm mapping
  via the GIL-releasing native ``recv_into`` (cpp/fastpath.c, with a
  ``socket.recv_into`` pure-Python fallback — see native.sock_recv_into):
  exactly one kernel->segment copy per chunk, no intermediate ``bytes``.
* ``run_striped`` — the fan-out engine: chunk offsets drain across every
  stripe of every replica-holding peer; a failing stripe hands its chunk
  back to the queue and retires, so the pull survives anything short of
  every stripe dying.

Wire framing (one request in flight per stripe; stripes give the
parallelism):

    request  (client -> server): [u32 len][msgpack [object_id, offset, length]]
    response (server -> client): [u32 len][msgpack [status, payload_len]]
                                 [payload bytes]
    status: 0 = ok (payload_len data bytes follow), 1 = object unknown.

Only chunk payloads travel here; sizes, locations, admission, sealing
and every failure decision stay on the control plane (raylet.py
FetchObjectMeta / EnsureObjectLocal).
"""

from __future__ import annotations

import asyncio
import logging
import socket
import struct
from typing import Any, Awaitable, Callable, Deque, Dict, List, Optional

import msgpack

from ray_tpu._private import faultpoints, native

logger = logging.getLogger(__name__)

_U32 = struct.Struct("<I")

# Hard cap on a request body: a corrupt/hostile length prefix must not
# allocate unbounded memory on the serving raylet.
_MAX_REQUEST_BYTES = 1 << 16

STATUS_OK = 0
STATUS_NOT_FOUND = 1

# Receive-path observability (asserted by tests, reported via
# GetNodeStats and the bench's cross_node_transfer block). ``chunks``
# counts every cross-node chunk pulled, striped AND legacy (the
# raylet's control-plane fallback reports here too);
# ``intermediate_copies`` counts chunk payloads that materialized as a
# Python ``bytes`` before reaching the destination segment — 0 on the
# striped plane (socket -> shm is the only copy), 1 per chunk on the
# legacy path (recv-loop bytes + copy_into).
pull_stats = {"chunks": 0, "bytes": 0, "intermediate_copies": 0,
              "stripe_failures": 0}
serve_stats = {"chunks": 0, "bytes": 0, "sendfile": 0, "mapped": 0}


def reset_stats() -> None:
    for d in (pull_stats, serve_stats):
        for k in d:
            d[k] = 0


# --------------------------------------------------------------------------
# Prometheus-side view (metrics registry): the same counters GetNodeStats
# reports, exported on the cluster /metrics endpoint so stripe failures,
# per-tier transfer bytes and per-pull throughput are scrapeable — not
# just bench counters. Registered lazily in whichever process runs the
# data plane (the raylet); shipped to the GCS by that process's metric
# reporter (CoreWorker loop in-process, heartbeat piggyback standalone).
# --------------------------------------------------------------------------

_prom = None
_TIER_STRIPED = {"tier": "striped"}
_TIER_CONTROL = {"tier": "control"}
_TIER_SENDFILE = {"tier": "sendfile"}
_TIER_MAPPED = {"tier": "mapped"}


def _plane_metrics() -> dict:
    global _prom
    if _prom is None:
        from ray_tpu._private import metrics as m
        _prom = {
            "bytes_pulled": m.Counter(
                "ray_tpu_data_plane_bytes_pulled_total",
                "Object bytes pulled cross-node, by transport tier "
                "(striped raw sockets vs control-plane fallback)"),
            "bytes_served": m.Counter(
                "ray_tpu_data_plane_bytes_served_total",
                "Object bytes served to peers, by serve tier "
                "(sendfile vs mapped-sendall fallback)"),
            "stripe_failures": m.Counter(
                "ray_tpu_data_plane_stripe_failures_total",
                "Pull stripes dropped by connection/framing failures"),
            "intermediate_copies": m.Counter(
                "ray_tpu_data_plane_intermediate_copies_total",
                "Chunk payloads that materialized as intermediate "
                "bytes before reaching the destination segment "
                "(0 on the striped plane, 1/chunk on the fallback)"),
            "pull_gb_per_s": m.Histogram(
                "ray_tpu_data_plane_pull_gb_per_s",
                "Per-pull end-to-end throughput (GB/s)",
                boundaries=(0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 4.0,
                            8.0, 16.0)),
        }
    return _prom


def observe_pull(total_bytes: int, wall_s: float) -> None:
    """One completed pull's throughput -> the Prometheus histogram."""
    _plane_metrics()["pull_gb_per_s"].observe(
        total_bytes / max(wall_s, 1e-9) / 1e9)


def note_control_chunk(nbytes: int) -> None:
    """Legacy control-plane chunk accounting (raylet fallback lanes):
    the recv loop materialized the payload as bytes before copy_into —
    exactly one intermediate copy per chunk."""
    pull_stats["chunks"] += 1
    pull_stats["bytes"] += nbytes
    pull_stats["intermediate_copies"] += 1
    m = _plane_metrics()
    m["bytes_pulled"].inc(nbytes, _TIER_CONTROL)
    m["intermediate_copies"].inc()


def _wait_readable(sock: socket.socket) -> "asyncio.Future":
    """Future that resolves when ``sock`` has data (loop add_reader).
    Resolving it EXTERNALLY (set_exception — see _Stripe wake-on-close)
    also deregisters the reader via the done callback."""
    loop = asyncio.get_running_loop()
    fut = loop.create_future()
    fd = sock.fileno()
    if fd < 0:
        fut.set_exception(ConnectionError("socket already closed"))
        return fut

    def _ready():
        if not fut.done():
            fut.set_result(None)

    def _on_done(f):
        try:
            loop.remove_reader(fd)
        except (OSError, ValueError):
            pass  # fd already closed/deregistered

    loop.add_reader(fd, _ready)
    fut.add_done_callback(_on_done)
    return fut


async def recv_exact_into(sock: socket.socket, buf, off: int,
                          nbytes: int, waiter_box=None) -> None:
    """Receive exactly ``nbytes`` into ``buf[off:off+nbytes]`` from a
    non-blocking socket — the single-copy seam: the bytes land straight
    in the caller's buffer (for chunk payloads, the mapped destination
    segment). Tries the GIL-releasing receive first and awaits loop
    readability only on EAGAIN. ``waiter_box`` (an object with a
    ``waiter`` attribute, e.g. a _Stripe) exposes the parked future so
    a LOCAL close can wake it — closing an fd silently removes it from
    the loop's selector, so an unwoken reader would park forever."""
    got = 0
    while got < nbytes:
        try:
            n = native.sock_recv_into(sock, buf, off + got, nbytes - got)
        except OSError as e:  # closed-under-us fd (EBADF) et al.
            raise ConnectionError(f"data channel receive failed: {e}") \
                from e
        if n == -1:
            fut = _wait_readable(sock)
            if waiter_box is not None:
                waiter_box.waiter = fut
            try:
                await fut
            finally:
                if waiter_box is not None:
                    waiter_box.waiter = None
            continue
        if n == 0:
            raise ConnectionError("data channel peer closed mid-frame")
        got += n


async def _recv_frame(sock: socket.socket, waiter_box=None) -> Any:
    """One [u32 len][msgpack body] control frame (requests and response
    headers — small metadata, never chunk payload)."""
    hdr = bytearray(4)
    await recv_exact_into(sock, hdr, 0, 4, waiter_box)
    (blen,) = _U32.unpack(hdr)
    if blen > _MAX_REQUEST_BYTES:
        raise ConnectionError(f"data channel frame too large ({blen} B)")
    body = bytearray(blen)
    await recv_exact_into(sock, body, 0, blen, waiter_box)
    return msgpack.unpackb(bytes(body), raw=False)


def _pack_frame(body: Any) -> bytes:
    payload = msgpack.packb(body, use_bin_type=True)
    return _U32.pack(len(payload)) + payload


def _configure(sock: socket.socket) -> None:
    sock.setblocking(False)
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass  # not a TCP socket (tests may use socketpairs)


# --------------------------------------------------------------------------
# Sender side
# --------------------------------------------------------------------------


class _Source:
    """A cached chunk source (open fd for sendfile, or a mapped
    attachment), refcounted: concurrent serves of one segment PIN the
    source, and eviction/free only marks it dropped — the close runs
    when the last in-flight serve unpins, never under an active
    sendfile."""

    __slots__ = ("kind", "obj", "pins", "dropped")

    def __init__(self, kind: str, obj):
        self.kind = kind
        self.obj = obj
        self.pins = 0
        self.dropped = False

    def close_if_free(self) -> None:
        if self.dropped and self.pins == 0:
            try:
                self.obj.close()
            except (OSError, BufferError):
                pass  # a live consumer view may still pin a mapping


class DataPlaneServer:
    """Serves chunk ranges of sealed segments over raw sockets.

    Runs inside the raylet next to (and independent of) the RPC server:
    a slow multi-GiB transfer here never queues behind — or ahead of —
    heartbeats and lease grants on the control stream. Chunk bytes go
    file -> socket via sendfile; where the segment is not /dev/shm-backed
    (exotic platforms) a mapped attachment serves the range with
    ``sock_sendall`` of a live memoryview — still no re-buffering.
    """

    # Bounded source cache: a multi-chunk pull hits the same segment
    # many times; re-opening per chunk would sit on the hot path
    # (mirrors the raylet's _serve_attachments bound).
    MAX_SOURCES = 16

    def __init__(self, store, host: str = "127.0.0.1"):
        self.store = store
        self.host = host
        self.address = ""
        self._sock: Optional[socket.socket] = None
        self._accept_task: Optional[asyncio.Task] = None
        self._conn_tasks: "set[asyncio.Task]" = set()
        self._sources: Dict[str, _Source] = {}
        # Serve-side table for UNSEALED segments: ring-collective
        # accumulators must be readable by ring peers mid-collective,
        # before (and without) a store seal. Key = the 28-byte ring
        # member id (same width as an ObjectID, disjoint key space —
        # driver-minted per collective x rank), value = (segment_name,
        # total_size). Entries are registered by RingInit and dropped
        # by RingFinish/RingAbort; the segment is store-LEASED for the
        # whole window, so it can never be recycled under a reader and
        # needs no mark_exposed pin.
        self.extra_entries: Dict[bytes, tuple] = {}
        self._closing = False
        # per-instance counter (module serve_stats aggregates every
        # server in the process; tests with several in-process raylets
        # need to tell them apart)
        self.num_chunks_served = 0
        # Fault injection rides the faultpoints registry (point
        # "data.serve_chunk" — raise/hook/delay, plus the
        # site-interpreted corrupt/short/miss/sever actions applied in
        # _serve_chunk); the old ad-hoc ``on_serve`` callback is gone.

    async def start(self) -> str:
        loop = asyncio.get_running_loop()
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((self.host, 0))
        sock.listen(64)
        sock.setblocking(False)
        self._sock = sock
        self.address = "%s:%d" % sock.getsockname()[:2]
        self._accept_task = loop.create_task(self._accept_loop())
        return self.address

    async def _accept_loop(self):
        loop = asyncio.get_running_loop()
        while not self._closing:
            try:
                conn, _ = await loop.sock_accept(self._sock)
            except asyncio.CancelledError:
                # close() cancels this task and awaits it: stay
                # cancelled so the canceller sees the loop actually
                # stop instead of a phantom clean exit
                raise
            except OSError as e:
                if self._closing:
                    return
                # transient accept failure (EMFILE under high fan-in,
                # ECONNABORTED): the listener must survive it — dying
                # here would silently strand every future striped pull
                # on connect timeouts while the node still advertises
                # its data_address
                logger.warning("data plane accept error (retrying): %r",
                               e)
                await asyncio.sleep(0.1)
                continue
            _configure(conn)
            task = loop.create_task(self._serve_conn(conn))
            self._conn_tasks.add(task)
            task.add_done_callback(self._conn_tasks.discard)

    async def _serve_conn(self, sock: socket.socket):
        try:
            while not self._closing:
                try:
                    req = await _recv_frame(sock)
                except (ConnectionError, OSError):
                    return  # peer closed / reset: normal stripe teardown
                oid_b, offset, length = req
                fault = None
                if faultpoints.armed:
                    # raise/hook faults propagate (the serving conn
                    # tears down exactly like a mid-serve crash);
                    # corrupt/short/miss/sever are applied below
                    fault = await faultpoints.async_fire(
                        "data.serve_chunk", oid=oid_b, offset=offset,
                        length=length, server=self.address)
                    if fault == "sever":
                        return  # finally closes the socket mid-exchange
                try:
                    await self._serve_chunk(sock, oid_b, int(offset),
                                            int(length), fault=fault)
                except (ConnectionError, OSError) as e:
                    # the puller hung up mid-serve (cancelled pull /
                    # raylet stop): routine teardown, not an error
                    logger.debug("data plane serve aborted by peer: %r",
                                 e)
                    return
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("data plane serve error")
        finally:
            try:
                sock.close()
            except OSError:
                pass  # already torn down

    async def _serve_chunk(self, sock: socket.socket, oid_b: bytes,
                           offset: int, length: int,
                           fault: Optional[str] = None):
        from ray_tpu._private.ids import ObjectID

        loop = asyncio.get_running_loop()
        if fault == "corrupt":
            # corrupt-frame fault: garbage where the response header
            # belongs. The client's framing rejects it (length prefix
            # over _MAX_REQUEST_BYTES) and retires the stripe — the
            # deterministic stand-in for a peer scribbling the wire.
            await loop.sock_sendall(sock, b"\xff" * 8)
            return
        if fault == "miss":
            await loop.sock_sendall(sock,
                                    _pack_frame([STATUS_NOT_FOUND, 0]))
            return
        entry = self.extra_entries.get(oid_b)
        if entry is None:
            entry = self.store.entry(ObjectID(oid_b))
            if entry is not None:
                # a remote raylet is mid-pull: its future chunk reads
                # must see this exact data — the segment must never
                # enter the recycle pool while the transfer is in
                # flight (same pin as the control-plane
                # FetchObjectChunk serve path). Side-table entries
                # (ring accumulators) skip this: they are store-LEASED,
                # which already bars recycling.
                self.store.mark_exposed(ObjectID(oid_b))
        if entry is None or offset < 0 or length < 0 \
                or offset > entry[1]:
            # invalid range = hostile/corrupt peer: a negative offset
            # would inflate ``count`` past the real payload and either
            # hang the client stripe (short mapped slice) or EINVAL the
            # sendfile after the OK header is on the wire
            await loop.sock_sendall(sock,
                                    _pack_frame([STATUS_NOT_FOUND, 0]))
            return
        name, total = entry
        end = min(offset + max(0, length), total)
        count = max(0, end - offset)
        if fault == "short" and count > 1:
            # short-read fault: a divergent replica promising (and
            # sending) fewer bytes than the puller asked for — the
            # client's exact-length check must reject the chunk
            count //= 2
            end = offset + count
        src = await self._source(name)
        if src is None:
            # segment vanished between lookup and open (freed mid-pull)
            await loop.sock_sendall(sock,
                                    _pack_frame([STATUS_NOT_FOUND, 0]))
            return
        try:
            await loop.sock_sendall(sock, _pack_frame([STATUS_OK, count]))
            if count == 0:
                return
            if src.kind == "fd":
                try:
                    await loop.sock_sendfile(sock, src.obj, offset,
                                             count, fallback=False)
                except (asyncio.SendfileNotAvailableError,
                        NotImplementedError):
                    # kernel refused this fd/socket pairing: demote the
                    # source to a mapped attachment for every later
                    # chunk (the header is already on the wire, so
                    # serve THIS range from the new mapping too)
                    src = await self._demote(name, src)
            if src.kind == "mm":
                # zero-copy mapped path: the range rides to the socket
                # as a live view of the attachment — never flattened
                await loop.sock_sendall(sock, src.obj.buf[offset:end])
                serve_stats["mapped"] += 1
                _plane_metrics()["bytes_served"].inc(count, _TIER_MAPPED)
            else:
                serve_stats["sendfile"] += 1
                _plane_metrics()["bytes_served"].inc(count, _TIER_SENDFILE)
        finally:
            src.pins -= 1
            src.close_if_free()
        serve_stats["chunks"] += 1
        serve_stats["bytes"] += count
        self.num_chunks_served += 1

    async def _source(self, name: str) -> Optional[_Source]:
        """Pinned source for ``name`` (caller unpins when its send is
        done). LRU-bounded; a dropped/evicted source closes only once
        the last pin releases — never under an in-flight sendfile."""
        src = self._sources.get(name)
        if src is None or src.dropped:
            loop = asyncio.get_running_loop()
            try:
                # executor: file open / MAP_POPULATE attach of a large
                # segment must not stall the serving loop
                kind, obj = await loop.run_in_executor(
                    None, _open_source, name)
            except (FileNotFoundError, OSError, ValueError):
                return None
            src = _Source(kind, obj)
            cur = self._sources.get(name)
            if cur is not None and not cur.dropped:
                # raced a concurrent first serve during the open: keep
                # the cached one, close ours (it has no pins yet)
                src.dropped = True
                src.close_if_free()
                src = cur
            else:
                self._insert(name, src)
        else:
            # LRU touch: most recently used last
            self._sources.pop(name, None)
            self._sources[name] = src
        src.pins += 1
        return src

    def _insert(self, name: str, src: _Source) -> None:
        while len(self._sources) >= self.MAX_SOURCES:
            oldest = next(iter(self._sources))
            self.drop_source(oldest)
        self._sources[name] = src

    async def _demote(self, name: str, src: _Source) -> _Source:
        """Swap a pinned fd source for a mapped attachment (sendfile
        unavailable); returns the new source, pinned in its place."""
        loop = asyncio.get_running_loop()
        kind, obj = await loop.run_in_executor(None, _mm_source, name)
        mm = _Source(kind, obj)
        mm.pins = 1
        old = self._sources.get(name)
        if old is src:
            self._sources[name] = mm
        else:
            # the cache moved on during the open (FreeObject dropped
            # the entry, or LRU replaced it): don't re-cache — mark
            # dropped so the caller's unpin closes the mapping
            mm.dropped = True
        src.pins -= 1
        src.dropped = True
        src.close_if_free()
        return mm

    def drop_source(self, name: str) -> None:
        """Release the cached fd/mapping of a freed segment now instead
        of waiting for LRU eviction (the raylet's FreeObject path does
        the same for its control-plane serve attachments). In-flight
        serves keep it pinned; the close lands on the last unpin."""
        src = self._sources.pop(name, None)
        if src is not None:
            src.dropped = True
            src.close_if_free()

    async def close(self):
        self._closing = True
        if self._accept_task is not None:
            self._accept_task.cancel()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        for task in list(self._conn_tasks):
            task.cancel()
        if self._conn_tasks:
            await asyncio.gather(*self._conn_tasks,
                                 return_exceptions=True)
        for name in list(self._sources):
            self.drop_source(name)


def _open_source(name: str):
    """("fd", fileobj) for the sendfile path, or ("mm", attachment)
    where /dev/shm is unavailable (executor-thread helper)."""
    from ray_tpu._private import shm_store

    try:
        return "fd", shm_store.open_segment_for_read(name)
    except (FileNotFoundError, OSError):
        return _mm_source(name)


def _mm_source(name: str):
    from ray_tpu._private import shm_store

    return "mm", shm_store._QuietSharedMemory(name)


# --------------------------------------------------------------------------
# Receiver side
# --------------------------------------------------------------------------


class _Stripe:
    __slots__ = ("sock", "lock", "waiter")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        # Chunk-level serialization: two concurrent PULLS sharing this
        # cached stripe interleave whole request/response exchanges,
        # never frames.
        self.lock = asyncio.Lock()
        # The fetch's parked readable-future, if any: wake-on-close
        # target (sock.close() alone would strand the parked reader).
        self.waiter: Optional[asyncio.Future] = None

    def wake(self) -> None:
        w = self.waiter
        if w is not None and not w.done():
            w.set_exception(ConnectionError(
                "data channel closed under a parked receive"))


class DataChannelClient:
    """N striped raw connections to one peer's DataPlaneServer."""

    def __init__(self, address: str, stripes: int):
        self.address = address
        self.num_stripes = max(1, stripes)
        self.stripes: List[_Stripe] = []
        self._closed = False

    async def _dial(self, timeout: float) -> socket.socket:
        if faultpoints.armed:
            # stripe-dial fault: arm with exc=ConnectionError(...) to
            # model an unreachable/black-holed data port
            await faultpoints.async_fire("data.stripe_dial",
                                         address=self.address)
        host, _, port = self.address.rpartition(":")
        loop = asyncio.get_running_loop()
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        _configure(sock)
        try:
            await asyncio.wait_for(
                loop.sock_connect(sock, (host, int(port))), timeout)
        except BaseException as e:
            # BaseException: a CANCELLED dial (caller timeout, raylet
            # stop) must close the socket too, or every cancel/retry
            # cycle leaks an fd
            sock.close()
            if isinstance(e, (OSError, asyncio.TimeoutError)):
                raise ConnectionError(
                    f"data channel connect to {self.address}: {e}") \
                    from e
            raise
        return sock

    async def connect(self, timeout: float = 5.0):
        # stripes dial CONCURRENTLY: a black-holed port costs ONE
        # timeout, not num_stripes of them. Landed sockets accumulate
        # in a shared list so cancellation mid-gather can close them
        # (gather would otherwise strand completed results).
        socks: List[socket.socket] = []
        errs: List[BaseException] = []

        async def _one():
            try:
                socks.append(await self._dial(timeout))
            except ConnectionError as e:
                errs.append(e)

        try:
            await asyncio.gather(
                *(_one() for _ in range(self.num_stripes)))
        except BaseException:
            for s in socks:
                s.close()
            raise
        if errs:  # all-or-nothing: a half-reachable peer is suspect
            for s in socks:
                s.close()
            raise errs[0]
        self.stripes = [_Stripe(s) for s in socks]
        return self

    async def ensure_stripes(self, timeout: float = 5.0) -> None:
        """Re-dial stripes dropped by failures/cancelled pulls, so a
        transient error does not leave this peer's channel permanently
        degraded (down to one socket = up to a num_stripes-x throughput
        loss). Best-effort: the surviving stripes keep working even
        when the top-up fails. Landed stripes attach immediately, so a
        cancelled top-up leaks nothing — the channel owns them."""
        missing = self.num_stripes - len(self.stripes)
        if missing <= 0 or self._closed:
            return

        async def _one():
            try:
                s = await self._dial(timeout)
            except ConnectionError as e:
                logger.debug("stripe top-up to %s failed: %r",
                             self.address, e)
                return
            if self._closed:
                s.close()
            else:
                self.stripes.append(_Stripe(s))

        await asyncio.gather(*(_one() for _ in range(missing)))

    @property
    def alive(self) -> bool:
        return bool(self.stripes) and not self._closed

    async def fetch_chunk(self, stripe: _Stripe, oid_b: bytes,
                          offset: int, length: int, dst, dst_off: int
                          ) -> int:
        """Fetch one chunk over ``stripe`` DIRECTLY into
        ``dst[dst_off:dst_off+length]`` (the destination segment
        mapping). Returns the payload size served."""
        loop = asyncio.get_running_loop()
        async with stripe.lock:
            try:
                if faultpoints.armed:
                    # puller-side fault seam: delay storms park here
                    # (awaited, per chunk); raise retires this stripe
                    # through the except below like any wire failure
                    await faultpoints.async_fire(
                        "data.fetch_chunk", offset=offset, length=length)
                await loop.sock_sendall(
                    stripe.sock, _pack_frame([oid_b, offset, length]))
                status, payload_len = await _recv_frame(stripe.sock,
                                                        stripe)
                if status != STATUS_OK:
                    raise ConnectionError("object vanished mid-pull")
                if payload_len != length:
                    # requests are exact (the puller clamps to its
                    # total), so a short serve means this replica's
                    # sealed size diverged: accepting it would seal a
                    # hole of stale segment bytes as valid object data
                    raise ConnectionError(
                        f"short chunk from divergent replica "
                        f"({payload_len} != {length} at {offset})")
                if payload_len:
                    await recv_exact_into(stripe.sock, dst, dst_off,
                                          payload_len, stripe)
            except BaseException:
                # Any failure — including cancellation — may leave
                # unread payload on the wire: the stripe's framing is
                # unrecoverable, so drop it rather than let a later
                # pull read garbage.
                self._drop_stripe(stripe)
                raise
        pull_stats["chunks"] += 1
        pull_stats["bytes"] += payload_len
        _plane_metrics()["bytes_pulled"].inc(payload_len, _TIER_STRIPED)
        return payload_len

    def _drop_stripe(self, stripe: _Stripe) -> None:
        pull_stats["stripe_failures"] += 1
        _plane_metrics()["stripe_failures"].inc()
        try:
            stripe.sock.close()
        except OSError:
            pass
        stripe.wake()  # a parked reader would never see the close
        if stripe in self.stripes:
            self.stripes.remove(stripe)

    async def close(self):
        self._closed = True
        for stripe in self.stripes:
            try:
                stripe.sock.close()
            except OSError:
                pass
            # closing an fd removes it from the selector SILENTLY: a
            # fetch parked in _wait_readable would otherwise hang the
            # pull forever (and pin its admission budget)
            stripe.wake()
        self.stripes = []


# --------------------------------------------------------------------------
# Fan-out engine
# --------------------------------------------------------------------------


async def run_striped(offsets: "Deque[int]",
                      fetchers: List[Callable[[int], Awaitable[None]]]
                      ) -> None:
    """Drain ``offsets`` across ``fetchers`` concurrently (one worker
    per fetcher — a stripe socket, or a legacy control-plane window
    slot). A fetcher that fails hands its in-flight offset back to the
    queue and retires for good; chunks handed back AFTER the surviving
    workers already drained out are re-run on the surviving fetchers in
    a follow-up round (a lost tail chunk must not void a transfer that
    healthy stripes can finish). ConnectionError only when every
    fetcher is dead with work remaining. On any raise — including
    cancellation of the caller — every in-flight worker is cancelled
    and awaited BEFORE this returns, so the caller may close the
    destination mapping immediately after."""
    if not fetchers:
        raise ConnectionError("no data-plane fetchers for pull")
    loop = asyncio.get_running_loop()
    dead: set = set()
    last_err: Optional[BaseException] = None

    async def _worker(idx: int, fetch):
        nonlocal last_err
        while True:
            try:
                off = offsets.popleft()
            except IndexError:
                return
            try:
                await fetch(off)
            except asyncio.CancelledError:
                offsets.appendleft(off)
                raise
            except Exception as e:  # noqa: BLE001 — any stripe failure retires the stripe
                offsets.appendleft(off)
                dead.add(idx)
                last_err = e
                logger.debug("pull stripe %d retired (%d left): %r",
                             idx, len(fetchers) - len(dead), e)
                return

    while offsets:
        lanes = [(i, f) for i, f in enumerate(fetchers) if i not in dead]
        if not lanes:
            raise ConnectionError(
                f"all pull stripes failed mid-pull: {last_err!r}"
            ) from last_err
        tasks = [loop.create_task(_worker(i, f)) for i, f in lanes]
        try:
            await asyncio.gather(*tasks)
        except BaseException:
            # Stop the in-flight siblings BEFORE the caller's segment
            # goes away — an orphan receive into a closed mmap raises
            # and leaks "exception never retrieved" noise.
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            raise
        # offsets non-empty here means some lane died this round while
        # the survivors had already drained out — loop: the handed-back
        # chunks re-run on the still-healthy lanes. Terminates: every
        # extra round strictly grows ``dead`` (a round leaves work
        # behind only by failing at least one lane) or drains the queue.
