"""Typed runtime config registry.

Equivalent of the reference's RAY_CONFIG flag registry
(reference: src/ray/common/ray_config_def.h): every tunable is a typed entry
with a default, overridable by (priority order) an explicit
``_system_config`` dict passed to ``init()``/process argv, then the
``RAY_TPU_<NAME>`` environment variable.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, fields
from typing import Any, Dict


def _env_override(name: str, typ, default):
    raw = os.environ.get(f"RAY_TPU_{name.upper()}")
    if raw is None:
        return default
    if typ is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    if typ is int:
        return int(raw)
    if typ is float:
        return float(raw)
    return raw


@dataclass
class RayTpuConfig:
    # --- object plane ---
    # Values at or below this size are returned/passed inline through the
    # owner's in-process memory store rather than the shared-memory store
    # (reference: max_direct_call_object_size, ray_config_def.h).
    max_direct_call_object_size: int = 100 * 1024
    # Size of the shared-memory object store arena per node, bytes.
    object_store_memory: int = 512 * 1024 * 1024
    # Fraction of the store that may be used before create requests block.
    object_store_full_delay_ms: int = 10
    # Enable spilling objects to disk when the store fills.
    object_spilling_enabled: bool = True
    spill_path: str = ""
    # External spill target (reference: external_storage.py S3 via
    # smart_open): a workflow-storage URL (file:///shared, kv://, or
    # s3://bucket/prefix) that overrides the local spill dir.
    spill_external_storage_url: str = ""
    # Chunk size for node-to-node object transfer. This is the FLOOR of
    # the data plane's adaptive chunking (and the fixed chunk of the
    # legacy control-plane pull): large objects scale their chunk up to
    # data_plane_max_chunk_size so per-chunk request overhead amortizes.
    object_manager_chunk_size: int = 1024 * 1024
    # Striped raw-socket data channels per peer for cross-node object
    # pulls (the bulk transport under the msgpack control plane; see
    # data_channel.py). Chunks fan out across the stripes — and across
    # every replica-holding peer — and land directly in the destination
    # shm mapping (one copy per chunk). 0 disables the data plane
    # entirely: pulls fall back to chunked FetchObjectChunk RPCs on the
    # shared control connection (the pre-data-plane path).
    data_plane_stripes: int = 4
    # Ceiling of the adaptive per-chunk size on the striped data plane.
    # object_manager_chunk_size stays the floor; multi-GiB objects use
    # chunks up to this size so the transfer is syscall-bound, not
    # round-trip-bound.
    data_plane_max_chunk_size: int = 8 * 1024 * 1024
    # When every known location of an object fails mid-pull, the raylet
    # re-queries the owner's location index after a backoff — a replica
    # added meanwhile (e.g. by a concurrent pull elsewhere) is found
    # instead of erroring the get. This is the BASE delay of the
    # exponential-jitter policy (backoff.py); the refresh is attempted
    # pull_location_refresh_attempts times.
    pull_location_refresh_backoff_s: float = 0.2
    # How many location-refresh rounds a failing pull gets before the
    # get errors (1 preserves the original one-shot refresh; each extra
    # round backs off exponentially from
    # pull_location_refresh_backoff_s up to retry_backoff_cap_s).
    pull_location_refresh_attempts: int = 1

    # --- scheduling ---
    # Pipeline depth CEILING for pushing tasks to a leased worker before
    # waiting for replies (reference: max_tasks_in_flight_per_worker;
    # far deeper here — the batched submit/reply path amortizes bursts:
    # measured 16.7k/s at 32, plateau 22.2k/s at 512 on the task
    # microbenchmark). The transport fills BREADTH-first: batches are
    # sized to an even split over current+pending workers, and this cap
    # only bites once the cluster stops granting leases.
    max_tasks_in_flight_per_worker: int = 512
    # Outstanding lease requests per scheduling class (reference:
    # max_pending_lease_requests_per_scheduling_category); requested in
    # proportion to the backlog, ~one per 8 queued tasks.
    max_pending_leases_per_scheduling_class: int = 16
    # How long an idle leased worker is kept before returning it to the
    # pool. Returning instantly makes every sync-loop task pay a fresh
    # lease round trip through the raylet (~500us of the sync row).
    idle_lease_keepalive_s: float = 0.2
    # Hybrid policy: prefer the local/first node until its utilization
    # exceeds this threshold, then spread (reference: scheduler_spread_threshold).
    scheduler_spread_threshold: float = 0.5
    # Which scheduler backend the raylet uses: "host" (dict/heap reference
    # implementation) or "tpu_batched" (JAX batched frontier/scoring kernel).
    scheduler_backend: str = "host"
    # What happens to a task no node can currently satisfy: "fail" the
    # lease (fast feedback) or "wait" in the queue until capacity
    # appears — dynamic resources / autoscaled nodes (the reference
    # keeps infeasible tasks pending and warns).
    infeasible_task_policy: str = "fail"
    # Max tasks the batched backend scores per tick.
    scheduler_batch_size: int = 4096
    # Lease reuse: keep an idle leased worker this long before returning it.
    idle_worker_lease_timeout_ms: int = 2000

    # --- streaming lease credits ---
    # Master switch for streaming leases. On (the default) the raylet
    # pre-grants each owner a revocable CREDIT WINDOW of worker slots
    # per scheduling class — leases as a flow-controlled stream instead
    # of a per-lease request/grant ping-pong. The owner's submit path
    # (including the C fastpath) dispatches tasks against local credits
    # with zero control-plane round-trips on the hot path and falls
    # back to the legacy RequestWorkerLease path when credits are
    # exhausted, revoked, or this knob is off. Wire frames:
    # GrantLeaseCredits (raylet -> owner push: credits + window target,
    # issued on demand registration and renewed on the heartbeat
    # cadence) and RevokeLeaseCredits (raylet -> owner call: the owner
    # relinquishes the listed credits it is not using; in-use ones are
    # kept and reconciled on a later beat). Memory pressure (PR10)
    # zeroes and revokes windows BEFORE lease backpressure engages —
    # revocation is a first-class recovery path, chaos-soaked by the
    # credit_revoke schedule.
    lease_credits_enabled: bool = True
    # Ceiling on credit worker-slots outstanding per (owner connection,
    # scheduling class). The actual window is sized from the owner's
    # reported backlog and the REAL scheduler view (cluster slot
    # capacity for the window's resource shape), clamped by this.
    lease_credit_window_max: int = 64
    # Unused-credit reclaim cadence: a window whose demand report is
    # older than this gets its outstanding credits offered back via
    # RevokeLeaseCredits on the next heartbeat (the owner keeps the
    # ones it is actively using). Bounds how long an idle owner can
    # park pool slots it no longer needs.
    lease_credit_stale_s: float = 2.0

    # --- SPMD gangs & distributed arrays ---
    # How many times the driver re-asks for a gang lease after an
    # all-or-nothing booking round came back short (retry_later). Each
    # rejection prestarts workers toward the deficit on the raylets
    # that ran dry, so retries converge instead of re-probing the same
    # empty pool; the wait between rounds follows the shared
    # exponential-jitter policy (backoff.py) starting from
    # gang_lease_retry_backoff_s. 0 = a single attempt, fail fast.
    gang_lease_retry_attempts: int = 20
    # BASE delay between gang-lease booking rounds (exponential-jitter
    # up to retry_backoff_cap_s). Short by default: the common cause of
    # a short round is workers still forking, which resolves in tens of
    # milliseconds.
    gang_lease_retry_backoff_s: float = 0.1
    # Per-member worker-socket dial timeout when the driver adopts a
    # freshly granted gang. A member that cannot be dialed inside this
    # window fails the formation (the whole gang is released — all-or-
    # nothing extends to adoption, not just booking).
    gang_member_dial_timeout_s: float = 5.0
    # Per-run override of the striped chunk size for GatherShards
    # collective transfers (reshard / all-gather / all-reduce). 0 (the
    # default) keeps the pull path's adaptive sizing:
    # object_manager_chunk_size floor, data_plane_max_chunk_size
    # ceiling, ~8 chunks per stripe lane.
    reshard_chunk_bytes: int = 0
    # Which algorithm all_reduce / all_gather use when every precondition
    # holds: "ring" (the default — bandwidth-optimal reduce-scatter +
    # all-gather, per-rank wire traffic 2*(P-1)/P*N bytes) or "fold"
    # (the PR15 single-destination GatherShards path, (P-1)*N per
    # destination). Ring silently falls back to fold when it cannot
    # apply: fewer than 3 ranks, data plane off
    # (data_plane_stripes=0), or a source layout whose segments the
    # ring math cannot partition (see the README fallback matrix).
    collective_algorithm: str = "ring"
    # Per-member scratch WINDOW size for the pipelined ring fold: each
    # reduce step double-buffers two windows of this size so segment
    # bytes for window k+1 stream off the wire while window k folds in
    # an executor thread. Bigger windows amortize per-window overhead;
    # smaller ones overlap sooner and cap the fold's cache footprint.
    # Segments smaller than the window use one exact-size buffer pair.
    collective_scratch_bytes: int = 16 * 1024 * 1024
    # How long a ring-collective member record (and its leased
    # accumulator segment) may sit idle before the raylet's
    # opportunistic sweep discards it. Members are normally freed by
    # RingFinish/RingAbort; the TTL only catches a driver that died
    # between rounds without aborting.
    collective_member_ttl_s: float = 120.0

    # --- worker pool ---
    # Hard cap on workers started per node (0 = num_cpus).
    max_workers_per_node: int = 0
    # Workers prestarted at node boot. -1 = auto: one per CPU (the
    # reference's PrestartWorkers heuristic, worker_pool.h:94 — cold
    # leases then never pay process-start latency). 0 disables.
    num_prestart_workers: int = -1
    worker_register_timeout_s: float = 30.0
    # Zygote worker factory (zygote.py): one forkserver-style template
    # process per raylet pre-imports the worker module graph and
    # pre-builds the native fastpath, then fork()s per spawn request —
    # worker/actor startup and post-kill recovery become milliseconds
    # instead of a full interpreter boot (bench.py worker_spawn row).
    # Takes effect only where forking is safe: Linux, and ONLY when the
    # workers run a forkable platform — raylets whose workers use a TPU
    # platform (RAY_TPU_WORKER_JAX_PLATFORMS contains "tpu"/"axon", or
    # is empty = inherit) always cold-Popen, because an initialized
    # accelerator client must never be forked. Cold Popen is also the
    # automatic fallback when the template dies mid-session.
    worker_zygote_enabled: bool = True
    # Comma list of EXTRA modules the zygote pre-imports on top of the
    # default worker graph (core_worker, task_executor, rpc,
    # serialization, worker_main + the ray_tpu package). Keep entries
    # fork-safe: no threads, no event loops, no accelerator backends at
    # import time (jax is deliberately absent from the default list).
    zygote_preload_modules: str = ""

    # --- memory watchdog (memory_monitor.py) ---
    # Master switch for the raylet-side node memory watchdog. On (the
    # default) the raylet polls node memory on its heartbeat cadence
    # and, above memory_usage_threshold, runs the ordered degradation
    # sequence: store spill/evict pressure relief, then SIGKILL of the
    # most-recently-started retriable task's worker (surfaced to the
    # owner as a retriable OutOfMemoryError), plus lease backpressure
    # (new lease requests spill to other nodes or get a typed
    # retry-later) — instead of letting the kernel OOM killer shoot a
    # random process (often the raylet or GCS) and take the node down.
    memory_monitor_enabled: bool = True
    # Node-memory usage fraction above which the watchdog engages
    # (reference: RAY_memory_usage_threshold, default 0.95). Usage is
    # cgroup-aware: a container's memory limit wins over the host
    # total, so the threshold tracks the boundary the kernel OOM
    # killer actually enforces.
    memory_usage_threshold: float = 0.95
    # Minimum seconds between watchdog evaluations. The poll rides the
    # raylet heartbeat loop (no extra thread/timer), so the effective
    # cadence is max(this, raylet_heartbeat_period_ms). Each poll does
    # a handful of µs-scale procfs reads; bench.py's
    # memory_monitor_overhead row pins the cost under 2%.
    memory_monitor_interval_s: float = 0.5
    # Dedicated retry budget for watchdog OOM kills, SEPARATE from
    # max_retries: a task killed for memory pressure did nothing wrong
    # and shouldn't burn its worker-crash budget, but unbounded OOM
    # retries of a genuinely ballooning task would thrash the node
    # forever. Retries are paced with the shared exponential-jitter
    # backoff (backoff.py). 0 = never retry OOM kills; -1 = unlimited.
    # Non-retriable tasks (max_retries=0) always surface
    # OutOfMemoryError immediately.
    task_oom_retries: int = 3

    # --- liveness / fault tolerance ---
    raylet_heartbeat_period_ms: int = 250
    # 10s of silence marks a node dead (reference default ≈3s; wider
    # here because an in-process head under full single-host task load
    # can delay the heartbeat coroutine by seconds — GIL + loop
    # occupancy — and a false node death kills the whole bench).
    num_heartbeats_timeout: int = 40
    task_max_retries_default: int = 3
    actor_max_restarts_default: int = 0
    # Enable lineage-based reconstruction of lost shared-memory objects.
    lineage_reconstruction_enabled: bool = True
    lineage_max_bytes: int = 64 * 1024 * 1024

    # --- rpc ---
    rpc_connect_timeout_s: float = 10.0
    rpc_frame_max_bytes: int = 1 << 31
    gcs_port: int = 0
    # Append-only metadata journal for GCS restart recovery ("" = off)
    # (reference: GcsTableStorage persistence + GcsInitData reload).
    gcs_journal_path: str = ""
    # How long a raylet keeps retrying to reach a restarting GCS.
    gcs_reconnect_timeout_s: float = 60.0
    # Shared retry/backoff policy (backoff.py): every reconnect /
    # re-resolve loop (raylet->GCS redial, actor re-resolution, pull
    # location refresh) backs off exponentially with full jitter from
    # this base up to this cap, so failure storms never produce
    # fixed-interval thundering herds. Multiplier is the growth factor
    # per attempt.
    retry_backoff_base_s: float = 0.05
    retry_backoff_cap_s: float = 2.0
    retry_backoff_multiplier: float = 2.0

    # --- serving (ray_tpu/serve) ---
    # Request/response bodies at or above this size (bytes) cross the
    # proxy->replica boundary BY REFERENCE: the HTTP proxy writes the
    # body straight into shm through the AllocSegment lease path
    # (core_worker.put_async — the same recycled-segment pipeline as
    # any large put) and ships an ObjectRef, so a 100 MB upload costs
    # one shm fill instead of riding the pickle lane through the
    # control plane. Bodies below the threshold stay inline (a ref
    # round trip costs more than a small copy). 0 disables the shm
    # ingress path entirely. Large replica RETURNS need no knob: the
    # task-return plane already seals them into the store.
    serve_ingress_shm_threshold: int = 64 * 1024
    # Per-replica queue-depth cap, enforced replica-side on top of the
    # router's max_concurrent_queries flow control: a replica that
    # somehow accumulates more than max_concurrent_queries +
    # serve_max_queue_depth in-flight calls (several independent
    # routers, a handle that bypassed flow control) sheds the excess
    # with the typed ServeOverloadedError instead of queueing without
    # bound. Also the default queue cap of a DecodeScheduler built by
    # a replica that doesn't pass its own.
    serve_max_queue_depth: int = 16
    # The proxy's admission-controller queue budget, as a multiple of
    # the deployment's dispatch capacity (replicas x
    # max_concurrent_queries): once waiting + in-flight requests reach
    # capacity x this factor, new requests are shed at the door with
    # 503 + Retry-After (the serving analog of the lease plane's
    # retry_later) instead of joining a backlog the replicas can never
    # drain. 2.0 = allow one full batch queued behind the one in
    # flight. Must be >= 1; larger values trade shed rate for queueing
    # latency.
    serve_shed_queue_factor: float = 2.0
    # Optional latency half of the SLO budget (seconds; 0 = queue-only
    # shedding): when set, the proxy also sheds while the deployment's
    # observed p99 (rolling per-proxy reservoir, fed to the metrics
    # registry as ray_tpu_serve_request_seconds) exceeds this budget
    # AND every replica slot is busy — a saturated deployment with
    # degraded tails sheds before the backlog doubles the damage.
    serve_shed_p99_budget_s: float = 0.0
    # Floor (seconds) of the Retry-After hint on shed responses. The
    # proxy scales the hint with the observed backlog (queue depth x
    # mean latency / capacity, capped at 30 s); this knob is the
    # minimum — and the whole hint when no latency samples exist yet.
    serve_retry_after_s: float = 1.0

    # --- observability ---
    event_log_enabled: bool = True
    metrics_report_period_ms: int = 2000
    # Task-lifecycle event recording (task_events.py): every task gets
    # a recorded state machine (SUBMITTED -> PENDING_LEASE ->
    # DISPATCHED -> RUNNING -> FINISHED|FAILED plus retry/spillback
    # annotations) surfaced by ray_tpu.state.list_tasks()/timeline().
    # ON by default — the history must exist when the straggler
    # happens; bench.py's task_events_overhead row pins the submit-path
    # cost under 5%.
    task_events_enabled: bool = True
    # Per-process event buffer capacity (events, not bytes). When full,
    # NEW transitions are dropped and counted (TaskEventBuffer.dropped
    # -> GCS dropped_events) — memory stays flat, the hot path never
    # blocks on observability. Also bounds the per-flush wire batch
    # (the whole buffer ships each reporting period): 16384 events ~=
    # 1.5 MB worst case.
    task_events_buffer_size: int = 16384
    # GCS task-table cap per job: oldest-seen tasks are evicted first
    # and the eviction is COUNTED per job (GetTaskSummary
    # evicted_tasks), so a truncated view always reports as truncated.
    task_events_max_tasks_per_job: int = 8192
    # Object-lifecycle event recording (object_events.py): the
    # object-plane twin of task_events — every plasma/borrowed/
    # contained object's lifecycle (CREATED -> SEALED/PINNED ->
    # BORROWED/PULLED/locations -> OUT_OF_SCOPE/FREED, plus
    # eviction/spill/restore and the leak-detector verdicts) recorded
    # at the layer that owns each transition and surfaced by
    # ray_tpu.state.list_objects() / summary_objects() /
    # memory_summary() / timeline(). ON by default; bench.py's
    # object_events_overhead row pins the put/get cost under 5%.
    object_events_enabled: bool = True
    # Per-process object-event buffer capacity (events, not bytes).
    # Same honest-truncation contract as task_events_buffer_size: when
    # full, NEW transitions are dropped and counted — memory stays
    # flat, the put/free hot paths never block on observability.
    object_events_buffer_size: int = 16384
    # GCS object-table cap per job (the job is read off the object id
    # prefix): oldest-seen objects are evicted first and the eviction
    # is COUNTED per job (GetObjectSummary evicted_objects) — a
    # truncated view always reports as truncated.
    object_events_max_objects_per_job: int = 8192
    # Leak-detector sweep cadence (seconds; 0 disables). Each sweep the
    # raylet cross-checks store-held segments against live owner
    # references (one batched ProbeObjectLiveness per owner): an object
    # whose owner holds no reference — a dropped FreeObject, a
    # SIGKILLed owner — is flagged LEAKED (objects_leaked gauge,
    # leaked=True in list_objects()) on its second dead verdict and
    # reclaimed (freed + LEAK_RECLAIMED, counter back to 0) one sweep
    # later. Objects younger than one interval, and objects whose
    # owner cannot be judged (probe unsupported / transient error),
    # are never touched.
    leak_sweep_interval_s: float = 5.0
    # Per-method RPC telemetry (rpc.py RpcTelemetry): the control-plane
    # flight recorder. ON by default — server side records exec-time
    # percentiles, queueing delay (frame arrival -> handler start),
    # bytes in/out, in-flight and error counts per method; client side
    # records per-method call latency, timeout/redial counts and push
    # bytes; the loop-lag probe rides the existing periodic loops. All
    # bounded and drop-counted; surfaced by ray_tpu.state.list_rpc() /
    # summary_rpc(), /api/rpc, Prometheus per-method histograms, and
    # timeline() cat="rpc" slices. bench.py's rpc_telemetry_overhead
    # row pins the submit-path cost under 2%. Off = no recording at
    # all (the note paths are one bool check).
    rpc_telemetry_enabled: bool = True
    # Bounded per-(side, method) latency reservoir size (samples, not
    # bytes). Reservoirs drop OLDEST when full — percentiles are
    # recency-biased by design — and the drop count is reported
    # honestly (count - samples) in every snapshot.
    rpc_telemetry_reservoir: int = 512
    # Width (seconds) of the rotating max window behind every reported
    # max_ms (RPC telemetry AND the legacy rpc_handlers block): the max
    # covers the worst of the last one-to-two windows, so dashboards
    # reflect recent behavior instead of an all-time high-water mark
    # from a cold start a week ago.
    rpc_stats_window_s: float = 60.0
    # Slow-callback / slow-call threshold (milliseconds), the
    # instrumented-io-context analog: an RPC handler exceeding it logs
    # a WARNING naming the handler and counts into slow_callbacks; a
    # loop-lag probe sample exceeding it logs the loop occupancy; and
    # any server/client call above it becomes a bounded slow-call
    # record that timeline() renders as a cat="rpc" slice on the same
    # wall clock as tasks/objects/pulls.
    loop_slow_callback_threshold_ms: float = 200.0
    # Per-process cluster-event buffer capacity (events, not bytes):
    # EventEmitter emissions (node/worker death, OOM kills, leak
    # reclaims, credit revokes, backpressure engage/clear, zygote
    # fallbacks...) buffer here and ship piggybacked on the heartbeat
    # (raylets) or the metrics-report loop (workers/drivers). When
    # full, NEW events are dropped and counted — the hot path never
    # blocks on observability.
    cluster_event_buffer_size: int = 4096
    # GCS ClusterEventTable cap: beyond it the OLDEST events are
    # evicted and the eviction is COUNTED (GetClusterEvents summary) —
    # a truncated event feed always reports as truncated. Events carry
    # a GCS-assigned monotonic seq, so ordering survives reporter
    # clock skew.
    cluster_events_max: int = 10_000
    # Cluster-KV span cap for util/tracing.py exports: beyond this many
    # stored spans the GCS evicts the OLDEST whole trace (and counts
    # the drop in the __rtpu_trace_dropped__ KV key /
    # tracing.dropped_span_count()) so long-running clusters with
    # RAY_TPU_TRACE=1 don't leak the KV and its journal. 0 = unbounded
    # (the pre-cap behavior).
    tracing_max_spans: int = 100_000
    # Prometheus text endpoint on the GCS host (0 = auto-assign; the
    # bound address lands in the KV key __rtpu_metrics_address__).
    metrics_export_port: int = 0
    profiling_enabled: bool = True
    debug_dump_period_ms: int = 10000

    extra: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def create(cls, system_config: Dict[str, Any] | None = None) -> "RayTpuConfig":
        cfg = cls()
        for f in fields(cls):
            if f.name == "extra":
                continue
            setattr(cfg, f.name, _env_override(f.name, f.type if isinstance(f.type, type) else type(getattr(cfg, f.name)), getattr(cfg, f.name)))
        if system_config:
            known = {f.name for f in fields(cls)}
            for k, v in system_config.items():
                if k in known:
                    setattr(cfg, k, v)
                else:
                    cfg.extra[k] = v
        return cfg

    def to_json(self) -> str:
        d = {f.name: getattr(self, f.name) for f in fields(self) if f.name != "extra"}
        d.update(self.extra)
        return json.dumps(d)

    @classmethod
    def from_json(cls, s: str) -> "RayTpuConfig":
        return cls.create(json.loads(s))


_global_config: RayTpuConfig | None = None


def get_config() -> RayTpuConfig:
    global _global_config
    if _global_config is None:
        _global_config = RayTpuConfig.create()
    return _global_config


def set_config(cfg: RayTpuConfig) -> None:
    global _global_config
    _global_config = cfg
