"""ObjectRef: a first-class future naming an object in the cluster.

Parity: reference ObjectRef (python/ray/includes/object_ref.pxi) — hashable,
awaitable, refcounted on construction/destruction so the owner can release
the value when the last reference anywhere drops.
"""

from __future__ import annotations

import asyncio
from typing import Any, Optional

from ray_tpu._private.ids import ObjectID


class ObjectRef:
    # no __weakref__ slot: nothing weakrefs ObjectRefs, and the header
    # is per-task allocation cost on the submit hot path
    __slots__ = ("object_id", "owner_address", "_worker", "call_site")

    def __init__(self, object_id: ObjectID, owner_address: str = "",
                 worker=None, skip_adding_local_ref: bool = False,
                 call_site: str = ""):
        self.object_id = object_id
        self.owner_address = owner_address
        self._worker = worker
        self.call_site = call_site
        if worker is not None and not skip_adding_local_ref:
            worker.reference_counter.add_local_reference(object_id)

    def binary(self) -> bytes:
        return self.object_id.binary()

    def hex(self) -> str:
        return self.object_id.hex()

    def task_id(self):
        return self.object_id.task_id()

    def __hash__(self):
        return hash(self.object_id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.object_id == self.object_id

    def __repr__(self):
        return f"ObjectRef({self.object_id.hex()})"

    def __del__(self):
        worker = self._worker
        if worker is not None:
            try:
                worker.queue_local_decref(self.object_id)
            # raylint: disable=exception-hygiene — __del__ during interpreter teardown: anything may be half-dead
            except Exception:
                pass

    def __reduce__(self):
        # Bare pickling (outside the SerializationContext) drops ownership
        # info; the context's reducer_override path is the supported one.
        return (ObjectRef, (self.object_id, self.owner_address, None, True))

    # -- asyncio integration ------------------------------------------------

    def as_future(self) -> "asyncio.Future":
        """asyncio future on the CALLING loop (the value fetch itself
        runs on the core worker's IO loop; wrap_future bridges)."""
        if self._worker is None:
            raise RuntimeError("ObjectRef is detached from a worker")
        import asyncio

        return asyncio.wrap_future(self._worker.get_async(self))

    def __await__(self):
        return self.as_future().__await__()

    def future(self):
        """concurrent.futures-style Future resolving to the value."""
        if self._worker is None:
            raise RuntimeError("ObjectRef is detached from a worker")
        return self._worker.get_future(self)
