"""Sharded DistributedArray: mesh/PartitionSpec metadata + reshard plans.

The shard-native array layer (ROADMAP item 2): a ``DistributedArray`` is
a set of first-class objects — one C-contiguous ndarray shard per mesh
rank, living in the shm store of the node that produced it — tied
together by mesh + ``PartitionSpec`` metadata carried on the driver-side
handle and by a shard-group lineage unit in the owner's reference
counter (reference_count.Reference.shard_group). The jax analogy is
``GlobalDeviceArray``/``jax.sharding.NamedSharding``: the mesh names
axes, the spec maps array dims onto mesh axes, and every rank can
compute everyone else's slice without communication.

This module is pure metadata + plan math — no I/O. The driver-side
verbs (``put_sharded`` / ``get_shard`` / ``assemble`` / ``reshard`` /
collectives) live on the CoreWorker; the raylet's ``GatherShards``
handler executes the byte-run plans computed here against the striped
data plane. Both sides import the SAME plan functions, so the wire
protocol only ever carries absolute (src_offset, dst_offset, length)
byte runs — the receiving raylet never re-derives slice math.

Byte-run model: every shard segment has the store's standard layout
``[u32 header_len][msgpack([metadata, frame_lens])][pickle payload]
[raw array bytes]`` (shm_store.plan_segment). For a C-contiguous numpy
shard the raw bytes are frame 1, at a known absolute offset recorded on
the shard ref at put time (``ShardInfo.data_offset``). A reshard is
then a pure byte-scatter: intersect the source rank's index box with
the destination rank's box, emit one run per contiguous row of the
intersection (coalesced when both sides stay contiguous), offset both
ends into segment-absolute coordinates, and let ``fetch_chunk`` /
``recv_exact_into`` land every run straight into the destination
segment — zero intermediate copies.
"""

from __future__ import annotations

import itertools
import math
from typing import Dict, List, Optional, Sequence, Tuple

import msgpack

from ray_tpu._private.shm_store import _align8

__all__ = [
    "Mesh", "PartitionSpec", "ShardInfo", "DistributedArray",
    "shard_slices", "shard_shape", "byte_runs", "gather_plan",
    "frame_plan", "balanced_split",
    "ring_segments", "ring_reduce_schedule", "ring_gather_schedule",
]


def balanced_split(n: int, parts: int) -> List[Tuple[int, int]]:
    """Split ``range(n)`` into ``parts`` contiguous near-equal blocks.

    The first ``n % parts`` blocks get one extra element (jax's
    convention requires even divisibility; we relax to balanced blocks
    so any global shape shards over any mesh)."""
    q, r = divmod(n, parts)
    out = []
    start = 0
    for i in range(parts):
        stop = start + q + (1 if i < r else 0)
        out.append((start, stop))
        start = stop
    return out


class Mesh:
    """A named cartesian grid of ranks, e.g. ``Mesh((2, 4), ("dp", "mp"))``.

    Ranks are numbered in C order over the grid; ``coords(rank)`` gives
    the grid coordinates. Nodes are NOT part of the mesh — placement of
    ranks onto nodes is recorded per-shard on the DistributedArray."""

    __slots__ = ("shape", "axis_names")

    def __init__(self, shape: Sequence[int], axis_names: Sequence[str]):
        shape = tuple(int(s) for s in shape)
        axis_names = tuple(axis_names)
        if len(shape) != len(axis_names):
            raise ValueError("mesh shape and axis_names length mismatch")
        if any(s <= 0 for s in shape):
            raise ValueError(f"mesh shape must be positive: {shape}")
        if len(set(axis_names)) != len(axis_names):
            raise ValueError(f"duplicate mesh axis names: {axis_names}")
        self.shape = shape
        self.axis_names = axis_names

    @property
    def nranks(self) -> int:
        return math.prod(self.shape)

    def axis_size(self, name: str) -> int:
        return self.shape[self.axis_names.index(name)]

    def coords(self, rank: int) -> Tuple[int, ...]:
        out = []
        for s in reversed(self.shape):
            out.append(rank % s)
            rank //= s
        return tuple(reversed(out))

    def to_wire(self) -> dict:
        return {"shape": list(self.shape),
                "axis_names": list(self.axis_names)}

    @classmethod
    def from_wire(cls, d: dict) -> "Mesh":
        return cls(d["shape"], d["axis_names"])

    def __eq__(self, other):
        return (isinstance(other, Mesh) and self.shape == other.shape
                and self.axis_names == other.axis_names)

    def __hash__(self):
        return hash((self.shape, self.axis_names))

    def __repr__(self):
        body = ", ".join(f"{n}={s}"
                         for n, s in zip(self.axis_names, self.shape))
        return f"Mesh({body})"


class PartitionSpec:
    """Maps array dimensions onto mesh axes, jax-style.

    ``PartitionSpec("dp", None)`` shards dim 0 over mesh axis "dp" and
    replicates dim 1. Entries beyond the array's rank are rejected at
    use time; missing trailing entries mean replicated. A fully-empty
    spec (``PartitionSpec()``) replicates the whole array — every rank
    holds a full copy (the all-gather destination layout)."""

    __slots__ = ("entries",)

    def __init__(self, *entries: Optional[str]):
        self.entries = tuple(entries)

    def to_wire(self) -> list:
        return list(self.entries)

    @classmethod
    def from_wire(cls, entries) -> "PartitionSpec":
        return cls(*entries)

    def __eq__(self, other):
        return (isinstance(other, PartitionSpec)
                and self.entries == other.entries)

    def __hash__(self):
        return hash(self.entries)

    def __repr__(self):
        return f"PartitionSpec({', '.join(map(repr, self.entries))})"


def _validate(global_shape, mesh: Mesh, spec: PartitionSpec) -> None:
    if len(spec.entries) > len(global_shape):
        raise ValueError(
            f"PartitionSpec has {len(spec.entries)} entries for a "
            f"{len(global_shape)}-d array")
    seen = set()
    for name in spec.entries:
        if name is None:
            continue
        if name not in mesh.axis_names:
            raise ValueError(f"unknown mesh axis {name!r} in {spec!r} "
                             f"(mesh axes: {mesh.axis_names})")
        if name in seen:
            raise ValueError(f"mesh axis {name!r} used twice in {spec!r}")
        seen.add(name)


def _rank_box(global_shape, mesh: Mesh, spec: PartitionSpec,
              rank: int) -> List[Tuple[int, int]]:
    """The index box [(start, stop), ...] of ``rank``'s shard."""
    coords = mesh.coords(rank)
    box = []
    for d, n in enumerate(global_shape):
        name = spec.entries[d] if d < len(spec.entries) else None
        if name is None:
            box.append((0, n))
        else:
            a = mesh.axis_names.index(name)
            box.append(balanced_split(n, mesh.shape[a])[coords[a]])
    return box


def shard_slices(global_shape, mesh: Mesh,
                 spec: PartitionSpec) -> List[Tuple[slice, ...]]:
    """Per-rank index slices into the global array, rank-ordered."""
    _validate(global_shape, mesh, spec)
    return [tuple(slice(a, b) for a, b in
                  _rank_box(global_shape, mesh, spec, r))
            for r in range(mesh.nranks)]


def shard_shape(global_shape, mesh: Mesh, spec: PartitionSpec,
                rank: int) -> Tuple[int, ...]:
    _validate(global_shape, mesh, spec)
    return tuple(b - a for a, b in _rank_box(global_shape, mesh, spec, rank))


def _box_offset(idx, box, itemsize: int, row: int) -> int:
    """Byte offset of element ``idx`` (global coords, last dim = start
    of the run's row at ``row``) inside the C-contiguous shard whose
    index box is ``box``."""
    off = 0
    for d in range(len(box) - 1):
        extent = box[d][1] - box[d][0]
        off = off * extent + (idx[d] - box[d][0])
    last = box[-1][1] - box[-1][0]
    return (off * last + (row - box[-1][0])) * itemsize


def byte_runs(itemsize: int, src_box, dst_box) -> List[List[int]]:
    """Contiguous byte runs moving the intersection of two index boxes.

    Returns ``[[src_off, dst_off, length], ...]`` with offsets relative
    to each shard's own C-contiguous data buffer. One run per row of the
    intersection (a row — fixed leading indices, a contiguous range of
    the last dim — is contiguous inside ANY C-contiguous shard);
    consecutive rows are coalesced whenever both source and destination
    offsets advance exactly by the run length, so a same-layout copy
    collapses to a single run."""
    inter = []
    for (sa, sb), (da, db) in zip(src_box, dst_box):
        a, b = max(sa, da), min(sb, db)
        if a >= b:
            return []
        inter.append((a, b))
    row_len = (inter[-1][1] - inter[-1][0]) * itemsize
    row0 = inter[-1][0]
    runs: List[List[int]] = []
    for lead in itertools.product(*[range(a, b) for a, b in inter[:-1]]):
        idx = lead + (row0,)
        s = _box_offset(idx, src_box, itemsize, row0)
        d = _box_offset(idx, dst_box, itemsize, row0)
        if runs and runs[-1][0] + runs[-1][2] == s \
                and runs[-1][1] + runs[-1][2] == d:
            runs[-1][2] += row_len
        else:
            runs.append([s, d, row_len])
    return runs


def gather_plan(global_shape, itemsize: int,
                mesh_src: Mesh, spec_src: PartitionSpec,
                mesh_dst: Mesh, spec_dst: PartitionSpec
                ) -> List[List[Tuple[int, List[List[int]]]]]:
    """Full reshard plan: for every destination rank, which source ranks
    contribute which byte runs. ``plan[dst_rank]`` is a list of
    ``(src_rank, [[src_off, dst_off, length], ...])`` with offsets
    relative to each shard's raw data frame (the caller rebases them to
    segment-absolute by adding each segment's data_offset)."""
    _validate(global_shape, mesh_src, spec_src)
    _validate(global_shape, mesh_dst, spec_dst)
    src_boxes = [_rank_box(global_shape, mesh_src, spec_src, r)
                 for r in range(mesh_src.nranks)]
    plan = []
    for dr in range(mesh_dst.nranks):
        dst_box = _rank_box(global_shape, mesh_dst, spec_dst, dr)
        contribs = []
        covered = 0
        need = math.prod(b - a for a, b in dst_box) * itemsize
        # Replicated sources share identical boxes; one representative
        # per distinct box keeps contributions disjoint (distinct boxes
        # of a balanced partition tile without partial overlap), so
        # coverage accounting is exact.
        seen_boxes = set()
        for sr, src_box in enumerate(src_boxes):
            box_key = tuple(src_box)
            if box_key in seen_boxes:
                continue
            seen_boxes.add(box_key)
            runs = byte_runs(itemsize, src_box, dst_box)
            if runs:
                contribs.append((sr, runs))
                covered += sum(r[2] for r in runs)
            if covered >= need:
                break  # dest box fully covered
        plan.append(contribs)
    return plan


def frame_plan(metadata: bytes, frame_lens: Sequence[int]):
    """(header, offsets, total) for a segment holding frames of the given
    lengths — the same math as shm_store.plan_segment, but from sizes
    alone, so a GatherShards destination can lay out its segment before
    a single payload byte exists."""
    header = msgpack.packb([metadata, list(frame_lens)], use_bin_type=True)
    total = _align8(4 + len(header))
    offsets = []
    for n in frame_lens:
        offsets.append(total)
        total = _align8(total + n)
    return header, offsets, total


# --------------------------------------------------------------------------
# Ring collective plan math (pure, shared by driver and raylet).
#
# A ring all-reduce over P ranks partitions every rank's data frame into
# P element-aligned segments and runs two phases of P-1 steps each
# around the rank cycle r -> (r+1) % P:
#
#   reduce-scatter step s: rank r pulls segment (r-s-1) mod P from rank
#     r-1 and FOLDS it into its own accumulator — after P-1 steps rank r
#     owns the fully-reduced segment (r+1) mod P;
#   all-gather step s: rank r pulls the finished segment (r-s) mod P
#     from rank r-1 (pure copy).
#
# Every rank moves each segment at most twice, so per-rank wire traffic
# is 2*(P-1)/P * N bytes — the bandwidth-optimal bound — versus the fold
# path's (P-1)*N. Both wire ends derive the identical plan from (rank,
# nranks) alone: the sender never needs to be told what the receiver
# will ask for, and the receiving raylet never re-derives slice math —
# the RPCs carry absolute (segment offset, length) byte runs computed
# from these functions.
# --------------------------------------------------------------------------


def ring_segments(nbytes: int, itemsize: int,
                  nranks: int) -> List[Tuple[int, int]]:
    """Partition a ``nbytes`` data frame into ``nranks`` contiguous
    element-aligned ``(offset, length)`` segments — ``balanced_split``
    over the ELEMENT count scaled back to bytes, so a fold never
    straddles an element boundary. Segments tile ``[0, nbytes)``
    exactly; trailing segments may be empty when P > element count."""
    if nbytes % itemsize:
        raise ValueError(
            f"frame of {nbytes} bytes is not a whole number of "
            f"{itemsize}-byte elements")
    return [(a * itemsize, (b - a) * itemsize)
            for a, b in balanced_split(nbytes // itemsize, nranks)]


def ring_reduce_schedule(rank: int, nranks: int) -> List[dict]:
    """The 2*(P-1)-step ring all-reduce schedule for ``rank``: each step
    names the segment this rank PULLS this round, the peer it pulls
    from, the peer that will pull from it (telemetry/symmetry — the
    pull model never contacts it), and whether the inbound bytes fold
    into the accumulator (reduce-scatter) or land verbatim
    (all-gather). Steps are globally barriered by the driver: step s
    reads only data its peer finished in step s-1."""
    if nranks < 2:
        raise ValueError("ring schedules need at least 2 ranks")
    prev = (rank - 1) % nranks
    nxt = (rank + 1) % nranks
    steps = []
    for s in range(nranks - 1):
        steps.append({"step": s, "phase": "rs",
                      "seg": (rank - s - 1) % nranks,
                      "recv_peer": prev, "send_peer": nxt,
                      "reduce": True})
    for s in range(nranks - 1):
        steps.append({"step": nranks - 1 + s, "phase": "ag",
                      "seg": (rank - s) % nranks,
                      "recv_peer": prev, "send_peer": nxt,
                      "reduce": False})
    return steps


def ring_gather_schedule(rank: int, nranks: int) -> List[dict]:
    """The (P-1)-step all-gather-only ring for ``rank``: rank r starts
    owning segment r and pulls segment (r-s-1) mod P from rank r-1 at
    step s — pure copies, no folds. Per-rank wire traffic is
    (P-1)/P * N bytes."""
    if nranks < 2:
        raise ValueError("ring schedules need at least 2 ranks")
    prev = (rank - 1) % nranks
    nxt = (rank + 1) % nranks
    return [{"step": s, "phase": "ag",
             "seg": (rank - s - 1) % nranks,
             "recv_peer": prev, "send_peer": nxt,
             "reduce": False}
            for s in range(nranks - 1)]


class ShardInfo:
    """Driver-side record of one shard: the ref plus enough placement
    and layout metadata to plan collectives without touching data."""

    __slots__ = ("ref", "rank", "node_id", "data_offset", "nbytes", "shape")

    def __init__(self, ref, rank: int, node_id: bytes,
                 data_offset: int, nbytes: int, shape: Tuple[int, ...]):
        self.ref = ref
        self.rank = rank
        self.node_id = node_id
        self.data_offset = data_offset
        self.nbytes = nbytes
        self.shape = shape

    def __repr__(self):
        nid = self.node_id.hex()[:12] if self.node_id else "?"
        return (f"ShardInfo(rank={self.rank}, node={nid}, "
                f"shape={self.shape}, nbytes={self.nbytes})")


class DistributedArray:
    """Handle to a sharded array: mesh + spec + per-rank ShardInfo.

    The handle itself is cheap driver-side metadata; the bytes live in
    per-node shm stores behind the shard refs. Dropping the handle drops
    the shard refs, and the owner's reference counter releases the whole
    shard set as ONE unit (see ReferenceCounter.add_shard_group) —
    either every shard segment on every node is freed, or none are."""

    __slots__ = ("mesh", "spec", "shape", "dtype_str", "shards")

    def __init__(self, mesh: Mesh, spec: PartitionSpec,
                 shape: Tuple[int, ...], dtype_str: str,
                 shards: List[ShardInfo]):
        self.mesh = mesh
        self.spec = spec
        self.shape = tuple(shape)
        self.dtype_str = dtype_str
        self.shards = shards

    @property
    def nranks(self) -> int:
        return self.mesh.nranks

    def shard_refs(self):
        return [s.ref for s in self.shards]

    def placement(self) -> Dict[int, str]:
        """rank -> node id hex(12): where each shard's bytes live."""
        return {s.rank: s.node_id.hex()[:12] for s in self.shards}

    def __len__(self):
        return len(self.shards)

    def __repr__(self):
        return (f"DistributedArray(shape={self.shape}, "
                f"dtype={self.dtype_str}, mesh={self.mesh!r}, "
                f"spec={self.spec!r}, nshards={len(self.shards)})")
