"""Process-local metric registry with Prometheus text rendering.

Role parity: the reference's C++ OpenCensus stats layer + per-node
metrics agent (reference: src/ray/stats/metric.h:100, metric_defs.h,
python/ray/_private/metrics_agent.py:61 → Prometheus). Re-design: each
process records into an in-memory registry; snapshots ship to the GCS
(piggybacked on heartbeats for raylets, a periodic ReportMetrics RPC
for workers), and the GCS renders the merged view on one Prometheus
text endpoint — no per-node agent daemon, no OpenCensus.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                    50.0, 100.0)


def percentile(sorted_seq, p: float):
    """Nearest-rank percentile of an ascending-sorted sequence (the one
    definition shared by the raylet latency stats and bench.py, so the
    two rows stay comparable).

    Raises ``ValueError`` on an empty sequence: the old negative-index
    arithmetic either raised a bare ``IndexError`` (lists) or silently
    returned the LAST element of whatever backing store a view aliased
    — callers must guard (``raylet._pct_block`` returns ``{"count": 0}``
    for empty reservoirs)."""
    if not sorted_seq:
        raise ValueError("percentile() of an empty sequence")
    return sorted_seq[min(len(sorted_seq) - 1, int(p * len(sorted_seq)))]


def _label_key(labels: Optional[Dict[str, str]]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((labels or {}).items()))


class Metric:
    def __init__(self, name: str, description: str = "",
                 registry: "MetricRegistry | None" = None):
        if not name.replace("_", "a").isalnum():
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.description = description
        self._lock = threading.Lock()
        (registry or global_registry()).register(self)


class Counter(Metric):
    kind = "counter"

    def __init__(self, name, description="", registry=None):
        super().__init__(name, description, registry)
        self._values: Dict[tuple, float] = {}

    def inc(self, value: float = 1.0,
            labels: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("counters only go up")
        k = _label_key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value

    def snapshot(self):
        with self._lock:
            return dict(self._values)


class Gauge(Metric):
    kind = "gauge"

    def __init__(self, name, description="", registry=None):
        super().__init__(name, description, registry)
        self._values: Dict[tuple, float] = {}

    def set(self, value: float,
            labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def snapshot(self):
        with self._lock:
            return dict(self._values)


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name, description="",
                 boundaries: Sequence[float] = _DEFAULT_BUCKETS,
                 registry=None):
        super().__init__(name, description, registry)
        self.boundaries = tuple(boundaries)
        # per label-set: (bucket counts, sum, count)
        self._values: Dict[tuple, list] = {}

    def observe(self, value: float,
                labels: Optional[Dict[str, str]] = None) -> None:
        k = _label_key(labels)
        with self._lock:
            entry = self._values.get(k)
            if entry is None:
                entry = [[0] * (len(self.boundaries) + 1), 0.0, 0]
                self._values[k] = entry
            buckets, _, _ = entry
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    buckets[i] += 1
                    break
            else:
                buckets[-1] += 1
            entry[1] += value
            entry[2] += 1

    def snapshot(self):
        with self._lock:
            return {k: [list(v[0]), v[1], v[2]]
                    for k, v in self._values.items()}


class MetricRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Metric] = {}

    def register(self, metric: Metric) -> None:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None and type(existing) is not type(metric):
                raise ValueError(
                    f"metric {metric.name!r} already registered with a "
                    f"different type")
            self._metrics[metric.name] = metric

    def metrics(self) -> List[Metric]:
        with self._lock:
            return list(self._metrics.values())

    def snapshot(self) -> dict:
        """Wire-format dump for shipping to the GCS."""
        out = {}
        for m in self.metrics():
            out[m.name] = {
                "kind": m.kind, "description": m.description,
                "boundaries": list(getattr(m, "boundaries", ())),
                "values": [[list(k), v] for k, v in m.snapshot().items()],
            }
        return out


_GLOBAL: Optional[MetricRegistry] = None
_GLOBAL_LOCK = threading.Lock()


def global_registry() -> MetricRegistry:
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = MetricRegistry()
        return _GLOBAL


# One shipper per process for the global registry: a CoreWorker's
# metrics-report loop marks itself here; a raylet sharing the process
# (in-process head) then skips shipping on its heartbeat — otherwise
# the SAME counters would reach the GCS under two reporter ids and
# merge_snapshots would double them. Standalone raylet processes
# (worker nodes, headless heads) have no CoreWorker, stay unmarked, and
# ship via heartbeat.
_CORE_REPORTER = False


def mark_core_reporter() -> None:
    global _CORE_REPORTER
    _CORE_REPORTER = True


def core_reporter() -> bool:
    return _CORE_REPORTER


# ---------------------------------------------------------- serve plane

_SERVE: "Optional[Dict[str, Metric]]" = None
_SERVE_LOCK = threading.Lock()


def serve_metrics() -> Dict[str, Metric]:
    """Serving-plane instruments, created lazily in whichever process
    routes serve traffic (HTTP proxy actor, driver-side handles,
    replicas) and shipped by that process's normal metrics loop.

    Gauges carry a ``router`` label alongside ``deployment`` because
    gauge merging is last-writer-wins per label set: two routers of the
    same deployment must not overwrite each other's queue view. The
    cluster rollup (``/api/serve``) sums across routers.
    """
    global _SERVE
    with _SERVE_LOCK:
        if _SERVE is None:
            _SERVE = {
                # requests dispatched to a replica, not yet replied
                "inflight": Gauge(
                    "ray_tpu_serve_inflight",
                    "In-flight requests per deployment router"),
                # requests waiting for a free replica slot
                "queue_depth": Gauge(
                    "ray_tpu_serve_queue_depth",
                    "Requests queued for a free replica slot per "
                    "deployment router"),
                "requests": Counter(
                    "ray_tpu_serve_requests_total",
                    "HTTP requests accepted per deployment"),
                "shed": Counter(
                    "ray_tpu_serve_shed_total",
                    "Requests shed at admission (503 + Retry-After) "
                    "per deployment"),
                "ingress_shm": Counter(
                    "ray_tpu_serve_ingress_shm_total",
                    "Request bodies ingested by shm reference instead "
                    "of the pickle lane"),
                "latency": Histogram(
                    "ray_tpu_serve_request_seconds",
                    "End-to-end proxy request latency (s)",
                    boundaries=(0.005, 0.02, 0.05, 0.1, 0.25, 0.5,
                                1.0, 2.5, 5.0, 10.0, 30.0)),
            }
        return _SERVE


# ------------------------------------------------------------- rendering

def _escape_label(value) -> str:
    # Prometheus exposition escaping: backslash, double-quote, newline.
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(pairs) -> str:
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in pairs)
    return "{" + inner + "}"


def render_prometheus(merged: Dict[str, dict]) -> str:
    """merged: {metric_name: {kind, description, boundaries,
    values: [[labelpairs, value], ...]}} → Prometheus text format."""
    lines: List[str] = []
    for name in sorted(merged):
        m = merged[name]
        kind = m.get("kind", "gauge")
        lines.append(f"# HELP {name} {m.get('description', '')}")
        lines.append(f"# TYPE {name} {kind}")
        if kind == "histogram":
            bounds = m.get("boundaries", [])
            for pairs, (buckets, total, count) in m["values"]:
                pairs = [tuple(p) for p in pairs]
                acc = 0
                for b, c in zip(list(bounds) + ["+Inf"], buckets):
                    acc += c
                    lp = _fmt_labels(pairs + [("le", b)])
                    lines.append(f"{name}_bucket{lp} {acc}")
                lines.append(
                    f"{name}_sum{_fmt_labels(pairs)} {total}")
                lines.append(
                    f"{name}_count{_fmt_labels(pairs)} {count}")
        else:
            for pairs, value in m["values"]:
                pairs = [tuple(p) for p in pairs]
                lines.append(f"{name}{_fmt_labels(pairs)} {value}")
    return "\n".join(lines) + "\n"


def merge_snapshots(snapshots: List[dict]) -> Dict[str, dict]:
    """Merge per-process snapshots (counters/histograms add; gauges
    last-writer-wins per label set)."""
    merged: Dict[str, dict] = {}
    for snap in snapshots:
        for name, m in snap.items():
            dst = merged.setdefault(name, {
                "kind": m["kind"], "description": m["description"],
                "boundaries": m.get("boundaries", []), "_vals": {}})
            vals = dst["_vals"]
            for pairs, value in m["values"]:
                k = tuple(tuple(p) for p in pairs)
                if k not in vals:
                    vals[k] = value
                elif m["kind"] == "counter":
                    vals[k] = vals[k] + value
                elif m["kind"] == "histogram":
                    old_b, old_s, old_c = vals[k]
                    new_b, new_s, new_c = value
                    vals[k] = [[a + b for a, b in zip(old_b, new_b)],
                               old_s + new_s, old_c + new_c]
                else:  # gauge: last writer
                    vals[k] = value
    for m in merged.values():
        m["values"] = [[list(k), v] for k, v in m.pop("_vals").items()]
    return merged
