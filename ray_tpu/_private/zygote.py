"""Zygote worker factory: fork-fast worker and actor startup.

Every cold worker spawn pays a full CPython boot, the whole
``ray_tpu._private`` import graph and a ``native.load_fastpath()``
warm-up — seconds per process, which dominates actor creation and
post-kill recovery (the scalability bench measured ~2.5 actors/s,
almost all interpreter startup). The zygote is a forkserver-style
template process, one per raylet, that pays those fixed costs ONCE:

* it pre-imports the worker module graph (``core_worker``,
  ``task_executor``, ``rpc``, ``serialization`` + a configurable
  preload list) and pre-builds the native fastpath;
* then blocks SINGLE-THREADED — no event loop, no threads, so there is
  never a lock or a loop to corrupt across ``fork()`` — on a unix
  socketpair waiting for spawn requests;
* per request it ``fork()``s; the child applies env overrides (so
  ``JAX_PLATFORMS`` / ``RAY_TPU_FAULTPOINTS`` arming still work
  per-spawn), redirects stdout/stderr to its own log file, starts a
  fresh session/process group (the raylet's ``killpg`` teardown and
  chaos kill schedules keep working), re-keys ``random`` and the id
  RNG, and enters the same :func:`worker_main.boot_worker` path a cold
  start uses;
* the zygote reaps its forked children (``waitpid`` WNOHANG between
  requests) and reports child pids back to the raylet.

Fork-safety rules (why this is sound): the template never creates an
event loop, never starts a thread, and never initializes an
accelerator backend — the worker import graph is jax-free by
construction, and raylets whose workers run a TPU platform never use
the zygote at all (an initialized accelerator client must never be
forked). Cold ``Popen`` remains the fallback everywhere: zygote dead,
non-Linux, or ``worker_zygote_enabled=False``.

Wire protocol (one request, one reply, strictly in order): 4-byte
big-endian length + JSON. The zygote sends a ``{"ready": true}``
banner after preloading; requests sent earlier simply queue in the
socket buffer, so the raylet can fire prestart spawns at boot without
waiting for the template.
"""

from __future__ import annotations

import argparse
import asyncio
import importlib
import json
import logging
import os
import random
import select
import signal
import socket
import struct
import subprocess
import sys
import time
import traceback
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

# The import graph worker_main pays on a cold start. ``ray_tpu`` pulls
# the driver-surface modules (worker, actor, remote_function) the boot
# path touches; the rest are the private hot-path modules. Deliberately
# jax-free: importing jax starts backend threads, which would break the
# single-threaded fork-safety contract above.
DEFAULT_PRELOAD = (
    "ray_tpu",
    "ray_tpu._private.rpc",
    "ray_tpu._private.serialization",
    "ray_tpu._private.core_worker",
    "ray_tpu._private.task_executor",
    "ray_tpu._private.worker_main",
)


class ZygoteError(RuntimeError):
    """The zygote is gone or refused a spawn (caller falls back to Popen)."""


# ---------------------------------------------------------------------------
# framing (blocking side — the zygote process)
# ---------------------------------------------------------------------------


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None  # EOF: the raylet went away
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> Optional[dict]:
    head = _recv_exact(sock, 4)
    if head is None:
        return None
    body = _recv_exact(sock, struct.unpack("!I", head)[0])
    if body is None:
        return None
    return json.loads(body)


def _send_frame(sock: socket.socket, msg: dict) -> None:
    payload = json.dumps(msg).encode()
    sock.sendall(struct.pack("!I", len(payload)) + payload)


# ---------------------------------------------------------------------------
# zygote process (template side)
# ---------------------------------------------------------------------------


def _reap_children() -> None:
    """waitpid(WNOHANG) drain: forked workers the raylet SIGKILLed (or
    that exited on their own) are children of the ZYGOTE, not the
    raylet — without this they would sit as zombies for the template's
    lifetime."""
    while True:
        try:
            pid, _status = os.waitpid(-1, os.WNOHANG)
        except ChildProcessError:
            return  # no children at all
        if pid == 0:
            return  # children exist but none exited yet


def _child_main(sock: socket.socket, req: Dict[str, Any]) -> None:
    """The forked worker: tear off the template's identity, then enter
    the shared boot path. NEVER returns — ``os._exit`` always, so a
    failure can't fall back into the zygote's serve loop."""
    status = 70  # EX_SOFTWARE unless boot exits with its own code
    try:
        # Fresh session + process group: the raylet's killpg-based
        # teardown and the chaos kill schedules address this child
        # alone, exactly like a Popen(start_new_session=True) worker.
        os.setsid()
        sock.close()
        for k, v in (req.get("env") or {}).items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = str(v)
        # The child owns its log file; stdout/stderr swing over before
        # anything can print, same contract as the Popen stdout= dup.
        log_fd = os.open(req["log_path"],
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        os.dup2(log_fd, 1)
        os.dup2(log_fd, 2)
        os.close(log_fd)
        # fork() copies the template's RNG state byte-for-byte: re-key
        # every stream a worker draws from (jitter, sampling, and the
        # id-suffix RNG — shared state would collide object ids).
        random.seed(int.from_bytes(os.urandom(16), "little"))
        from ray_tpu._private import ids
        ids.reseed()

        import types

        from ray_tpu._private.worker_main import boot_worker

        argv = req["argv"]
        boot_worker(types.SimpleNamespace(
            raylet_address=argv["raylet_address"],
            gcs_address=argv["gcs_address"],
            node_id=argv["node_id"],
            worker_id=argv["worker_id"],
            session_dir=argv["session_dir"],
            log_level=argv.get("log_level", "INFO")))
        status = 0  # boot_worker sys.exit()s; not normally reached
    except SystemExit as e:
        status = e.code if isinstance(e.code, int) else 0
    except BaseException:  # noqa: BLE001 — last-resort child report: the traceback goes to the worker log, then the process dies
        traceback.print_exc()
        try:
            sys.stderr.flush()
        except OSError:
            pass
    finally:
        os._exit(status)


def _spawn_child(sock: socket.socket, req: Dict[str, Any]) -> int:
    pid = os.fork()
    if pid == 0:
        _child_main(sock, req)  # never returns
        os._exit(70)  # unreachable belt-and-braces
    return pid


def serve(sock: socket.socket, preload: List[str]) -> None:
    t0 = time.monotonic()
    errors: List[str] = []
    for name in preload:
        try:
            importlib.import_module(name)
        except Exception as e:  # noqa: BLE001 — a bad preload entry must not kill the factory; reported in the ready banner
            logger.warning("zygote preload %s failed: %r", name, e)
            errors.append(f"{name}: {e!r}")
    from ray_tpu._private import native

    native.load_fastpath()  # children inherit the warm copy tier
    _send_frame(sock, {"ready": True, "pid": os.getpid(),
                       "preload_s": round(time.monotonic() - t0, 3),
                       "preload_errors": errors})
    logger.info("zygote ready in %.2fs (pid %d, %d modules preloaded)",
                time.monotonic() - t0, os.getpid(), len(preload))
    while True:
        _reap_children()
        # Still single-threaded-blocking — the timeout only bounds how
        # long a dead child can sit unreaped while no requests arrive.
        readable, _, _ = select.select([sock], [], [], 0.5)
        if not readable:
            continue
        req = _recv_frame(sock)
        if req is None:
            break  # EOF: the raylet is gone — exit with it
        op = req.get("op")
        try:
            if op == "spawn":
                pid = _spawn_child(sock, req)
                _send_frame(sock, {"ok": True, "pid": pid})
            elif op == "ping":
                _send_frame(sock, {"ok": True, "pid": os.getpid(),
                                   "preload_errors": errors})
            elif op == "exit":
                break
            else:
                _send_frame(sock, {"ok": False,
                                   "error": f"unknown op {op!r}"})
        except (OSError, ConnectionError) as e:
            # fork failure (EAGAIN) or the raylet vanished mid-reply:
            # report if the pipe still works, otherwise exit.
            logger.error("zygote request %r failed: %r", op, e)
            try:
                _send_frame(sock, {"ok": False, "error": repr(e)})
            except (OSError, ConnectionError):
                break
    _reap_children()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--sock-fd", type=int, required=True,
                        help="inherited socketpair fd the raylet holds "
                             "the other end of")
    parser.add_argument("--preload", default="",
                        help="comma list of extra modules to pre-import "
                             "on top of the default worker graph")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=os.environ.get("RAY_TPU_ZYGOTE_LOG_LEVEL", "INFO"),
        format="[zygote] %(levelname)s %(name)s: %(message)s")
    # A terminated raylet closes the socketpair and EOF ends the serve
    # loop; SIGTERM is only the belt-and-braces external teardown.
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    preload = list(DEFAULT_PRELOAD)
    for name in args.preload.split(","):
        name = name.strip()
        if name and name not in preload:
            preload.append(name)
    sock = socket.socket(fileno=args.sock_fd)
    sock.setblocking(True)
    try:
        serve(sock, preload)
    finally:
        sock.close()
    return 0


# ---------------------------------------------------------------------------
# raylet side
# ---------------------------------------------------------------------------


class ZygoteProc:
    """Popen-shaped handle for a zygote-FORKED worker.

    The raylet is not the child's parent (the zygote is), so
    ``waitpid`` is unavailable here: liveness comes from
    ``/proc/<pid>/stat`` and a zombie (state ``Z``, awaiting the
    zygote's reap pass) already counts as exited. ``kill`` matches the
    Popen surface the raylet's teardown uses."""

    def __init__(self, pid: int):
        self.pid = pid
        self.returncode: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        try:
            with open(f"/proc/{self.pid}/stat", "rb") as f:
                state = f.read().rpartition(b") ")[2][:1]
        except OSError:
            state = b""
        if state in (b"", b"Z", b"X"):
            # gone, zombie, or dead: the exit status lives with the
            # zygote — report the SIGKILL shape teardown expects.
            self.returncode = -signal.SIGKILL
        return self.returncode

    def kill(self) -> None:
        try:
            os.kill(self.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


class ZygoteClient:
    """Raylet-side handle on the template process: launch, spawn
    requests over the socketpair (asyncio streams, serialized — the
    zygote answers strictly in order), and teardown."""

    def __init__(self, proc: subprocess.Popen, sock: socket.socket,
                 log_path: str):
        self.proc = proc
        self.log_path = log_path
        self._sock: Optional[socket.socket] = sock
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()
        self._ready_banner: Optional[dict] = None
        # Set when an exchange was interrupted after its request was
        # written: the reply is (or may be) still in flight, so any
        # later read would adopt the WRONG frame — the stream is
        # strictly request/reply ordered. A broken client only errors;
        # the raylet tears it down and falls back to cold Popen.
        self._broken = False

    @classmethod
    def launch(cls, *, session_dir: str, env: Dict[str, str],
               preload: str = "", tag: str = "") -> "ZygoteClient":
        """Popen the template. Cheap (~fork+exec): the expensive preload
        happens inside the zygote while the raylet keeps serving;
        spawn requests sent meanwhile queue in the socket buffer."""
        log_dir = os.path.join(session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir, f"zygote-{tag or os.getpid()}.log")
        parent, child = socket.socketpair()
        cmd = [sys.executable, "-m", "ray_tpu._private.zygote",
               "--sock-fd", str(child.fileno())]
        if preload:
            cmd += ["--preload", preload]
        out = open(log_path, "ab")
        try:
            proc = subprocess.Popen(
                cmd, stdout=out, stderr=subprocess.STDOUT, env=env,
                pass_fds=(child.fileno(),), start_new_session=True)
        finally:
            out.close()  # Popen dup'd it — the parent copy must not leak
            child.close()
        return cls(proc, parent, log_path)

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    async def _ensure_stream(self) -> None:
        if self._reader is None:
            # raylint: disable=await-atomicity — only reached under _call's self._lock; one caller at a time
            self._reader, self._writer = await asyncio.open_connection(
                sock=self._sock)
            # the transport owns the fd now; drop our direct handle so
            # nothing can double-close it
            self._sock = None

    async def _read_frame(self) -> dict:
        try:
            head = await self._reader.readexactly(4)
            body = await self._reader.readexactly(
                struct.unpack("!I", head)[0])
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
            raise ZygoteError(f"zygote connection lost: {e!r}") from None
        return json.loads(body)

    async def _call(self, msg: dict) -> dict:
        async with self._lock:
            if self._broken:
                raise ZygoteError("zygote stream out of sync "
                                  "(a prior exchange was interrupted)")
            await self._ensure_stream()
            try:
                if self._ready_banner is None:
                    banner = await self._read_frame()
                    if not banner.get("ready"):
                        raise ZygoteError(
                            f"zygote sent {banner!r} before its ready "
                            f"banner")
                    if banner.get("preload_errors"):
                        logger.warning("zygote preload errors: %s",
                                       banner["preload_errors"])
                    self._ready_banner = banner
                payload = json.dumps(msg).encode()
                try:
                    self._writer.write(
                        struct.pack("!I", len(payload)) + payload)
                    await self._writer.drain()
                except (ConnectionError, OSError) as e:
                    raise ZygoteError(
                        f"zygote write failed: {e!r}") from None
                return await self._read_frame()
            except (asyncio.CancelledError, ZygoteError):
                # cancelled (caller timeout) or failed mid-exchange: a
                # reply may still land later — no caller may ever read
                # this stream again or it would mis-pair frames
                self._broken = True
                raise

    async def spawn(self, *, worker_id: str, log_path: str,
                    env_overrides: Dict[str, Optional[str]],
                    argv: Dict[str, str]) -> int:
        """Fork one worker; returns its pid (the child is already
        booting toward RegisterWorker when this resolves)."""
        reply = await self._call({"op": "spawn", "worker_id": worker_id,
                                  "log_path": log_path,
                                  "env": env_overrides, "argv": argv})
        if not reply.get("ok"):
            raise ZygoteError(reply.get("error", "spawn refused"))
        return int(reply["pid"])

    async def ping(self) -> dict:
        return await self._call({"op": "ping"})

    async def close(self) -> None:
        """Graceful teardown: EOF ends the serve loop, then a bounded
        non-blocking reap of the template (its own forked children are
        either already dead or reparented to init when it exits)."""
        self._close_pipe()
        for _ in range(100):
            if self.proc.poll() is not None:
                return
            await asyncio.sleep(0.02)
        try:
            self.proc.kill()
        except OSError:
            pass
        for _ in range(50):
            if self.proc.poll() is not None:
                return
            await asyncio.sleep(0.02)
        logger.warning("zygote pid %s did not exit at close", self.proc.pid)

    def kill(self) -> None:
        """Abrupt sync teardown (crash-style harnesses): SIGKILL the
        template and drop the pipe; poll() reaps the zombie."""
        self._close_pipe()
        try:
            self.proc.kill()
        except OSError:
            pass
        self.proc.poll()

    def _close_pipe(self) -> None:
        try:
            if self._writer is not None:
                self._writer.close()
            elif self._sock is not None:
                self._sock.close()
                self._sock = None
        except OSError:
            pass


if __name__ == "__main__":
    sys.exit(main())
