"""Binary IDs with embedded lineage.

Mirrors the reference ID scheme (reference: src/ray/common/id.h) without
copying it: fixed-width byte IDs where an ObjectID embeds the TaskID that
creates it plus a return/put index, and a TaskID embeds the ActorID/JobID it
belongs to.  This embedding is what makes lineage reconstruction and
ownership bookkeeping cheap: given an ObjectID you can always recover the
creating task and the owning job without a directory lookup.

Sizes: JobID 4B, ActorID 16B (job + 12 unique), TaskID 24B (actor + 8
unique), ObjectID 28B (task + 4B little-endian index), NodeID/WorkerID 28B
random, PlacementGroupID 16B.
"""

from __future__ import annotations

import os
import random
import threading

# ID suffixes only need uniqueness, not cryptographic strength; a urandom-
# seeded Mersenne twister is ~50x cheaper per draw than os.urandom on this
# path (each process seeds independently — workers are fresh interpreters).
_rng = random.Random(int.from_bytes(os.urandom(16), "little"))


def _random_bytes(n: int) -> bytes:
    return _rng.getrandbits(n * 8).to_bytes(n, "little")


def reseed() -> None:
    """Re-key the module RNG from fresh entropy.

    A zygote-forked worker (zygote.py) inherits the template process's
    Mersenne state byte-for-byte — without this every fork would draw
    the SAME object/task id suffixes and collide in the owner tables.
    Called from the forked child before any id is drawn."""
    global _rng
    _rng = random.Random(int.from_bytes(os.urandom(16), "little"))

JOB_ID_SIZE = 4
ACTOR_ID_SIZE = 16
TASK_ID_SIZE = 24
OBJECT_ID_SIZE = 28
UNIQUE_ID_SIZE = 28
PLACEMENT_GROUP_ID_SIZE = 16

_MAX_INDEX = 2**32 - 1


class BaseID:
    SIZE = 0
    __slots__ = ("_bytes", "_hash")

    def __init__(self, id_bytes: bytes):
        if len(id_bytes) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(id_bytes)}"
            )
        # skip the defensive copy when already immutable (hot path)
        self._bytes = id_bytes if type(id_bytes) is bytes \
            else bytes(id_bytes)
        self._hash = None

    @classmethod
    def from_random(cls):
        return cls(_random_bytes(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\xff" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\xff" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        # Plain bytes hash (no type-name tuple): ids of different types
        # never share a table, and __eq__ still type-checks, so the only
        # cost of a cross-type hash collision is one extra __eq__ probe.
        if self._hash is None:
            self._hash = hash(self._bytes)
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = JOB_ID_SIZE

    @classmethod
    def from_int(cls, value: int):
        return cls(value.to_bytes(JOB_ID_SIZE, "little"))

    def int_value(self) -> int:
        return int.from_bytes(self._bytes, "little")


class ActorID(BaseID):
    SIZE = ACTOR_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID):
        return cls(job_id.binary() + _random_bytes(ACTOR_ID_SIZE - JOB_ID_SIZE))

    def job_id(self) -> JobID:
        return JobID(self._bytes[:JOB_ID_SIZE])


class TaskID(BaseID):
    SIZE = TASK_ID_SIZE

    @classmethod
    def of(cls, actor_id: ActorID):
        """A task within an actor's (or the job's driver "actor") lineage."""
        return cls(actor_id.binary() + _random_bytes(TASK_ID_SIZE - ACTOR_ID_SIZE))

    @classmethod
    def for_driver(cls, job_id: JobID):
        return cls.of(ActorID(job_id.binary() + b"\x00" * (ACTOR_ID_SIZE - JOB_ID_SIZE)))

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[:ACTOR_ID_SIZE])

    def job_id(self) -> JobID:
        return JobID(self._bytes[:JOB_ID_SIZE])

    def object_id(self, index: int) -> "ObjectID":
        if not 0 <= index <= _MAX_INDEX:
            raise ValueError(f"object index out of range: {index}")
        return ObjectID(self._bytes + index.to_bytes(4, "little"))


# ---- bytes-level helpers for the submit hot path (single source of
# truth for the wire layout; core_worker avoids ID-object churn) ----

# Return-object index suffixes (1-based little-endian), precomputed.
OID_SUFFIX = tuple((i + 1).to_bytes(4, "little") for i in range(64))


def id_key(object_id) -> bytes:
    """Raw-bytes key of an id: accepts an ObjectID (or any BaseID) or the
    bytes themselves.  The owner-side tables (memory store, reference
    counter) key by raw bytes so dict probes hash in C."""
    return object_id if type(object_id) is bytes else object_id._bytes


def make_task_id_bytes(lineage_prefix16: bytes) -> bytes:
    """task_id = 16-byte actor/lineage prefix + 8 random bytes."""
    return lineage_prefix16 + _random_bytes(TASK_ID_SIZE - ACTOR_ID_SIZE)


def return_object_id_bytes(task_id: bytes, index1: int) -> bytes:
    """ObjectID bytes for 1-based return ``index1`` of ``task_id``."""
    if index1 <= len(OID_SUFFIX):
        return task_id + OID_SUFFIX[index1 - 1]
    return task_id + index1.to_bytes(4, "little")


class ObjectID(BaseID):
    SIZE = OBJECT_ID_SIZE

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:TASK_ID_SIZE])

    def job_id(self) -> JobID:
        return JobID(self._bytes[:JOB_ID_SIZE])

    def index(self) -> int:
        return int.from_bytes(self._bytes[TASK_ID_SIZE:], "little")


class NodeID(BaseID):
    SIZE = UNIQUE_ID_SIZE


class WorkerID(BaseID):
    SIZE = UNIQUE_ID_SIZE


class PlacementGroupID(BaseID):
    SIZE = PLACEMENT_GROUP_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID):
        return cls(job_id.binary() + _random_bytes(PLACEMENT_GROUP_ID_SIZE - JOB_ID_SIZE))


class PutIndexAllocator:
    """Allocates monotonically increasing put/return indices for one task.

    Return objects use indices [1, num_returns]; ``put`` objects continue
    the sequence after them, so every ObjectID created by a task is unique
    and lineage-addressable (reference: ObjectID::FromIndex semantics in
    src/ray/common/id.h).
    """

    def __init__(self, task_id: TaskID, first_free_index: int):
        self._task_id = task_id
        self._lock = threading.Lock()
        self._next = first_free_index

    def next_object_id(self) -> ObjectID:
        with self._lock:
            idx = self._next
            self._next += 1
        return self._task_id.object_id(idx)
