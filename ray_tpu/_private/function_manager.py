"""Function/actor-class distribution via the GCS KV store.

Role parity: reference FunctionActorManager + ImportThread
(python/ray/_private/function_manager.py, _private/import_thread.py): the
driver pickles the function/class once, exports it to the GCS KV under a
content-hash key; workers fetch-and-cache on first execution of a task
naming that key (pull-based instead of the reference's push/import-thread —
no work for functions a worker never runs).
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Dict, Optional, Tuple

import cloudpickle

FN_KV_PREFIX = b"fn:"


class FunctionManager:
    def __init__(self, kv_put, kv_get):
        """kv_put(key: bytes, value: bytes) / kv_get(key: bytes) -> bytes are
        synchronous callables bound to the GCS client."""
        self._kv_put = kv_put
        self._kv_get = kv_get
        self._lock = threading.Lock()
        self._exported: set[str] = set()
        self._cache: Dict[str, Any] = {}
        self._pickled_cache: Dict[str, bytes] = {}

    def export(self, fn: Any) -> str:
        """Pickle and export; returns the content-hash key."""
        pickled = cloudpickle.dumps(fn)
        key = hashlib.sha1(pickled).hexdigest()
        self.export_prepickled(key, pickled, fn)
        return key

    def prepare(self, fn: Any):
        """Pickle once; returns (key, pickled) for caching by the caller."""
        pickled = cloudpickle.dumps(fn)
        return hashlib.sha1(pickled).hexdigest(), pickled

    def export_prepickled(self, key: str, pickled: bytes, fn: Any = None) -> None:
        """Idempotent per-cluster export. The ``_exported`` set lives on this
        core worker, so a decorated function reused across clusters
        re-exports to each new GCS."""
        # Lock-free fast path: set membership is atomic under the GIL and
        # keys are only ever added, so a stale miss just re-checks below.
        if key in self._exported:
            return
        self._kv_put(FN_KV_PREFIX + key.encode(), pickled)
        with self._lock:
            self._exported.add(key)
            if fn is not None:
                self._cache[key] = fn
            self._pickled_cache[key] = pickled

    def fetch(self, key: str) -> Any:
        # Lock-free fast path: dict reads are atomic under the GIL and
        # entries are only ever added (per-task hot path).
        fn = self._cache.get(key)
        if fn is not None:
            return fn
        pickled = self._kv_get(FN_KV_PREFIX + key.encode())
        if pickled is None:
            raise RuntimeError(f"function {key} not found in GCS KV")
        fn = cloudpickle.loads(pickled)
        with self._lock:
            self._cache[key] = fn
        return fn
