"""Worker process entrypoint.

Role parity: reference python/ray/workers/default_worker.py — boots a core
worker in worker mode, registers with the local raylet, then serves task
pushes until told to exit or the raylet connection drops.

Two spawn paths share :func:`boot_worker`:

* cold start — ``python -m ray_tpu._private.worker_main`` (this module's
  ``main``): a fresh interpreter pays the full import graph + fastpath
  warm-up before booting;
* zygote fork — zygote.py forks its pre-imported template process and
  the child calls :func:`boot_worker` directly (imports and the native
  fastpath are already warm, so spawn-to-registered is milliseconds).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import sys


def boot_worker(args) -> None:
    """Boot a worker in THIS process and serve until the raylet goes away.

    ``args`` carries ``raylet_address``, ``gcs_address``, ``node_id``,
    ``worker_id``, ``session_dir`` and ``log_level`` (an argparse
    namespace from ``main`` or a SimpleNamespace from a zygote fork).
    Never returns: exits the process when the serve loop ends.
    """
    # force=True: a zygote-forked child inherits the template's root
    # logger handlers; the per-worker format must still win.
    logging.basicConfig(
        level=getattr(args, "log_level", "INFO"), force=True,
        format=f"[worker {args.worker_id[:8]}] %(levelname)s %(name)s: %(message)s")

    # Debug aids: periodic all-thread stack dumps to the worker log,
    # and SIGUSR1 → immediate stack dump (so a wedged worker can be
    # inspected from outside without killing it).
    import faulthandler
    import signal

    faulthandler.register(signal.SIGUSR1, all_threads=True)
    # SIGTERM = graceful exit (atexit hooks — profile dumps — run);
    # the raylet's hard teardown still uses SIGKILL.
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    dump_s = float(os.environ.get("RAY_TPU_WORKER_STACK_DUMP_S", "0"))
    if dump_s > 0:
        faulthandler.dump_traceback_later(dump_s, repeat=True)

    from ray_tpu._private import faultpoints, native
    from ray_tpu._private.config import RayTpuConfig, set_config
    from ray_tpu._private.core_worker import CoreWorker
    from ray_tpu._private.task_executor import TaskExecutor
    import ray_tpu.actor  # registers the actor-handle factory hook
    import ray_tpu.worker as worker_mod

    # Warm the native copy tier before the loop exists: copy_into never
    # builds (a cold-cache compile on the loop was a raylint transitive
    # async-blocking finding), so the one place that may pay the
    # compiler is process boot. A zygote fork already has it warm —
    # load_fastpath is a cached no-op then.
    native.load_fastpath()
    # Deterministic fault schedules (e.g. "die at the 3rd task") are
    # armed from the spawning test's environment — a seeded plan, not a
    # SIGKILL race. For zygote forks the raylet forwards the CURRENT
    # env value per spawn, so arming stays per-spawn, not per-template.
    faultpoints.arm_from_env()

    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)

    async def boot():
        config = RayTpuConfig.create()
        core = CoreWorker(
            mode="worker", config=config,
            gcs_address=args.gcs_address,
            raylet_address=args.raylet_address,
            session_dir=args.session_dir,
            worker_id=bytes.fromhex(args.worker_id),
            node_id=bytes.fromhex(args.node_id),
            loop=loop)
        executor = TaskExecutor(core)
        core.task_executor = executor
        await core._connect_async()
        ray_tpu.actor.register_with_core_worker(core)
        worker_mod.global_worker.core = core
        worker_mod.global_worker.mode = "worker"
        set_config(config)
        reply, _ = await core.raylet_conn.call("RegisterWorker", {
            "worker_id": core.worker_id,
            "address": core.address,
            "pid": os.getpid(),
        })
        core.node_id = reply["node_id"]
        # Adopt the cluster's config (raylet forwards the canonical one).
        set_config(RayTpuConfig.from_json(reply["config"]))
        core.config = RayTpuConfig.from_json(reply["config"])
        # Exit when the raylet goes away.
        core.raylet_conn.on_disconnect.append(lambda c: loop.stop())
        return core

    core = loop.run_until_complete(boot())
    worker_mod._tune_gc()  # same GC policy as drivers (hot exec path)
    # Debug aid: RAY_TPU_WORKER_PROFILE=/dir — the exec thread dumps
    # cProfile stats at exit (task_executor._serial_exec_loop). On
    # 3.12 cProfile is process-wide, so only that one thread profiles.
    try:
        loop.run_forever()
    finally:
        try:
            core.shutdown()
        # raylint: disable=exception-hygiene — worker exit path: nothing to report to, stderr goes to the log monitor
        except Exception:
            pass
        sys.exit(0)


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--raylet-address", required=True)
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--worker-id", required=True)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--log-level", default="INFO")
    boot_worker(parser.parse_args(argv))


if __name__ == "__main__":
    main()
