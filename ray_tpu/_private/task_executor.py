"""Worker-side task execution.

Role parity: reference task execution path (_raylet.pyx execute_task +
CoreWorkerDirectTaskReceiver / ActorSchedulingQueue in
src/ray/core_worker/transport/direct_actor_transport.h): normal tasks run
serially off a FIFO; actor tasks are reordered by client sequence number and
executed in order, with max_concurrency threads for threaded actors and an
asyncio path for async actors (the analog of the reference's boost::fiber
actors). Return values small enough go back inline in the RPC reply into
the owner's memory store; large ones are sealed into the node's shm store
and the reply carries only the location.
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import os
import queue as queue_mod
import threading
import traceback
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu import exceptions as exc
from ray_tpu._private import faultpoints, protocol, rpc
from ray_tpu._private import runtime_env as runtime_env_mod
from ray_tpu._private.core_worker import CoreWorker
from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.memory_store import IN_PLASMA
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.serialization import (META_RAW, SerializedObject,
                                            format_task_error)
from ray_tpu._private.ids import return_object_id_bytes
from ray_tpu._private.task_events import FAILED, FINISHED, RUNNING
from ray_tpu._private.task_spec import (ARG_REF, ARG_VALUE, REPLY_ERROR,
                                        REPLY_OK, REPLY_STOLEN, TaskSpec)

logger = logging.getLogger(__name__)

# Returns whose serialized size fits here ride inside the msgpack reply
# header (decoded by the owner's single C unpackb) instead of as
# out-of-band frames; larger values keep the frame path, which writes
# zero-copy from the worker (writev) and costs one copy on receive.
INLINE_RETURN_MAX = 4096

_task_ctx = threading.local()


def current_task_id() -> bytes:
    return getattr(_task_ctx, "task_id", b"")


import contextlib as _contextlib

_NULL_SPAN = _contextlib.nullcontext()  # shared: stateless enter/exit


def _exec_span(spec: TaskSpec):
    """Consumer span around task execution when the submission carried
    span context (reference: tracing_helper.py server-side span); a
    shared no-op context otherwise (hot path: one attribute check)."""
    if not spec.trace_ctx:
        return _NULL_SPAN
    from ray_tpu.util import tracing

    return tracing.task_execution_span(
        spec.name, TaskID(spec.task_id).hex(), spec.trace_ctx)


class _BatchState:
    """Reply aggregation for one pushed task batch, resolved into the
    single batch reply on the IO loop when the last slot lands.
    Replaces one asyncio.Future + done-callback PER TASK (profiled at
    several us/task). LOCK-FREE: slot claiming is dict.setdefault
    (GIL-atomic, first writer wins — the steal-vs-exec race), the
    countdown is deque-append + len (both atomic); a photo-finish can
    make both completers observe fullness, which _resolve absorbs via
    fut.done(). Slots complete from the exec thread (run/error) or the
    IO loop (stolen/cancelled)."""

    __slots__ = ("fut", "slots", "n", "_done_counter", "loop")

    def __init__(self, loop, n: int):
        self.fut = loop.create_future()
        self.slots: Dict[int, tuple] = {}
        self.n = n
        self._done_counter: deque = deque()
        self.loop = loop

    @property
    def remaining(self) -> int:
        return self.n - len(self._done_counter)

    def complete(self, i: int, reply: tuple) -> None:
        if self.slots.setdefault(i, reply) is not reply:
            return  # raced (e.g. steal vs. exec): first wins
        self._done_counter.append(None)
        if len(self._done_counter) == self.n:
            self.loop.call_soon_threadsafe(self._resolve)

    def _resolve(self) -> None:
        if self.fut.done():
            return
        rheaders = []
        rframes: List[bytes] = []
        for i in range(self.n):
            rh, rfr = self.slots[i]
            rheaders.append([rh, len(rframes), len(rfr)])
            rframes.extend(rfr)
        self.fut.set_result(({"replies": rheaders}, rframes))


class StealableQueue:
    """SimpleQueue-compatible FIFO whose tail can be relinquished.

    Backs the work-stealing protocol (reference: StealTasks in
    direct_task_transport.h:57 — queued-but-unstarted tasks move off a
    busy worker): the execution thread pops from the head one task at a
    time, so everything still queued here is fair game for a thief.

    LOCK-FREE fast path: deque.popleft/append/pop are GIL-atomic, so
    the per-item cost is one C call; the condition variable only comes
    out when the consumer finds the queue empty. The missed-notify
    window is closed because the producer's ``with self._cv`` cannot be
    entered until the consumer's wait() has released the lock — see
    tests/test_concurrency.py for the adversarial coverage."""

    def __init__(self):
        self._dq = deque()
        self._cv = threading.Condition()
        self._waiting = False

    def put(self, item):
        self._dq.append(item)
        if self._waiting:
            with self._cv:
                self._cv.notify()

    def get(self):
        try:
            return self._dq.popleft()  # hot path: no lock
        except IndexError:
            pass
        with self._cv:
            self._waiting = True
            try:
                while True:
                    try:
                        return self._dq.popleft()
                    except IndexError:
                        self._cv.wait()
            finally:
                self._waiting = False

    def get_nowait(self):
        try:
            return self._dq.popleft()
        except IndexError:
            raise queue_mod.Empty from None

    def empty(self) -> bool:
        return not self._dq

    def steal(self, max_n: int):
        """Pop up to max_n items from the TAIL (newest first), returned
        in original submission order. Tail pops race benignly with the
        consumer's head pops: on a one-item deque exactly one side wins
        (the loser's IndexError is absorbed)."""
        out = []
        while len(out) < max_n:
            try:
                out.append(self._dq.pop())
            except IndexError:
                break
        out.reverse()
        return out


class TaskExecutor:
    def __init__(self, core: CoreWorker):
        self.core = core
        # cached for the RUNNING-event attrs (hex per task would sit on
        # the exec hot path)
        self._wid12 = core.worker_id.hex()[:12]
        # Normal tasks execute serially, like a reference worker: one
        # dedicated execution thread fed by a queue. Batching the
        # reply delivery costs one loop wakeup per BURST of tasks
        # instead of one thread-pool hop per task.
        self._task_pool = ThreadPoolExecutor(max_workers=1,
                                             thread_name_prefix="rtpu-exec")
        self._exec_queue: StealableQueue = StealableQueue()
        self._exec_thread = threading.Thread(
            target=self._exec_loop, name="rtpu-task-exec", daemon=True)
        self._exec_thread.start()
        self._actor_instance: Any = None
        self._actor_id: bytes = b""
        # Incarnation this worker serves, stamped by CreateActor. A
        # PushActorTasks batch carrying a DIFFERENT incarnation is a
        # split-brain signal (the pusher resolved a restart this worker
        # doesn't represent): sever the connection so the pusher's
        # conn-lost path requeues inflight and re-resolves via the GCS.
        self._actor_incarnation = -1
        self._actor_is_asyncio = False
        self._actor_sema: Optional[asyncio.Semaphore] = None
        self._actor_pool: Optional[ThreadPoolExecutor] = None
        # Async actors run user coroutines on a DEDICATED loop thread,
        # never on the core IO loop (reference: async actors get their
        # own asyncio loop, _raylet.pyx:501-520 / fiber.h) — so actor
        # code may call the sync API (create actors, kill, get) without
        # deadlocking the RPC plane.
        self._actor_user_loop = None  # rpc.EventLoopThread
        self._actor_aio_limit = 1000
        # Serial (max_concurrency=1, non-async) actors execute on a
        # dedicated thread with batched dequeue + batched reply delivery,
        # same as normal tasks.
        self._actor_serial_queue: Optional[queue_mod.SimpleQueue] = None
        # Receiver-side ordering state is PER CALLER: every submitting
        # worker numbers its own stream from 0 (reference: per-caller
        # sequence_number in direct_actor_transport.h) — a global
        # counter would deadlock the second caller of a shared actor.
        self._actor_expected_seqno: Dict[bytes, int] = {}
        self._actor_reorder: Dict[
            bytes, Dict[int, Tuple[dict, List[bytes],
                                   asyncio.Future]]] = {}
        self._actor_exec_queue: Optional[asyncio.Queue] = None
        self._actor_consumer: Optional[asyncio.Task] = None
        core._server.handlers.update({
            "PushTasks": self.handle_push_tasks,
            "StealTasks": self.handle_steal_tasks,
            "CreateActor": self.handle_create_actor,
            "PushActorTasks": self.handle_push_actor_tasks,
            "CancelTask": self.handle_cancel_task,
            "DumpStack": self.handle_dump_stack,
            "Exit": self.handle_exit,
        })
        self._cancelled: set[bytes] = set()

    # ------------------------------------------------------------ normal tasks

    def _batch_reply_aggregator(self, loop, tws: List[list]):
        """Future-based batch aggregation for the SERIAL ACTOR path
        (its reorder buffer keys completion off per-task futures).
        Normal tasks use the cheaper ``_BatchState`` instead."""
        batch_fut = loop.create_future()
        n = len(tws)
        slots: List[Optional[tuple]] = [None] * n
        remaining = [n]

        def make_cb(i: int, tw: list):
            def _cb(f: asyncio.Future):
                if f.cancelled() or f.exception() is not None:
                    e = RuntimeError("cancelled") if f.cancelled() \
                        else f.exception()
                    slots[i] = self._infra_error_reply(tw, e)
                else:
                    slots[i] = f.result()
                remaining[0] -= 1
                if remaining[0] == 0 and not batch_fut.done():
                    rheaders = []
                    rframes: List[bytes] = []
                    for rh, rfr in slots:
                        rheaders.append([rh, len(rframes), len(rfr)])
                        rframes.extend(rfr)
                    batch_fut.set_result(({"replies": rheaders}, rframes))
            return _cb

        futs = []
        for i, tw in enumerate(tws):
            fut = loop.create_future()
            fut.add_done_callback(make_cb(i, tw))
            futs.append(fut)
        return batch_fut, futs

    def handle_push_tasks(self, conn, header, bufs):
        """Sync RPC fast path (rpc_sync): queue the batch for the execution
        thread and return the batch future the RPC layer replies from.
        The batch carries each distinct static spec tail once
        (TaskSpec.tail_wire); per-task entries are [proto_idx, task_id,
        args_wire, frame_start, num_frames, trace_ctx]."""
        loop = asyncio.get_running_loop()
        tasks = header["tasks"]
        protos = [TaskSpec.from_tail_wire(t) for t in header["protos"]]
        batch = _BatchState(loop, len(tasks))
        put = self._exec_queue.put
        for i, t in enumerate(tasks):
            if len(t) == 2:
                # compact row [pidx, task_id]: argless, traceless (the
                # dominant microbenchmark shape — 4 fields fewer to
                # pack/send/parse per task)
                put((protos[t[0]], t[1], (), (), None, batch, i))
                continue
            pidx, task_id, args_wire, fstart, nframes, trace_ctx = t
            put((protos[pidx], task_id, args_wire,
                 bufs[fstart:fstart + nframes], trace_ctx, batch, i))
        return batch.fut

    handle_push_tasks.rpc_sync = True

    async def handle_steal_tasks(self, conn, header, bufs):
        """Relinquish up to max_n queued-but-unstarted tasks (reference:
        direct_task_transport.h:57 StealTasks). The stolen specs ride
        back in THIS reply (the owner requeues them immediately); their
        slots in the original PushTasks batch reply resolve to a
        ``stolen`` marker the owner skips."""
        items = self._exec_queue.steal(int(header.get("max_n", 0)))
        tails: List[list] = []
        tail_idx: dict = {}
        theaders: List[list] = []
        frames: List[bytes] = []
        for proto, task_id, args_wire, tbufs, trace_ctx, batch, i in items:
            if task_id in self._cancelled:
                # an acknowledged cancel must not be undone by moving
                # the task to a thief that never saw the CancelTask
                self._cancelled.discard(task_id)
                batch.complete(i, self._error_reply(
                    proto.clone_for(task_id, []),
                    exc.TaskCancelledError(proto.name)))
                continue
            pidx = tail_idx.get(id(proto))
            if pidx is None:
                pidx = tail_idx[id(proto)] = len(tails)
                tails.append(proto.tail_wire())
            theaders.append([pidx, task_id, args_wire, len(frames),
                             len(tbufs), trace_ctx])
            frames.extend(tbufs)
            batch.complete(i, ([REPLY_STOLEN, ()], []))
        return {"protos": tails, "tasks": theaders}, frames

    def _exec_loop(self):
        self._serial_exec_loop(self._exec_queue, self._run_one_task,
                               batched=True)

    def _run_one_task(self, spec: TaskSpec):
        if spec.task_id in self._cancelled:
            self._cancelled.discard(spec.task_id)
            return self._error_reply(spec, exc.TaskCancelledError(spec.name))
        return self._execute_task_sync(spec)

    def _serial_exec_loop(self, q, run_one, batched: bool = False):
        """Dedicated execution thread: run tasks serially via
        ``run_one(spec)``, ONE dequeue at a time (whatever is still
        queued stays stealable).

        ``batched=True`` (normal tasks): items are (tw, bufs, batch, i)
        and completion goes through ``_BatchState`` — the batch itself
        coalesces the loop wakeup, no per-task future exists.
        ``batched=False`` (serial actors): items are (tw, bufs, fut);
        accumulated replies are flushed with one loop wakeup whenever
        the queue momentarily drains, and BEFORE any blocking dequeue
        (a steal can empty the queue between empty() and get())."""
        self._maybe_profile_thread()
        if batched:
            self._batched_exec_loop(q, run_one)  # never returns
        results = []
        while True:
            try:
                header, bufs, fut = q.get_nowait()
            except queue_mod.Empty:
                if results:
                    self.core.loop.call_soon_threadsafe(
                        self._deliver_replies, results)
                    results = []
                header, bufs, fut = q.get()
            try:
                reply = run_one(TaskSpec.from_wire(header, bufs))
            except BaseException as e:  # noqa: BLE001 — keep thread alive
                logger.exception("task execution loop error")
                reply = self._infra_error_reply(header, e)
            results.append((fut, reply))
            if q.empty():
                self.core.loop.call_soon_threadsafe(
                    self._deliver_replies, results)
                results = []

    def _batched_exec_loop(self, q, run_one):
        checkpoint = self._profile_checkpoint
        args_from_wire = TaskSpec._args_from_wire
        n_done = 0
        while True:
            proto, task_id, args_wire, bufs, trace_ctx, batch, i = q.get()
            try:
                spec = proto.clone_for(
                    task_id,
                    args_from_wire(args_wire, bufs) if args_wire else (),
                    trace_ctx=tuple(trace_ctx) if trace_ctx else None)
                reply = run_one(spec)
            except BaseException as e:  # noqa: BLE001 — keep thread alive
                logger.exception("task execution loop error")
                reply = self._infra_error_reply_for(
                    task_id, proto.num_returns, e)
            batch.complete(i, reply)
            if checkpoint is not None:
                n_done += 1
                if n_done % 20000 == 0:
                    checkpoint()

    _profiling_claimed = False
    _profile_checkpoint = None

    def _maybe_profile_thread(self):
        """RAY_TPU_WORKER_PROFILE=/dir: dump this thread's cProfile at
        exit. Only ONE exec thread per process profiles (a second
        enable doesn't reliably raise on 3.12, and two dumps to the
        same path would overwrite each other)."""
        profile_dir = os.environ.get("RAY_TPU_WORKER_PROFILE", "")
        if not profile_dir or TaskExecutor._profiling_claimed:
            return
        TaskExecutor._profiling_claimed = True
        import atexit
        import cProfile

        prof = cProfile.Profile()
        try:
            prof.enable()
        except ValueError:
            return

        path = os.path.join(profile_dir, f"worker-{os.getpid()}-exec.prof")
        os.makedirs(profile_dir, exist_ok=True)

        def _dump():
            prof.disable()
            prof.dump_stats(path)
        atexit.register(_dump)
        # The raylet's hard teardown can SIGKILL the worker before
        # atexit runs — the exec loop checkpoints via this hook so a
        # profile always lands (dump_stats disables; re-enable after).
        def _checkpoint():
            prof.dump_stats(path)
            prof.enable()
        self._profile_checkpoint = _checkpoint

    def _infra_error_reply(self, tw: list, e: BaseException):
        """Error reply built from the raw wire header (the spec may not even
        deserialize): every declared return gets an error object so the
        caller's get() raises instead of hanging."""
        raw_task_id = tw[TaskSpec.WIRE_TASK_ID] if len(tw) > 0 else b"\0" * 24
        num_returns = tw[TaskSpec.WIRE_NUM_RETURNS] \
            if len(tw) > TaskSpec.WIRE_NUM_RETURNS else 1
        return self._infra_error_reply_for(raw_task_id, num_returns, e)

    def _infra_error_reply_for(self, task_id: bytes, num_returns: int,
                               e: BaseException):
        serialized = self.core.serialization_context.serialize_error(
            exc.RaySystemError(f"task execution failed in the worker: {e!r}"))
        meta, frames = serialized.wire_frames()
        returns = []
        frames_out: List[bytes] = []
        for i in range(max(num_returns, 1)):
            start = len(frames_out)
            frames_out.extend(frames)
            returns.append([return_object_id_bytes(task_id, i + 1), 0,
                            meta, start, len(frames), ()])
        return [REPLY_ERROR, returns], frames_out

    @staticmethod
    def _deliver_replies(results):
        for fut, reply in results:
            if not fut.done():
                fut.set_result(reply)

    def _execute_task_sync(self, spec: TaskSpec):
        core = self.core
        _task_ctx.task_id = spec.task_id
        core._current_task_id = spec.task_id
        if not core.job_id and spec.job_id:
            # adopt the submitting job: nested task/actor creation from
            # this worker needs a job id for ID derivation (and the
            # job-level runtime env for nested submissions)
            core.job_id = spec.job_id
            core.adopt_job_runtime_env(spec.job_id)
        ev = core.task_events
        if ev.enabled:
            ev.record(spec.task_id, RUNNING,
                      {"name": spec.name, "worker": self._wid12})
        try:
            if faultpoints.armed:
                # worker-death fault seam (armed via RAY_TPU_FAULTPOINTS
                # in the spawning test's env): ``kill`` here IS the
                # deterministic "worker dies at its Nth task"; ``raise``
                # is an injected application error (retry_exceptions
                # path). Fired after RUNNING so the task-event history
                # shows the death honestly.
                faultpoints.fire("task.execute", name=spec.name,
                                 task_id=spec.task_id.hex())
            fn = core.function_manager.fetch(spec.fn_key)
            args, kwargs = self._resolve_args(spec) if spec.args \
                else ((), {})
            profile = core.config.profiling_enabled
            t0 = _now() if profile else 0.0
            if spec.runtime_env or spec.trace_ctx:
                env_cm = runtime_env_mod.activate(
                    spec.runtime_env, core.session_dir,
                    core._kv_get_sync) if spec.runtime_env else _NULL_SPAN
                with env_cm, _exec_span(spec):
                    result = fn(*args, **kwargs)
            else:
                # hot path: no env to realize, no span — skip the two
                # context-manager enter/exit pairs entirely
                result = fn(*args, **kwargs)
            if profile:
                core.add_exec_event(spec.name, spec.task_id, t0, _now())
            reply = self._build_reply(spec, result)
            if ev.enabled:
                ev.record(spec.task_id, FINISHED)
            return reply
        except Exception as e:  # noqa: BLE001
            logger.info("task %s failed:\n%s", spec.name, traceback.format_exc())
            if ev.enabled:
                ev.record(spec.task_id, FAILED,
                          {"reason": type(e).__name__,
                           "message": str(e)[:200]})
            return self._error_reply(spec, format_task_error(spec.name, e))
        finally:
            _task_ctx.task_id = b""
            core._current_task_id = b""

    def _resolve_args(self, spec: TaskSpec) -> Tuple[list, dict]:
        args: List[Any] = []
        for a in spec.args:
            if a.kind == ARG_VALUE:
                obj = SerializedObject(a.metadata, a.frames)
                args.append(self.core.serialization_context.deserialize(
                    obj.metadata, obj.frames))
            else:
                ref = ObjectRef(ObjectID(a.object_id),
                                owner_address=a.owner_address,
                                worker=self.core, skip_adding_local_ref=True)
                value = self.core._run(self.core._get_one(ref, 600.0))
                args.append(value)
        # kwargs travel as a trailing marker dict (see remote_function).
        kwargs = {}
        if args and isinstance(args[-1], dict) and args[-1].get("__rtpu_kwargs__"):
            kwargs = args.pop()["kwargs"]
        return args, kwargs

    def _build_reply(self, spec: TaskSpec, result: Any):
        if spec.num_returns == 0:
            return [REPLY_OK, ()], []
        if spec.num_returns == 1:
            if type(result) is bytes and \
                    len(result) <= self.core.config.max_direct_call_object_size:
                # Raw-bytes return: no serializer object at all.
                if len(result) <= INLINE_RETURN_MAX:
                    # Fastest path: a COMPACT 2-element return row
                    # [meta, frames] riding INSIDE the msgpack reply
                    # header — the owner derives the return oid from
                    # the task id (single return, index 1), so 28B of
                    # oid plus the out-of-band frame machinery never
                    # cross the wire.
                    return [REPLY_OK, [[META_RAW, [result]]]], []
                # Too big to inline in the header: out-of-band frame.
                return [REPLY_OK, [
                    [return_object_id_bytes(spec.task_id, 1), 0, META_RAW,
                     0, 1, ()],
                ]], [result]
            # Hot path: one return value, usually small enough to inline.
            serialized = self.core.serialization_context.serialize(result)
            if serialized.total_bytes() <= \
                    self.core.config.max_direct_call_object_size:
                # SNAPSHOT, not live views: the reply flush is deferred
                # (write coalescing / backpressure) and the next actor
                # method may mutate the returned buffers in place —
                # live frames would send torn data. Inline returns are
                # <= max_direct_call_object_size, so the copy is cheap;
                # the LARGE (plasma) path below stays zero-copy.
                meta, frames = serialized.to_wire()
                contained = [r.binary() for r in serialized.contained_refs]
                if serialized.total_bytes() <= INLINE_RETURN_MAX:
                    if not contained:
                        # compact row (oid derived owner-side)
                        return [REPLY_OK, [[meta, frames]]], []
                    return [REPLY_OK, [
                        [return_object_id_bytes(spec.task_id, 1), 0, meta,
                         0, 0, contained, frames],
                    ]], []
                return [REPLY_OK, [
                    [return_object_id_bytes(spec.task_id, 1), 0, meta, 0,
                     len(frames), contained],
                ]], frames
            results = [result]
        else:
            results = list(result) if result is not None else []
            if len(results) != spec.num_returns:
                return self._error_reply(spec, format_task_error(
                    spec.name, ValueError(
                        f"task declared {spec.num_returns} returns but "
                        f"produced {len(results)}")))
        returns = []
        frames_out: List[bytes] = []
        for i, value in enumerate(results):
            oid_b = return_object_id_bytes(spec.task_id, i + 1)
            serialized = self.core.serialization_context.serialize(value)
            contained = [r.binary() for r in serialized.contained_refs]
            if serialized.total_bytes() <= \
                    self.core.config.max_direct_call_object_size:
                # snapshot: see the single-return inline comment above
                meta, frames = serialized.to_wire()
                start = len(frames_out)
                frames_out.extend(frames)
                returns.append([oid_b, 0, meta, start, len(frames), contained])
            else:
                segment, size = self.core.write_segment_sync(serialized)
                # owner_address = the task's CALLER (the return's
                # owner), not this executing worker — the raylet's
                # leak detector probes the owner's live references
                reply, _ = self.core._run(self.core.raylet_conn.call(
                    "SealObject", protocol.SealObjectRequest(
                        object_id=oid_b, segment=segment, size=size,
                        pin=True,
                        owner_address=spec.owner_address).to_header()))
                if not reply.get("ok"):
                    return self._error_reply(spec, exc.ObjectStoreFullError(
                        f"return {i} of {spec.name} ({size}B) doesn't fit"))
                returns.append([oid_b, 1, reply["node_id"], size, 0, contained])
        return [REPLY_OK, returns], frames_out

    def _error_reply(self, spec: TaskSpec, error: BaseException):
        serialized = self.core.serialization_context.serialize_error(error)
        returns = []
        frames_out: List[bytes] = []
        meta, frames = serialized.wire_frames()
        for i in range(max(spec.num_returns, 1)):
            start = len(frames_out)
            frames_out.extend(frames)
            returns.append([return_object_id_bytes(spec.task_id, i + 1), 0,
                            meta, start, len(frames), ()])
        return [REPLY_ERROR, returns], frames_out

    async def handle_cancel_task(self, conn, header, bufs):
        self._cancelled.add(header["task_id"])
        return {"ok": True}

    async def handle_dump_stack(self, conn, header, bufs):
        """All-thread stack dump for ``ray_tpu stack`` (reference:
        scripts.py:1393 `ray stack` py-spy attach — here the worker
        self-reports over its RPC channel, no ptrace needed)."""
        import sys as _sys

        frames = _sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        parts = []
        for ident, frame in frames.items():
            parts.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
            parts.append("".join(traceback.format_stack(frame)))
        return {"pid": os.getpid(),
                "actor_id": self._actor_id,
                "stacks": "\n".join(parts)}

    async def handle_exit(self, conn, header, bufs):
        loop = asyncio.get_running_loop()
        loop.call_later(0.05, loop.stop)
        return {"ok": True}

    # ------------------------------------------------------------- actors

    async def handle_create_actor(self, conn, header, bufs):
        spec = TaskSpec.from_wire_dict(header["spec"], bufs)
        creation = spec.actor_creation or {}
        try:
            loop = asyncio.get_running_loop()
            instance = await loop.run_in_executor(
                self._task_pool, self._construct_actor, spec)
        except Exception as e:  # noqa: BLE001
            logger.info("actor %s constructor failed:\n%s", spec.name,
                        traceback.format_exc())
            return {"ok": False,
                    "error": f"{type(e).__name__}: {e}\n{traceback.format_exc()}"}
        self._actor_instance = instance
        self._actor_id = header["actor_id"]
        self._actor_incarnation = header.get("incarnation", 0)
        self._actor_is_asyncio = creation.get("is_asyncio", False)
        max_concurrency = creation.get("max_concurrency", 1)
        if self._actor_is_asyncio:
            from ray_tpu._private import rpc
            # actor.py already defaults async actors to 1000 when the
            # user didn't pass max_concurrency; an explicit 1 here
            # means the user wants serialized execution — honor it.
            self._actor_aio_limit = max(1, max_concurrency)
            self._actor_user_loop = rpc.EventLoopThread("rtpu-actor-aio")
        elif max_concurrency == 1:
            self._actor_serial_queue = queue_mod.SimpleQueue()
            threading.Thread(target=self._actor_serial_loop,
                             name="rtpu-actor-exec", daemon=True).start()
        else:
            self._actor_pool = ThreadPoolExecutor(
                max_workers=max_concurrency,
                thread_name_prefix="rtpu-actor")
        if self._actor_serial_queue is None:
            self._actor_exec_queue = asyncio.Queue()
            self._actor_consumer = asyncio.get_running_loop().create_task(
                self._actor_consume_loop())
        return {"ok": True}

    def _construct_actor(self, spec: TaskSpec):
        _task_ctx.task_id = spec.task_id
        self.core._current_task_id = spec.task_id
        if not self.core.job_id and spec.job_id:
            self.core.job_id = spec.job_id  # see _execute_task_sync
            self.core.adopt_job_runtime_env(spec.job_id)
        try:
            # Actor runtime envs persist for the actor's lifetime —
            # this worker process is dedicated to the actor
            # (reference: runtime envs realized at worker setup,
            # workers/setup_worker.py).
            runtime_env_mod.activate_persistent(
                spec.runtime_env, self.core.session_dir,
                self.core._kv_get_sync)
            cls = self.core.function_manager.fetch(spec.fn_key)
            args, kwargs = self._resolve_args(spec)
            return cls(*args, **kwargs)
        finally:
            _task_ctx.task_id = b""
            self.core._current_task_id = b""

    def handle_push_actor_tasks(self, conn, header, bufs):
        """Receiver-side ordering: execute strictly in client seqno order,
        buffering out-of-order arrivals (reference: ActorSchedulingQueue).
        Sync RPC fast path.

        Reply discipline depends on the actor's concurrency model.
        Serial actors (max_concurrency=1, non-async) complete in push
        order, so the whole batch shares ONE aggregated reply message
        (cheapest wire path — this is the microbenchmark hot loop).
        Concurrent actors (asyncio / thread pool) complete in ANY order
        and a long-running call (e.g. a 30s long-poll listen) must not
        hold the reply of a fast call pushed in the same batch — each
        task's result streams back as its own ActorTaskResult push the
        moment it lands (reference: per-call replies in
        direct_actor_transport.h)."""
        loop = asyncio.get_running_loop()
        pushed = header.get("incarnation", -1)
        if pushed != -1 and self._actor_incarnation != -1 and \
                pushed != self._actor_incarnation:
            # Stale-incarnation push (the pusher thinks it is talking to
            # a different restart generation). Executing it would run
            # tasks on a superseded actor — drop the connection instead:
            # the pusher's on_disconnect handler requeues its inflight
            # entries and re-resolves the live address via the GCS.
            logger.warning(
                "rejecting PushActorTasks for incarnation %d "
                "(this worker serves %d); severing connection",
                pushed, self._actor_incarnation)
            conn._mark_closed()
            return {"ok": False, "reason": "stale incarnation"}
        tasks = header["tasks"]
        serial = not self._actor_is_asyncio and self._actor_pool is None
        if serial:
            batch_fut, futs = self._batch_reply_aggregator(
                loop, [t[0] for t in tasks])
        else:
            batch_fut = {"streamed": True}
            futs = []
            for (tw, seqno, _f, _n) in tasks:
                fut = loop.create_future()
                fut.add_done_callback(
                    self._make_stream_reply_cb(conn, seqno, tw))
                futs.append(fut)
        callers = set()
        for (tw, seqno, fstart, nframes), fut in zip(tasks, futs):
            caller = tw[TaskSpec.WIRE_OWNER_WORKER_ID]
            self._actor_reorder.setdefault(caller, {})[seqno] = (
                tw, bufs[fstart:fstart + nframes], fut)
            callers.add(caller)
        for caller in callers:
            self._drain_reorder_buffer(caller)
        return batch_fut

    handle_push_actor_tasks.rpc_sync = True

    def _make_stream_reply_cb(self, conn, seqno: int, tw: list):
        def _cb(f: asyncio.Future):
            if f.cancelled() or f.exception() is not None:
                e = RuntimeError("cancelled") if f.cancelled() \
                    else f.exception()
                rheader, rframes = self._infra_error_reply(tw, e)
            else:
                rheader, rframes = f.result()
            try:
                conn.push_nowait("ActorTaskResult",
                                 {"seqno": seqno, "reply": rheader},
                                 bufs=rframes)
            except (ConnectionError, OSError):
                pass  # owner gone; its conn-loss path handles retries
        return _cb

    def _drain_reorder_buffer(self, caller: bytes):
        reorder = self._actor_reorder.get(caller, {})
        expected = self._actor_expected_seqno.setdefault(caller, 0)
        while expected in reorder:
            item = reorder.pop(expected)
            expected += 1
            if self._actor_serial_queue is not None:
                self._actor_serial_queue.put(item)
            else:
                self._actor_exec_queue.put_nowait(item)
        self._actor_expected_seqno[caller] = expected

    def _actor_serial_loop(self):
        """Serial-actor execution thread (max_concurrency=1, non-async):
        uses the FUTURE-based path (batched=False) — the reorder buffer
        keys completion off per-task futures, unlike normal tasks'
        _BatchState slot aggregation."""
        self._serial_exec_loop(self._actor_serial_queue,
                               self._execute_actor_task_sync)

    async def _actor_consume_loop(self):
        while True:
            header, bufs, fut = await self._actor_exec_queue.get()
            try:
                spec = TaskSpec.from_wire(header, bufs)
                if self._actor_is_asyncio:
                    # Admission control HERE (async acquire on the IO
                    # loop): intake pauses at the concurrency cap, so a
                    # flood of pushes can't pile unbounded coroutines
                    # onto the user loop. Release comes back via
                    # call_soon_threadsafe when the task finishes.
                    # run_coroutine_threadsafe preserves submit order,
                    # so in-order task STARTS are kept.
                    if self._actor_sema is None:
                        self._actor_sema = asyncio.Semaphore(
                            self._actor_aio_limit)
                    await self._actor_sema.acquire()
                    try:
                        asyncio.run_coroutine_threadsafe(
                            self._run_async_actor_task(
                                spec, fut, asyncio.get_running_loop()),
                            self._actor_user_loop.loop)
                    except BaseException:  # handoff failed: free slot
                        self._actor_sema.release()
                        raise
                else:
                    loop = asyncio.get_running_loop()

                    def _runner(spec=spec, fut=fut):
                        # Bind spec/fut as defaults: the enclosing loop
                        # rebinds them before the pool thread runs.
                        try:
                            res = self._execute_actor_task_sync(spec)
                        except BaseException as e:  # noqa: BLE001
                            logger.exception("actor task runner crashed")
                            res = self._error_reply(spec, exc.RaySystemError(
                                f"actor task runner crashed: {e!r}"))

                        def _set():
                            if not fut.done():
                                fut.set_result(res)

                        loop.call_soon_threadsafe(_set)

                    self._actor_pool.submit(_runner)
            except BaseException as e:  # noqa: BLE001
                logger.exception("actor consume loop error")
                if not fut.done():
                    fut.set_exception(e)

    def _execute_actor_task_sync(self, spec: TaskSpec):
        _task_ctx.task_id = spec.task_id
        if not self.core.job_id and spec.job_id:
            self.core.job_id = spec.job_id  # see _execute_task_sync
            self.core.adopt_job_runtime_env(spec.job_id)
        ev = self.core.task_events
        if ev.enabled:
            ev.record(spec.task_id, RUNNING,
                      {"name": spec.name, "worker": self._wid12})
        try:
            method = self._lookup_method(spec.name)
            args, kwargs = self._resolve_args(spec)
            with _exec_span(spec):
                result = method(*args, **kwargs)
            reply = self._build_reply(spec, result)
            if ev.enabled:
                ev.record(spec.task_id, FINISHED)
            return reply
        except _ActorExitSignal:
            self._request_exit("actor exited via exit_actor()")
            return self._build_reply(spec, None)
        except Exception as e:  # noqa: BLE001
            if ev.enabled:
                ev.record(spec.task_id, FAILED,
                          {"reason": type(e).__name__,
                           "message": str(e)[:200]})
            return self._error_reply(spec, format_task_error(spec.name, e))
        finally:
            _task_ctx.task_id = b""

    async def _run_async_actor_task(self, spec: TaskSpec,
                                    fut: asyncio.Future, io_loop):
        """Runs ON THE ACTOR USER LOOP; ``fut`` and the admission
        semaphore belong to ``io_loop``."""
        reply = None
        ev = self.core.task_events
        if ev.enabled:
            ev.record(spec.task_id, RUNNING,
                      {"name": spec.name, "worker": self._wid12})
        try:
            method = self._lookup_method(spec.name)
            if not spec.args:
                # argless calls (the dominant actor-RPC shape) resolve
                # trivially — the executor hop exists for plasma gets
                # inside _resolve_args, which can't happen here
                args, kwargs = self._resolve_args(spec)
            else:
                args, kwargs = await asyncio.get_running_loop() \
                    .run_in_executor(None,
                                     lambda: self._resolve_args(spec))
            result = method(*args, **kwargs)
            if inspect.isawaitable(result):
                result = await result
            # _build_reply may seal large returns via the sync raylet
            # RPC path (core._run), which must not block the actor
            # user loop — but small scalars serialize far below the
            # seal threshold and build with no RPC at all, so the
            # common reply skips the thread hop. Guards are
            # conservative in serialized bytes: ints are unbounded
            # bignums (bit_length-capped) and utf-8 is up to 4B/char.
            if result is None or isinstance(result, (bool, float)) or \
                    (isinstance(result, int)
                     and result.bit_length() < 512) or \
                    (isinstance(result, (str, bytes))
                     and len(result) * 4 < INLINE_RETURN_MAX):
                reply = self._build_reply(spec, result)
            else:
                reply = await asyncio.get_running_loop().run_in_executor(
                    None, self._build_reply, spec, result)
            if ev.enabled:
                ev.record(spec.task_id, FINISHED)
        except _ActorExitSignal:
            self._request_exit("actor exited via exit_actor()")
            reply = self._build_reply(spec, None)
        except Exception as e:  # noqa: BLE001
            if ev.enabled:
                ev.record(spec.task_id, FAILED,
                          {"reason": type(e).__name__,
                           "message": str(e)[:200]})
            reply = self._error_reply(spec, format_task_error(spec.name, e))
        finally:
            # BaseException paths too (CancelledError from a user-loop
            # shutdown): the admission slot and the caller's future MUST
            # be released either way, or the actor wedges at the cap.
            if reply is None:
                reply = self._error_reply(spec, exc.RaySystemError(
                    f"actor task {spec.name} cancelled"))

            def _set(reply=reply):
                self._actor_sema.release()
                if not fut.done():
                    fut.set_result(reply)

            try:
                io_loop.call_soon_threadsafe(_set)
            except RuntimeError:  # io loop closed: shutting down
                pass

    def _lookup_method(self, name: str):
        method_name = name.rsplit(".", 1)[-1]
        method = getattr(self._actor_instance, method_name, None)
        if method is None:
            raise AttributeError(
                f"actor {type(self._actor_instance).__name__} has no method "
                f"{method_name!r}")
        return method

    def _request_exit(self, reason: str):
        async def _notify():
            try:
                await self.core.raylet_conn.call("ActorExited", {
                    "actor_id": self._actor_id, "reason": reason})
            except ConnectionError:
                pass
            asyncio.get_event_loop().stop()
        asyncio.run_coroutine_threadsafe(_notify(), self.core.loop)


class _ActorExitSignal(BaseException):
    pass


def exit_actor():
    """Public helper: gracefully terminate the current actor after the
    in-flight call completes (reference: ray.actor.exit_actor)."""
    raise _ActorExitSignal()


# Bound once: _now ran twice per executed task and the in-function
# import cost a sys.modules probe per call on the exec hot path.
from time import time as _now  # noqa: E402
